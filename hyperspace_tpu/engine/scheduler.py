"""The serving plane: process-wide query scheduler + cancellation +
degradation circuit breaker.

ROADMAP's north star is "heavy traffic from millions of users"; until
this module, any number of threads could call `DataFrame.collect`
simultaneously with nothing budgeting device memory, no way to stop a
running query, and a persistently broken index re-paying the expensive
degraded fallback on every single query. Every `collect` now routes
through ONE `QueryScheduler` (`get_scheduler()`), which gives the
execution plane the same treatment PR 4 gave storage — typed failure
modes, counters behind every one of them, and fault seams a chaos test
can reach:

- **admission control**: each query's projected HBM footprint
  (`plan/footprint.py` — scan file sizes x a decode-expansion factor,
  conservative default when unknowable) is admitted against
  `spark.hyperspace.serve.hbm.budget.bytes`, derived against the
  `DeviceMemoryAccountant` live gauges (device pressure beyond the
  scheduler's own bookkeeping — resident caches, other tenants —
  shrinks the headroom). Over-budget queries wait in a bounded FIFO
  (`serve.queue.depth`); a query arriving at a full queue gets a typed
  `QueryRejectedError` IMMEDIATELY — backpressure to the caller, not a
  silent pile-up of blocked threads. Budget 0 (default) disables
  budgeting but keeps the bookkeeping (gauges, query registry, cancel).

- **deadlines & cooperative cancellation**: each query carries a
  `Deadline` (per-call `collect(timeout=...)`, else
  `serve.deadline.seconds`) in the same contextvar scope as its
  `QueryMetrics` (`telemetry.deadline_scope`, carried across pool
  threads by `telemetry.propagating`). `telemetry.check_deadline(phase)`
  checkpoints at every layer's iteration boundaries — operator starts
  (`engine/physical.py`), fusion stage entry (`engine/fusion.py`),
  transfer-engine chunk loops (`io/transfer.py`), sorted-run writes
  (`io/builder.py`) — raise `QueryDeadlineExceededError` /
  `QueryCancelledError` tagged with the interrupted phase;
  `session.cancel(query_id)` flips the same flag. Cancellation is
  COOPERATIVE: in-flight device work runs to its next checkpoint, so
  buffers unwind through the normal release paths (the leak-sentinel
  tests in `tests/test_serving.py` pin this).

- **inter-query batched execution**: after optimization (and the
  footprint credits), eligible point/filter plans route through the
  batching lane (`engine/batcher.py`): K concurrent queries sharing an
  execution signature coalesce into ONE jitted stacked-predicate
  invocation over the shared scan, with per-query slicing, deadlines,
  metrics, and the fallback contract preserved. `None` from the lane —
  ineligible shape, nothing to coalesce with, or a batch-lane
  fallback — lands on the per-query resilient path below unchanged.

- **degradation circuit breaker**: the PR-4 `IndexDataUnavailableError`
  fallback is wrapped in a per-index breaker (closed -> open after N
  failures in a window -> half-open probe; `serve.breaker.*` knobs).
  While open, a query selecting the bad index skips STRAIGHT to the
  source plan — no failed index scan to re-pay — with
  `resilience.breaker.*` counters and flight-recorder events marking
  every transition.

Fault seams for the chaos harness (`tests/chaos.py`):
`scheduler.admit` fires at admission entry, `scheduler.run` just
before plan optimization; `fusion.stage` and `transfer.put` cover the
execution layers below.

Typed serving errors and their counters are a CLOSED set
(`SERVING_ERROR_COUNTERS`): `scripts/check_metrics_coverage.py` fails
any `QueryServingError` subclass missing from the table, so a new
failure mode cannot ship without its scrape-able series.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from hyperspace_tpu import telemetry
from hyperspace_tpu.exceptions import (HyperspaceException,
                                       IndexDataUnavailableError,
                                       QueryCancelledError,
                                       QueryDeadlineExceededError,
                                       QueryRejectedError,
                                       QueryServingError)

__all__ = ["Deadline", "QueryScheduler", "BreakerBoard", "SloTracker",
           "get_scheduler", "set_scheduler", "reset_scheduler",
           "SERVING_ERROR_COUNTERS", "SLO_SHED_BURN_THRESHOLD"]

logger = logging.getLogger(__name__)

# Typed serving error -> the registry counter bumped when one is
# raised. The metrics-coverage lint cross-checks this table against the
# live QueryServingError subclass tree: every subclass must appear
# here, and its entry must equal the class's own `counter` attribute.
SERVING_ERROR_COUNTERS = {
    "QueryRejectedError": "serve.rejected",
    "QueryCancelledError": "serve.cancelled",
    "QueryDeadlineExceededError": "serve.deadline_exceeded",
}

# Queue-wait poll quantum: waiters re-check admission at least this
# often even without a notify (cheap safety against a lost wakeup
# under chaos; the cv IS notified on every release).
_WAIT_QUANTUM_S = 0.05


class Deadline:
    """Per-query cancellation token + optional wall-clock deadline.

    `check(phase)` is the ONE cooperative checkpoint primitive: raises
    the typed error tagged with the phase it would interrupt. The
    cancelled flag is a plain bool (GIL-atomic store; checkpoints pay
    an attribute read, not a lock). A Deadline with no timeout still
    supports `cancel()` — every query gets one."""

    __slots__ = ("query_id", "timeout_s", "_expires_t", "_cancelled")

    def __init__(self, query_id: Optional[str] = None,
                 timeout_s: Optional[float] = None):
        self.query_id = query_id
        self.timeout_s = timeout_s if timeout_s and timeout_s > 0 \
            else None
        self._expires_t = (time.monotonic() + self.timeout_s
                           if self.timeout_s is not None else None)
        self._cancelled = False

    def cancel(self) -> None:
        self._cancelled = True

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    def expired(self) -> bool:
        return self._expires_t is not None \
            and time.monotonic() >= self._expires_t

    def remaining(self) -> Optional[float]:
        """Seconds left (None = no time limit; 0.0 = expired)."""
        if self._expires_t is None:
            return None
        return max(0.0, self._expires_t - time.monotonic())

    def check(self, phase: str = "unknown") -> None:
        if self._cancelled:
            raise QueryCancelledError(
                f"query {self.query_id or '?'} cancelled (during "
                f"{phase})", query_id=self.query_id, phase=phase)
        if self.expired():
            raise QueryDeadlineExceededError(
                f"query {self.query_id or '?'} exceeded its "
                f"{self.timeout_s:.3f}s deadline (during {phase})",
                query_id=self.query_id, phase=phase)


# ---------------------------------------------------------------------------
# Sliding-window SLO tracking
# ---------------------------------------------------------------------------

# A p99 objective allows 1% of queries over the target; the burn rate
# is the observed violation fraction over that allowance (1.0 = burning
# the error budget exactly as fast as allowed).
_SLO_ALLOWED_FRACTION = 0.01
# Shedding engages while the burn rate exceeds this (the error budget
# is being consumed faster than the objective allows).
SLO_SHED_BURN_THRESHOLD = 1.0


class SloTracker:
    """Sliding window of completed-query walls vs the SLO target.

    The window is the scheduler's OWN deque of (monotonic t, violated)
    events rather than a view over the timeseries sampler: burn-rate
    decisions (shedding!) must be exact and available whether or not
    the background sampler is running; the sampler's `window.*` gauges
    are the derived, scrapeable view of the same story.

    `prefix` names the published series family: the global tracker
    publishes `serve.slo.*`; per-tenant trackers publish
    `serve.tenant.<id>.slo.*` — same window math, same knobs."""

    def __init__(self, prefix: str = "serve.slo"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._events: deque = deque()  # (monotonic t, violated: bool)
        self._violations_in_window = 0

    def _prune(self, now: float, window: float) -> None:
        # Caller holds the lock.
        while self._events and self._events[0][0] < now - window:
            _t, violated = self._events.popleft()
            if violated:
                self._violations_in_window -= 1

    def record(self, wall_s: float, conf) -> None:
        """Fold one completed query into the window (no-op when SLO
        tracking is off). Publishes `serve.slo.{violations,burn_rate}`."""
        target = conf.serve_slo_p99_seconds if conf is not None else 0.0
        if target <= 0 or wall_s is None:
            return
        window = max(conf.serve_slo_window_seconds, 1e-3)
        violated = wall_s > target
        now = time.monotonic()
        with self._lock:
            self._events.append((now, violated))
            if violated:
                self._violations_in_window += 1
            self._prune(now, window)
            total = len(self._events)
            violations = self._violations_in_window
        reg = telemetry.get_registry()
        if violated:
            reg.counter(f"{self.prefix}.violations").inc()
        burn = ((violations / total) / _SLO_ALLOWED_FRACTION
                if total else 0.0)
        reg.gauge(f"{self.prefix}.burn_rate").set(burn)
        reg.gauge(f"{self.prefix}.window_queries").set(total)

    def burn_rate(self, conf) -> float:
        """Current burn rate over the trailing window (0.0 = off or no
        traffic). Pruned on read so a quiet period decays the burn."""
        target = conf.serve_slo_p99_seconds if conf is not None else 0.0
        if target <= 0:
            return 0.0
        window = max(conf.serve_slo_window_seconds, 1e-3)
        with self._lock:
            self._prune(time.monotonic(), window)
            total = len(self._events)
            violations = self._violations_in_window
        return (violations / total) / _SLO_ALLOWED_FRACTION \
            if total else 0.0

    def refresh(self, conf) -> float:
        """Prune the window and RE-PUBLISH the burn gauges — the alert
        plane's feed. `record()` only publishes when a query completes,
        so after traffic stops `serve.slo.burn_rate` would freeze at
        its last (possibly burning) value and a burn incident could
        never resolve; the sampler-tick evaluation reads the burn
        through here so the published gauge always reflects the decayed
        window. Returns the current burn rate."""
        burn = self.burn_rate(conf)
        target = conf.serve_slo_p99_seconds if conf is not None else 0.0
        if target > 0:
            with self._lock:
                total = len(self._events)
            reg = telemetry.get_registry()
            reg.gauge(f"{self.prefix}.burn_rate").set(burn)
            reg.gauge(f"{self.prefix}.window_queries").set(total)
        return burn

    def snapshot(self, conf=None) -> dict:
        with self._lock:
            total = len(self._events)
            violations = self._violations_in_window
        out = {"window_queries": total,
               "window_violations": violations,
               "burn_rate": ((violations / total) / _SLO_ALLOWED_FRACTION
                             if total else 0.0)}
        if conf is not None:
            out["p99_target_s"] = conf.serve_slo_p99_seconds
            out["window_s"] = conf.serve_slo_window_seconds
            out["shed_enabled"] = conf.serve_slo_shed_enabled
        return out

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._violations_in_window = 0


# ---------------------------------------------------------------------------
# Degradation circuit breaker
# ---------------------------------------------------------------------------

_CLOSED, _OPEN, _HALF_OPEN = "closed", "open", "half_open"


class _Breaker:
    __slots__ = ("state", "failures", "opened_t", "probing")

    def __init__(self):
        self.state = _CLOSED
        self.failures: deque = deque()  # monotonic timestamps
        self.opened_t = 0.0
        self.probing = False


def _breaker_knobs(conf):
    from hyperspace_tpu import constants
    if conf is None:
        return (constants.SERVE_BREAKER_FAILURES_DEFAULT,
                constants.SERVE_BREAKER_WINDOW_SECONDS_DEFAULT,
                constants.SERVE_BREAKER_COOLDOWN_SECONDS_DEFAULT)
    return (conf.serve_breaker_failures,
            conf.serve_breaker_window_seconds,
            conf.serve_breaker_cooldown_seconds)


class BreakerBoard:
    """Per-index degradation circuit breakers.

    closed --N failures in window--> open --cooldown--> half-open
    (ONE probe query allowed through) --success--> closed / --failure-->
    open again. A failure here is an `IndexDataUnavailableError`
    fallback: the breaker's job is to stop re-paying the failed index
    scan once the index is KNOWN bad, not to mask novel errors.
    Transitions land in `resilience.breaker.{opened,half_open,closed}`
    counters and, when a query recorder is active, as flight-recorder
    visible `resilience: breaker` events."""

    def __init__(self):
        self._lock = threading.Lock()
        self._breakers: Dict[str, _Breaker] = {}

    def state(self, index_name: str) -> str:
        with self._lock:
            b = self._breakers.get(index_name)
            return b.state if b is not None else _CLOSED

    def _transition(self, b: _Breaker, state: str, index_name: str) -> None:
        # Called under the lock. Counter + decision event per move.
        b.state = state
        telemetry.get_registry().counter(
            f"resilience.breaker.{state if state != _OPEN else 'opened'}"
        ).inc()
        telemetry.event("resilience", "breaker", index=index_name,
                        state=state)

    def allow(self, index_name: str, conf=None) -> str:
        """Admission verdict for a query selecting `index_name`:
        "closed" (serve from index), "probe" (half-open: THIS query is
        the probe), or "open" (skip straight to the source plan)."""
        with self._lock:
            b = self._breakers.get(index_name)
            if b is None or b.state == _CLOSED:
                return _CLOSED
            _n, _w, cooldown = _breaker_knobs(conf)
            if b.state == _OPEN:
                if time.monotonic() - b.opened_t < cooldown:
                    return _OPEN
                self._transition(b, _HALF_OPEN, index_name)
                b.probing = True
                return "probe"
            # half-open: one probe at a time
            if not b.probing:
                b.probing = True
                return "probe"
            return _OPEN

    def record_failure(self, index_name: str, conf=None) -> None:
        now = time.monotonic()
        with self._lock:
            b = self._breakers.setdefault(index_name, _Breaker())
            n, window, _cooldown = _breaker_knobs(conf)
            if b.state == _HALF_OPEN:
                # Probe failed: straight back to open, fresh cooldown.
                b.probing = False
                b.opened_t = now
                self._transition(b, _OPEN, index_name)
                return
            if b.state == _OPEN:
                return  # already open (a pre-open query finishing late)
            b.failures.append(now)
            while b.failures and b.failures[0] < now - window:
                b.failures.popleft()
            if len(b.failures) >= max(1, n):
                b.opened_t = now
                b.failures.clear()
                self._transition(b, _OPEN, index_name)

    def record_success(self, index_name: str) -> None:
        with self._lock:
            b = self._breakers.get(index_name)
            if b is None:
                return
            if b.state == _HALF_OPEN:
                b.probing = False
                self._transition(b, _CLOSED, index_name)
            elif b.state == _CLOSED:
                b.failures.clear()

    def reset(self) -> None:
        with self._lock:
            self._breakers.clear()

    def snapshot(self) -> Dict[str, str]:
        with self._lock:
            return {name: b.state for name, b in self._breakers.items()}


# ---------------------------------------------------------------------------
# The scheduler
# ---------------------------------------------------------------------------


class _QueryEntry:
    __slots__ = ("query_id", "deadline", "footprint", "session_id",
                 "admitted", "replica", "n_replicas", "tenant", "shed")

    def __init__(self, query_id: str, deadline: Deadline, footprint: int,
                 session_id: Optional[int]):
        self.query_id = query_id
        self.deadline = deadline
        self.footprint = footprint
        self.session_id = session_id
        self.admitted = False
        # Replica routing (`parallel/replica.py`): the slice this
        # query's fills + execution are pinned to, or None. With a
        # replica set, admission charges the PER-REPLICA budget
        # (budget / n_replicas) so one hot replica cannot starve the
        # others' admission headroom.
        self.replica: Optional[int] = None
        self.n_replicas: int = 0
        # Billing identity: the tenant this query charges (default
        # tenant when no tenant scope is active — never None, so every
        # query always has someone to bill) and the shed flag the SLO
        # shedder sets to evict this WAITING entry from the queue.
        self.tenant: str = telemetry.DEFAULT_TENANT
        self.shed = False


class QueryScheduler:
    """Process-wide serving-plane scheduler (module docstring). All
    waiting happens on the CALLER's thread — the scheduler spawns no
    threads of its own (and the metrics-coverage lint bans raw
    `threading.Thread` elsewhere in `engine/`), so there is no
    dispatcher to deadlock or leak."""

    def __init__(self):
        self._cv = threading.Condition()
        self._active: Dict[str, _QueryEntry] = {}
        self._waiters: deque = deque()  # all waiting _QueryEntry
        self._admitted_bytes = 0
        self._inflight = 0
        self._idle_baseline = 0  # accountant live bytes at idle
        self._ids = itertools.count(1)
        self.peak_admitted_bytes = 0
        self._breakers = BreakerBoard()
        self._slo = SloTracker()
        # Per-replica load (replica routing, `parallel/replica.py`):
        # admitted bytes + in-flight counts keyed by replica slice.
        # The router reads these to pick the least-loaded replica; the
        # gauges `serve.replica.<i>.admitted_bytes` mirror them.
        self._replica_bytes: Dict[int, int] = {}
        self._replica_inflight: Dict[int, int] = {}
        # Multi-tenant state. The wait queue is weighted-fair
        # deficit-round-robin across per-tenant FIFOs (one burst cannot
        # starve the long tail): `_tenant_queues` holds each tenant's
        # waiters in arrival order, `_drr_order` rotates the tenants,
        # `_drr_deficit` accumulates each tenant's configured weight
        # per round and spends 1.0 per dequeue, and `_drr_next` pins
        # the selected head until it admits or leaves (selection must
        # be stable across cv wakeups or waiters livelock). Admission
        # quotas charge `_tenant_bytes`/`_tenant_inflight`; per-tenant
        # `SloTracker`s publish `serve.tenant.<id>.slo.*` and name the
        # burning tenant the shed hook evicts first.
        self._tenant_queues: Dict[str, deque] = {}
        self._drr_order: deque = deque()  # tenant ids, round-robin
        self._drr_deficit: Dict[str, float] = {}
        self._drr_next: Optional[_QueryEntry] = None
        self._tenant_bytes: Dict[str, int] = {}
        self._tenant_inflight: Dict[str, int] = {}
        self._tenant_slo: Dict[str, SloTracker] = {}

    # -- introspection ----------------------------------------------------

    def active_queries(self) -> List[str]:
        """Query ids currently admitted or queued (cancel targets)."""
        with self._cv:
            return sorted(self._active)

    def admitted_bytes(self) -> int:
        with self._cv:
            return self._admitted_bytes

    def queue_depth(self) -> int:
        """Queries currently WAITING for admission (0 = nothing queued)."""
        with self._cv:
            return len(self._waiters)

    def pressure(self) -> dict:
        """One-shot serving-pressure snapshot for background work that
        must yield to live traffic (the index advisor's build gate):
        admitted bytes, in-flight count, and queue depth under one lock
        acquisition."""
        with self._cv:
            return {"admitted_bytes": self._admitted_bytes,
                    "inflight": self._inflight,
                    "queue_depth": len(self._waiters)}

    def replica_admitted_bytes(self) -> Dict[int, int]:
        """Per-replica admitted bytes (the router's load signal)."""
        with self._cv:
            return dict(self._replica_bytes)

    def replica_inflight(self) -> Dict[int, int]:
        """Per-replica in-flight query counts (the router's tiebreak)."""
        with self._cv:
            return dict(self._replica_inflight)

    @property
    def breakers(self) -> BreakerBoard:
        return self._breakers

    @property
    def slo(self) -> SloTracker:
        return self._slo

    def slo_snapshot(self, conf=None) -> dict:
        """SLO window state for `/healthz` and the bench drivers."""
        return self._slo.snapshot(conf)

    def _tenant_slo_for(self, tenant: str) -> SloTracker:
        """The tenant's own SLO window (created on first use),
        publishing `serve.tenant.<id>.slo.*`. Lock-free on the hit
        path: this runs once per COMPLETED query, and taking the
        scheduler cv here would put every finisher in line behind
        admission traffic."""
        trk = self._tenant_slo.get(tenant)  # atomic dict read
        if trk is not None:
            return trk
        with self._cv:
            trk = self._tenant_slo.get(tenant)
            if trk is None:
                trk = SloTracker(prefix=f"serve.tenant.{tenant}.slo")
                self._tenant_slo[tenant] = trk
            return trk

    def tenant_snapshot(self, conf=None) -> dict:
        """Per-tenant serving state for `/healthz` and
        `Hyperspace.tenant_report()`: admitted bytes, in-flight and
        queued counts, the tenant's SLO window, and its configured
        scheduling knobs."""
        with self._cv:
            tenants = (set(self._tenant_bytes)
                       | set(self._tenant_inflight)
                       | set(self._tenant_queues)
                       | set(self._tenant_slo))
            out = {t: {"admitted_bytes": self._tenant_bytes.get(t, 0),
                       "inflight": self._tenant_inflight.get(t, 0),
                       "queued": len(self._tenant_queues.get(t, ()))}
                   for t in sorted(tenants)}
            trackers = dict(self._tenant_slo)
        for t, d in out.items():
            trk = trackers.get(t)
            if trk is not None:
                d["slo"] = trk.snapshot(conf)
            if conf is not None:
                d["weight"] = conf.serve_tenant_weight(t)
                frac = conf.serve_tenant_hbm_fraction(t)
                if frac > 0:
                    d["hbm_fraction"] = frac
                tdepth = conf.serve_tenant_queue_depth(t)
                if tdepth > 0:
                    d["queue_depth"] = tdepth
        return out

    # -- cancellation -----------------------------------------------------

    def cancel(self, query_id: str) -> bool:
        """Cooperatively cancel a queued or running query. True iff the
        id was live (the query raises `QueryCancelledError` at its next
        checkpoint — cancellation is a request, not preemption)."""
        with self._cv:
            ent = self._active.get(query_id)
            if ent is None:
                return False
            ent.deadline.cancel()
            self._cv.notify_all()
        return True

    def cancel_session(self, session) -> int:
        """Cancel every live query submitted through `session`
        (`session.close()`'s drain). Returns how many were flagged."""
        sid = id(session)
        n = 0
        with self._cv:
            for ent in self._active.values():
                if ent.session_id == sid:
                    ent.deadline.cancel()
                    n += 1
            if n:
                self._cv.notify_all()
        return n

    def drain_session(self, session, timeout_s: float = 10.0) -> bool:
        """Block until no query of `session` is live (or timeout).
        True iff drained."""
        sid = id(session)
        t_end = time.monotonic() + timeout_s
        with self._cv:
            while any(e.session_id == sid for e in self._active.values()):
                left = t_end - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(timeout=min(left, _WAIT_QUANTUM_S))
        return True

    # -- admission --------------------------------------------------------

    def _live_device_bytes(self) -> int:
        """Last-sampled accountant live total (no walk forced — the
        accountant samples at span boundaries and query ends already;
        admission reads whatever is freshest)."""
        try:
            return sum(telemetry.get_accountant().live.values())
        except Exception:
            return 0

    def _fits(self, ent: "_QueryEntry", budget: int, conf=None) -> bool:
        # Caller holds the cv lock. Progress guarantee: with nothing in
        # flight a query larger than the whole budget still admits —
        # the budget bounds CONCURRENCY, it must never wedge serving.
        if self._inflight == 0:
            return True
        # Per-tenant HBM quota (`serve.tenant.<id>.hbm.fraction`): a
        # configured tenant may hold at most its fraction of the budget
        # admitted concurrently, with the same progress guarantee — a
        # tenant with nothing in flight always admits one query.
        frac = (conf.serve_tenant_hbm_fraction(ent.tenant)
                if conf is not None else 0.0)
        if frac > 0 and self._tenant_inflight.get(ent.tenant, 0) > 0 \
                and self._tenant_bytes.get(ent.tenant, 0) \
                + ent.footprint > int(budget * frac):
            return False
        if ent.replica is not None and ent.n_replicas > 1:
            # Per-replica admission: the query charges its SLICE's
            # share of the budget, with the same per-replica progress
            # guarantee — an idle replica always admits.
            if self._replica_inflight.get(ent.replica, 0) == 0:
                return True
            per = budget // ent.n_replicas
            if self._replica_bytes.get(ent.replica, 0) \
                    + ent.footprint > per:
                return False
        live = self._live_device_bytes()
        used = max(self._admitted_bytes,
                   live - self._idle_baseline if live else 0)
        return used + ent.footprint <= budget

    # -- weighted-fair wait queue (deficit round robin) -------------------

    def _enqueue_waiter(self, ent: _QueryEntry) -> None:
        # Caller holds the cv lock.
        self._waiters.append(ent)
        q = self._tenant_queues.setdefault(ent.tenant, deque())
        q.append(ent)
        if ent.tenant not in self._drr_order:
            self._drr_order.append(ent.tenant)

    def _remove_waiter(self, ent: _QueryEntry) -> None:
        # Caller holds the cv lock. Safe to call when not queued.
        try:
            self._waiters.remove(ent)
        except ValueError:
            pass
        q = self._tenant_queues.get(ent.tenant)
        if q is not None:
            try:
                q.remove(ent)
            except ValueError:
                pass
            if not q:
                self._tenant_queues.pop(ent.tenant, None)
        if self._drr_next is ent:
            self._drr_next = None

    def _drr_select(self, conf) -> Optional[_QueryEntry]:
        """The waiter that admits next, by weighted-fair deficit round
        robin over the per-tenant FIFOs: each visited tenant banks its
        configured weight and a dequeue spends 1.0, so a weight-2
        tenant drains twice as fast as a weight-1 tenant under
        contention — and a one-tenant burst cannot starve the others'
        heads the way the old global FIFO could. The pick is PINNED
        (`_drr_next`) until that entry admits or leaves: selection must
        be stable across cv wakeups or waiters spin past each other.
        Caller holds the cv lock."""
        if self._drr_next is not None:
            return self._drr_next
        if not self._waiters:
            return None
        for _ in range(4096):  # weights are clamped > 0: bounded spin
            if not self._drr_order:
                self._drr_order.extend(self._tenant_queues)
                if not self._drr_order:
                    break
            t = self._drr_order[0]
            q = self._tenant_queues.get(t)
            if not q:
                self._drr_order.popleft()
                self._drr_deficit.pop(t, None)
                continue
            d = self._drr_deficit.get(t, 0.0)
            if d < 1.0:
                # Bank the weight only when broke: deficits stay
                # bounded in [0, max(w, 1)) instead of accumulating
                # credit a tenant could never spend.
                d += (conf.serve_tenant_weight(t)
                      if conf is not None else 1.0)
            if d >= 1.0:
                self._drr_deficit[t] = d - 1.0
                if d - 1.0 < 1.0:
                    # Deficit spent: this tenant's turn ends. While
                    # credit remains it stays at the head — a weight-2
                    # tenant dequeues twice per visit, which is what
                    # makes the weights mean drain RATE.
                    self._drr_order.rotate(-1)
                self._drr_next = q[0]
                return self._drr_next
            self._drr_deficit[t] = d
            self._drr_order.rotate(-1)
        self._drr_next = self._waiters[0]  # defensive: degrade to FIFO
        return self._drr_next

    def _shed_victim(self, arriving: _QueryEntry, conf) \
            -> Optional[_QueryEntry]:
        """While shedding is active, the BURNING tenant's queue sheds
        first: the waiter shed to make room is the newest queued entry
        of the tenant whose own SLO window burns hottest — not the
        arriving query, unless the arriver IS the burning tenant (or
        no burning tenant has anything queued). Caller holds the cv
        lock; returns None when the arriving query should be rejected
        instead (the pre-tenant behavior)."""
        burning, worst = None, SLO_SHED_BURN_THRESHOLD
        for t, trk in self._tenant_slo.items():
            if t == arriving.tenant:
                continue
            q = self._tenant_queues.get(t)
            if not q:
                continue
            burn = trk.burn_rate(conf)
            if burn > worst:
                burning, worst = t, burn
        if burning is None:
            return None
        return self._tenant_queues[burning][-1]

    def _admit(self, ent: _QueryEntry, conf) -> float:
        """Admit `ent` (blocking, weighted-fair across tenants, when
        over budget). Returns seconds spent queued. Raises
        QueryRejectedError when the wait queue is full (globally or for
        the entry's tenant), or the entry's own deadline error when it
        expires/cancels while queued."""
        from hyperspace_tpu.utils import faults
        faults.fire("scheduler.admit")
        reg = telemetry.get_registry()
        budget = conf.serve_hbm_budget_bytes if conf is not None else 0
        with self._cv:
            if budget <= 0 or (not self._waiters
                               and self._fits(ent, budget, conf)):
                self._grant(ent, reg)
                reg.histogram("serve.queue_wait_s").observe(0.0)
                return 0.0
            depth = max(0, conf.serve_queue_depth
                        if conf is not None else 0)
            # Per-tenant queue-depth quota: a configured tenant may
            # hold at most `serve.tenant.<id>.queue.depth` WAITING
            # queries — its burst backpressures itself before it can
            # occupy the shared queue.
            tdepth = (conf.serve_tenant_queue_depth(ent.tenant)
                      if conf is not None else 0)
            tqueued = len(self._tenant_queues.get(ent.tenant, ()))
            if tdepth > 0 and tqueued >= tdepth:
                reg.counter(f"serve.tenant.{ent.tenant}.rejected").inc()
                raise QueryRejectedError(
                    f"query {ent.query_id} rejected: tenant "
                    f"'{ent.tenant}' wait queue is full "
                    f"({tqueued}/{tdepth})",
                    query_id=ent.query_id, phase="queue")
            # SLO shedding (opt-in): while the burn rate says the error
            # budget is being consumed faster than the p99 objective
            # allows, tighten the wait queue to HALF its configured
            # depth — controlled backpressure at the admission door
            # instead of a queue whose tail is guaranteed to violate.
            # A query rejected by the tightened (not the configured)
            # depth counts `serve.slo.shed` exactly once. With tenants
            # in play the shed targets the BURNING tenant's queue
            # first: its newest waiter is evicted to make room for the
            # arriver, so one tenant burning its budget cannot convert
            # tightened depth into rejections for everyone else.
            effective = depth
            if conf is not None and conf.serve_slo_shed_enabled \
                    and self._slo.burn_rate(conf) \
                    > SLO_SHED_BURN_THRESHOLD:
                effective = depth // 2
            if len(self._waiters) >= effective:
                shed_mode = effective < depth \
                    and len(self._waiters) < depth
                if shed_mode:
                    victim = self._shed_victim(ent, conf)
                    if victim is not None and not victim.shed:
                        victim.shed = True
                        reg.counter("serve.slo.shed").inc()
                        reg.counter(
                            f"serve.tenant.{victim.tenant}.rejected"
                        ).inc()
                        self._cv.notify_all()
                    else:
                        reg.counter("serve.slo.shed").inc()
                        reg.counter(
                            f"serve.tenant.{ent.tenant}.rejected").inc()
                        raise QueryRejectedError(
                            f"query {ent.query_id} rejected: projected "
                            f"{ent.footprint} B does not fit the "
                            f"serving budget ({budget} B, "
                            f"{self._admitted_bytes} B admitted) and "
                            f"the wait queue is full "
                            f"({len(self._waiters)}/{effective} — SLO "
                            f"shedding active)",
                            query_id=ent.query_id, phase="queue")
                else:
                    reg.counter(
                        f"serve.tenant.{ent.tenant}.rejected").inc()
                    raise QueryRejectedError(
                        f"query {ent.query_id} rejected: projected "
                        f"{ent.footprint} B does not fit the serving "
                        f"budget ({budget} B, {self._admitted_bytes} B "
                        f"admitted) and the wait queue is full "
                        f"({len(self._waiters)}/{effective})",
                        query_id=ent.query_id, phase="queue")
            t0 = time.perf_counter()
            self._enqueue_waiter(ent)
            reg.counter("serve.queued").inc()
            reg.counter(f"serve.tenant.{ent.tenant}.queued").inc()
            reg.gauge("serve.queue_depth").set(len(self._waiters))
            try:
                while not (self._drr_select(conf) is ent
                           and self._fits(ent, budget, conf)):
                    if ent.shed:
                        raise QueryRejectedError(
                            f"query {ent.query_id} shed from the wait "
                            f"queue: tenant '{ent.tenant}' is burning "
                            f"its SLO error budget",
                            query_id=ent.query_id, phase="queue")
                    ent.deadline.check("queue")
                    rem = ent.deadline.remaining()
                    self._cv.wait(timeout=(_WAIT_QUANTUM_S if rem is None
                                           else min(rem + 1e-3,
                                                    _WAIT_QUANTUM_S)))
                self._remove_waiter(ent)
                self._grant(ent, reg)
            finally:
                self._remove_waiter(ent)  # no-op when admitted above
                reg.gauge("serve.queue_depth").set(len(self._waiters))
                self._cv.notify_all()
            wait_s = time.perf_counter() - t0
        reg.histogram("serve.queue_wait_s").observe(wait_s)
        return wait_s

    def _grant(self, ent: _QueryEntry, reg) -> None:
        # Caller holds the cv lock.
        self._admitted_bytes += ent.footprint
        self._inflight += 1
        ent.admitted = True
        if self._admitted_bytes > self.peak_admitted_bytes:
            self.peak_admitted_bytes = self._admitted_bytes
        reg.counter("serve.admitted").inc()
        reg.counter(f"serve.tenant.{ent.tenant}.admitted").inc()
        reg.gauge("serve.admitted_bytes").set(self._admitted_bytes)
        reg.gauge("serve.active").set(self._inflight)
        self._tenant_bytes[ent.tenant] = \
            self._tenant_bytes.get(ent.tenant, 0) + ent.footprint
        self._tenant_inflight[ent.tenant] = \
            self._tenant_inflight.get(ent.tenant, 0) + 1
        if ent.replica is not None:
            r = ent.replica
            self._replica_bytes[r] = (self._replica_bytes.get(r, 0)
                                      + ent.footprint)
            self._replica_inflight[r] = \
                self._replica_inflight.get(r, 0) + 1
            reg.gauge(f"serve.replica.{r}.admitted_bytes").set(
                self._replica_bytes[r])

    def _credit(self, ent: _QueryEntry, nbytes: int) -> int:
        """Footprint credit for already-HBM-resident bytes: once the
        optimized plan is known, the bytes its index scans will serve
        from the segment cache (`io/segcache.py`) are NOT bytes this
        query will stage — shrink its admitted charge so queued queries
        over the same hot index stop serially occupying budget as if
        each re-staged the data (the admission-side half of shared-scan
        coalescing; the cache's single-flight fill is the other half).
        Returns the bytes actually credited (clamped so a query never
        charges below the footprint floor)."""
        from hyperspace_tpu.plan.footprint import MIN_FOOTPRINT_BYTES
        with self._cv:
            if not ent.admitted or nbytes <= 0:
                return 0
            delta = min(int(nbytes),
                        max(0, ent.footprint - MIN_FOOTPRINT_BYTES))
            if delta <= 0:
                return 0
            ent.footprint -= delta
            self._admitted_bytes -= delta
            self._tenant_bytes[ent.tenant] = max(
                0, self._tenant_bytes.get(ent.tenant, 0) - delta)
            reg = telemetry.get_registry()
            reg.counter("serve.footprint_credit_bytes").inc(delta)
            reg.gauge("serve.admitted_bytes").set(self._admitted_bytes)
            if ent.replica is not None:
                r = ent.replica
                self._replica_bytes[r] = max(
                    0, self._replica_bytes.get(r, 0) - delta)
                reg.gauge(f"serve.replica.{r}.admitted_bytes").set(
                    self._replica_bytes[r])
            self._cv.notify_all()
        return delta

    def _release(self, ent: _QueryEntry) -> None:
        reg = telemetry.get_registry()
        with self._cv:
            self._active.pop(ent.query_id, None)
            if ent.admitted:
                self._admitted_bytes -= ent.footprint
                self._inflight -= 1
                self._tenant_bytes[ent.tenant] = max(
                    0, self._tenant_bytes.get(ent.tenant, 0)
                    - ent.footprint)
                self._tenant_inflight[ent.tenant] = max(
                    0, self._tenant_inflight.get(ent.tenant, 0) - 1)
                if ent.replica is not None:
                    r = ent.replica
                    self._replica_bytes[r] = max(
                        0, self._replica_bytes.get(r, 0) - ent.footprint)
                    self._replica_inflight[r] = max(
                        0, self._replica_inflight.get(r, 0) - 1)
                    reg.gauge(f"serve.replica.{r}.admitted_bytes").set(
                        self._replica_bytes[r])
                if self._inflight == 0:
                    # Re-anchor: bookkeeping drift cannot accumulate,
                    # and the idle baseline tracks resident caches so
                    # `_fits` charges queries only for QUERY memory.
                    self._admitted_bytes = 0
                    self._replica_bytes.clear()
                    self._replica_inflight.clear()
                    self._tenant_bytes.clear()
                    self._tenant_inflight.clear()
                    self._idle_baseline = self._live_device_bytes()
                reg.gauge("serve.admitted_bytes").set(self._admitted_bytes)
                reg.gauge("serve.active").set(self._inflight)
            self._cv.notify_all()

    # -- serving-error bookkeeping ---------------------------------------

    def _record_serving_error(self, exc: QueryServingError, metrics,
                              conf) -> None:
        """One place counts every typed serving error (exactly once):
        the class-declared counter, a per-phase `serve.interrupted.*`
        series, and — when the query had started executing — the event
        + interrupted-phase counter on its recorder, which then joins
        the flight ring so timeout clusters are diagnosable post-hoc."""
        reg = telemetry.get_registry()
        reg.counter(exc.counter).inc()
        phase = exc.phase or "unknown"
        reg.counter(f"serve.interrupted.{phase}").inc()
        if metrics is None:
            return
        metrics.event("serve", exc.counter.split(".", 1)[1],
                      query_id=exc.query_id, phase=phase)
        metrics.add_count(f"serve.interrupted.{phase}")
        metrics.finish()
        telemetry.flight.record(metrics, conf=conf)
        # Completed puts of the cancelled query release their window
        # bytes + staging buffers now, not at the next caller's put.
        try:
            from hyperspace_tpu.io import transfer
            transfer.get_engine().sweep()
        except Exception:
            pass

    # -- resilient execution (breaker + degradation fallback) ------------

    @staticmethod
    def _index_scans(plan) -> List[tuple]:
        """(index_name, breaker_key) of every rule-selected index scan.
        The breaker keys on name AND data root: two warehouses (or two
        test environments) reusing an index name are different indexes,
        and one going bad must not short-circuit the other."""
        from hyperspace_tpu.plan.nodes import Scan
        out: List[tuple] = []

        def visit(node):
            if isinstance(node, Scan) and node.index_name:
                root = node.root_paths[0] if node.root_paths else ""
                out.append((node.index_name,
                            f"{node.index_name}@{root}"))
            for c in node.children:
                visit(c)

        visit(plan)
        return out

    def _degrade(self, df, metrics, conf, index_name, reason: str):
        """Answer from the SOURCE plan (graceful degradation), keeping
        the downgrade loud in telemetry."""
        from hyperspace_tpu.engine.executor import execute_plan
        telemetry.get_registry().counter("resilience.fallbacks").inc()
        metrics.add_count("resilience.fallbacks")
        metrics.event("resilience", "degraded", index=index_name,
                      reason=reason)
        return execute_plan(df.plan, conf=conf)

    def _execute_resilient(self, df, plan, metrics, conf):
        """Execute the optimized plan with the per-index circuit
        breaker wrapped around the PR-4 degradation fallback."""
        from hyperspace_tpu.engine.executor import execute_plan
        index_scans = self._index_scans(plan) if plan is not df.plan \
            else []
        for name, key in index_scans:
            verdict = self._breakers.allow(key, conf)
            if verdict == _OPEN:
                # Known-bad index: skip STRAIGHT to the source plan —
                # no failed index scan to re-pay.
                telemetry.get_registry().counter(
                    "resilience.breaker.short_circuits").inc()
                metrics.add_count("resilience.breaker.short_circuits")
                return self._degrade(df, metrics, conf, name,
                                     "breaker open")
        try:
            batch = execute_plan(plan, conf=conf)
        except IndexDataUnavailableError as exc:
            if plan is df.plan:
                raise  # no rewrite to fall back from
            logger.warning("Index data unavailable (%s); falling back "
                           "to the source plan", exc)
            for name, key in index_scans:
                if name == exc.index_name:
                    self._breakers.record_failure(key, conf)
                    break
            return self._degrade(df, metrics, conf, exc.index_name,
                                 str(exc))
        for _name, key in index_scans:
            self._breakers.record_success(key)
        return batch

    # -- the collect pipeline ---------------------------------------------

    def collect(self, df, timeout: Optional[float] = None,
                tenant: Optional[str] = None):
        """Execute a DataFrame end to end under serving control.
        Returns `(arrow_table, QueryMetrics)` — `DataFrame.collect`
        owns the user-facing return shape. `tenant` (else the
        session's sticky `session.tenant(...)` default, else the
        DEFAULT tenant) is the billing identity the query charges:
        admission quotas, DRR dequeue weight, SLO window, and every
        chargeback counter key on it."""
        from hyperspace_tpu.io.columnar import to_arrow
        from hyperspace_tpu.plan import footprint as _footprint
        from hyperspace_tpu.utils import faults

        session = df.session
        conf = session.conf if session is not None else None
        if session is not None and getattr(session, "_closed", False):
            raise HyperspaceException(
                "Session is closed; create a new HyperspaceSession.")
        if tenant is None and session is not None:
            tenant = getattr(session, "_default_tenant", None)
        eff_tenant = str(tenant) if tenant else telemetry.DEFAULT_TENANT
        query_id = f"q-{next(self._ids)}"
        if timeout is None and conf is not None:
            timeout = conf.serve_deadline_seconds or None
        deadline = Deadline(query_id, timeout)
        ent = _QueryEntry(query_id, deadline,
                          _footprint.projected_bytes(df.plan),
                          id(session) if session is not None else None)
        ent.tenant = eff_tenant
        # Replica routing (`parallel/replica.py`): on a multi-slice
        # topology with replication on, pin this query's fills +
        # execution to the least-loaded replica slice (cold-range
        # queries pin to their home slice). Routed BEFORE admission so
        # the per-replica budget charges the right slice; routing must
        # never fail a query.
        try:
            from hyperspace_tpu.parallel import replica as _replica
            from hyperspace_tpu.parallel.context import topology
            rep = _replica.get_router().route(df.plan, conf, self)
            if rep is not None:
                topo = topology(conf)
                ent.replica = rep
                ent.n_replicas = topo[0] if topo is not None else 0
        except Exception:
            logger.debug("replica routing skipped", exc_info=True)
        description = ", ".join(df.schema.names[:6])
        metrics = telemetry.QueryMetrics(description=description)
        metrics.query_id = query_id  # cancel/log correlation handle
        # Routed-replica dimension: flight-ring consumers (slow-decile
        # attribution, /healthz's by-replica grouping) can now group
        # entries by the slice that served them; None = unrouted.
        metrics.replica = ent.replica
        # Tenant dimension: stamped on the recorder (flight-ring
        # `tenant=` filter, /healthz by-tenant grouping) — always the
        # EFFECTIVE tenant, "default" included, so post-hoc grouping
        # never needs a null branch.
        metrics.tenant = eff_tenant
        # The SOURCE (pre-optimization) logical plan rides the recorder
        # into the flight ring: the index advisor's what-if scorer
        # replays exactly this plan against hypothetical indexes
        # (logical plans are immutable once built; holding the reference
        # costs nothing per query — no serialization on the hot path).
        metrics.logical_plan = df.plan
        with self._cv:
            self._active[query_id] = ent
        reg = telemetry.get_registry()
        try:
            try:
                t_admit0 = time.perf_counter()
                wait_s = self._admit(ent, conf)
                # Critical-path sources: the recorder's wall started at
                # construction (before admission), so queue wait and the
                # admission bookkeeping around it are genuine wall
                # segments — stamp both as per-query counters for
                # `telemetry/critical_path.py` to classify.
                metrics.add_seconds("serve.queue_wait_s", wait_s)
                metrics.add_seconds(
                    "serve.admission_s",
                    max(time.perf_counter() - t_admit0 - wait_s, 0.0))
            except QueryServingError as exc:
                self._record_serving_error(exc, None, conf)
                raise
            try:
                with telemetry.recording(metrics), \
                        telemetry.deadline_scope(deadline), \
                        telemetry.tenant_scope(eff_tenant), \
                        telemetry.span("query", "query",
                                       description=description):
                    metrics.event("serve", "admitted",
                                  query_id=query_id,
                                  footprint_bytes=ent.footprint,
                                  queue_wait_s=round(wait_s, 6))
                    faults.fire("scheduler.run")
                    deadline.check("plan")
                    plan = (session.optimize(df.plan)
                            if session is not None else df.plan)
                    if plan is not df.plan:
                        # Admission charged the UNOPTIMIZED plan. The
                        # rewritten plan may read strictly fewer bytes —
                        # a covering index's narrower data, or a
                        # sketch-pruned scan's surviving files — so
                        # re-project and credit the difference:
                        # admission control charges only what the plan
                        # will actually stage.
                        opt_fp = _footprint.projected_bytes(plan)
                        if opt_fp < ent.footprint:
                            reproj = self._credit(ent,
                                                  ent.footprint - opt_fp)
                            if reproj:
                                metrics.event("serve",
                                              "footprint_reprojected",
                                              query_id=query_id,
                                              credited_bytes=reproj)
                        # Already-resident index segments are bytes this
                        # query will never stage: credit them back so
                        # queued queries coalesce onto the warm cache.
                        try:
                            from hyperspace_tpu.io import segcache
                            resident = (segcache.get_cache()
                                        .resident_bytes_for_plan(plan))
                        except Exception:
                            resident = 0
                        credited = self._credit(ent, resident)
                        if credited:
                            metrics.event("serve", "footprint_credit",
                                          query_id=query_id,
                                          credited_bytes=credited)
                    # Inter-query batched execution (`engine/batcher.py`):
                    # concurrent same-signature point/filter queries
                    # coalesce into one jitted predicate invocation over
                    # the shared scan. None = ineligible shape, nothing
                    # to coalesce with, or batch-lane fallback — the
                    # per-query resilient path below stays the general
                    # executor (and the fallback target).
                    batch = None
                    if conf is not None and conf.serve_batch_enabled:
                        from hyperspace_tpu.engine import batcher
                        batch = batcher.get_batcher().try_collect(
                            df, plan, metrics, conf, deadline, self)
                    if batch is None:
                        # Replica-pinned execution: under the scope,
                        # every distribution decision (fills, SPMD
                        # programs) sees the routed slice's flat
                        # submesh. The batched lane above is exempt by
                        # design — its one invocation already serves
                        # the whole cohort.
                        from hyperspace_tpu.parallel.context import \
                            replica_scope
                        if ent.replica is not None:
                            metrics.event("serve", "replica",
                                          query_id=query_id,
                                          replica=ent.replica)
                        with replica_scope(ent.replica):
                            batch = self._execute_resilient(df, plan,
                                                            metrics,
                                                            conf)
                    if not batch.is_host:
                        # Query-end HBM watermark, FORCED (throttling
                        # may have swallowed every span-boundary sample
                        # of a fast query) and inside the recording so
                        # it attributes here.
                        telemetry.memory.sample()
                    else:
                        import sys as _sys
                        if "jax" in _sys.modules:
                            # Host result, but intermediates may have
                            # ridden the device; throttled sample — and
                            # never an import of jax to find zero bytes.
                            telemetry.memory.maybe_sample()
            except QueryServingError as exc:
                self._record_serving_error(exc, metrics, conf)
                raise
        finally:
            self._release(ent)
        metrics.finish()
        # Latency anatomy: decompose the finished wall into the closed
        # segment set and stamp it on the recorder BEFORE the flight
        # ring sees it, so ring entries and slow-query dumps carry
        # their own anatomy. Decomposition failure never fails the
        # query it explains.
        if conf is None or conf.critpath_enabled:
            try:
                from hyperspace_tpu.telemetry import critical_path
                critical_path.stamp(metrics)
            except Exception:
                logger.debug("critical-path stamp failed",
                             exc_info=True)
        # Process-lifetime aggregates next to the per-query recorder.
        reg.counter("queries.total").inc()
        reg.counter("queries.seconds").inc(metrics.wall_s)
        reg.histogram("query.wall_s").observe(metrics.wall_s)
        # Tenant-dimensioned wall: the sampler windows this histogram
        # like `query.wall_s`, so per-tenant window p50/p99 land on
        # `/metrics` and `/timeseries` beside the global series.
        reg.histogram(f"tenant.{eff_tenant}.query_wall_s").observe(
            metrics.wall_s)
        # Sliding-window SLO: fold this wall into the burn window
        # (no-op while `serve.slo.p99.seconds` is 0) — globally AND
        # into the tenant's own window (`serve.tenant.<id>.slo.*`),
        # which the shed hook reads to name the burning tenant.
        self._slo.record(metrics.wall_s, conf)
        self._tenant_slo_for(eff_tenant).record(metrics.wall_s, conf)
        # Triggered device capture: a burn rate past 1.0 grabs a
        # device profile of the incident while it is happening (armed
        # only when `telemetry.profiler.capture.seconds` > 0; the
        # capture itself rides the profiler's background lane).
        if conf is not None and conf.profiler_capture_seconds > 0:
            try:
                from hyperspace_tpu.telemetry import profiler
                profiler.maybe_capture_on_burn(
                    conf, self._slo.burn_rate(conf))
            except Exception:
                logger.debug("burn-triggered capture failed",
                             exc_info=True)
        # Per-index rule-usage mining (the drop advisor's raw signal):
        # one process counter per index a rule actually SERVED this
        # query from — `Hyperspace.index_usage()` joins these against
        # the flight ring to name indexes nothing selects anymore.
        for use in metrics.index_usage():
            if use.get("name"):
                reg.counter(f"rules.served.{use['name']}").inc()
        # Flight recorder: the finished recorder joins the always-on
        # ring of recent queries; a wall past the session's slowlog
        # threshold also persists a self-contained dump (metric tree +
        # registry snapshot + trace slice) for post-hoc diagnosis.
        telemetry.flight.record(metrics, conf=conf)
        if session is not None:
            session._last_query_metrics = metrics
        table = to_arrow(batch)
        return table, metrics


# ---------------------------------------------------------------------------
# Process-wide scheduler
# ---------------------------------------------------------------------------

_scheduler: Optional[QueryScheduler] = None
_scheduler_lock = threading.Lock()


def get_scheduler() -> QueryScheduler:
    global _scheduler
    if _scheduler is None:
        with _scheduler_lock:
            if _scheduler is None:
                _scheduler = QueryScheduler()
    return _scheduler


def set_scheduler(scheduler: QueryScheduler) -> QueryScheduler:
    """Install a specific scheduler (tests: fresh budgets/breakers)."""
    global _scheduler
    _scheduler = scheduler
    return scheduler


def reset_scheduler() -> None:
    global _scheduler
    _scheduler = None
