"""User-facing DataFrame: a logical plan + session.

The equivalent of the Spark DataFrame surface the reference operates on.
Transformations are lazy plan builders; `collect`/`to_pandas`/`count` run
the optimizer (rewrite rules, when enabled) and execute on device.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan import expr as E
from hyperspace_tpu.plan.nodes import (Aggregate, AggSpec, Filter, Join,
                                       Limit, LogicalPlan, Project, Sort)
from hyperspace_tpu.plan.schema import Schema


class DataFrame:
    def __init__(self, plan: LogicalPlan, session=None):
        self.plan = plan
        self.session = session

    @property
    def schema(self) -> Schema:
        return self.plan.schema

    @property
    def columns(self) -> List[str]:
        return self.schema.names

    # -- transformations (lazy) ------------------------------------------

    def filter(self, condition: E.Expression) -> "DataFrame":
        if not isinstance(condition, E.Expression):
            raise HyperspaceException("filter() takes an Expression predicate.")
        return DataFrame(Filter(condition, self.plan), self.session)

    where = filter
    # HAVING is a filter over an aggregate's output (SQL surface parity);
    # the engine plans it as FilterExec(AggregateExec(...)).
    having = filter

    def select(self, *columns) -> "DataFrame":
        """Projection. Entries are column names or named expressions:
        `df.select("a", (col("x") * col("y")).alias("xy"))`."""
        names = [c for col in columns
                 for c in (col if isinstance(col, (list, tuple)) else [col])]
        return DataFrame(Project(names, self.plan), self.session)

    def with_column(self, name: str, expression: E.Expression) -> "DataFrame":
        """Append a computed column; replacing an existing one keeps its
        position (Spark withColumn semantics)."""
        alias = E.Alias(expression, name)
        entries: list = []
        replaced = False
        for c in self.schema.names:
            if c.lower() == name.lower():
                entries.append(alias)
                replaced = True
            else:
                entries.append(c)
        if not replaced:
            entries.append(alias)
        return DataFrame(Project(entries, self.plan), self.session)

    def join(self, other: "DataFrame",
             on: Union[E.Expression, str, Sequence[str], None] = None,
             how: str = "inner") -> "DataFrame":
        how = {"semi": "left_semi", "anti": "left_anti",
               "left": "left_outer", "right": "right_outer",
               "full": "full_outer", "outer": "full_outer"}.get(how, how)
        if how == "cross" or on is None:
            if on is not None or how != "cross":
                raise HyperspaceException(
                    "join needs `on` keys unless how='cross'; cross joins "
                    "take none.")
            return DataFrame(Join(self.plan, other.plan, None, "cross"),
                             self.session)
        if isinstance(on, str):
            on = [on]
        if isinstance(on, (list, tuple)):
            condition: Optional[E.Expression] = None
            for name in on:
                term = E.EqualTo(E.Column(name), E.Column(name))
                condition = term if condition is None else E.And(condition, term)
            if condition is None:
                raise HyperspaceException("join requires at least one key.")
        else:
            condition = on
        return DataFrame(Join(self.plan, other.plan, condition, how),
                         self.session)

    def sort(self, *columns: str) -> "DataFrame":
        """ORDER BY. Plain names sort ascending (nulls first); prefix a
        name with "-" for descending (nulls last): df.sort("a", "-b")."""
        return DataFrame(Sort(list(columns), self.plan), self.session)

    order_by = sort

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(Limit(n, self.plan), self.session)

    def group_by(self, *columns: str) -> "GroupedData":
        return GroupedData(self, list(columns))

    def window(self, partition_by: Sequence[str],
               order_by: Optional[Sequence[str]] = None,
               **specs) -> "DataFrame":
        """Append window columns over partitions:
        `df.window(["k"], order_by=["-total"], rk=("rank", "*"),
        part_avg=("avg", "total"))`. Functions: rank, dense_rank,
        row_number (ORDER BY required; column "*"), and partition-wide
        sum/avg/min/max/count."""
        from hyperspace_tpu.plan.nodes import Window
        parsed = [AggSpec(func, column, alias)
                  for alias, (func, column) in specs.items()]
        return DataFrame(Window(list(partition_by), list(order_by or []),
                                parsed, self.plan), self.session)

    def distinct(self) -> "DataFrame":
        """SELECT DISTINCT: deduplicate rows (an aggregation over all
        columns with no aggregate outputs)."""
        return DataFrame(Aggregate(self.columns, [], self.plan),
                         self.session)

    drop_duplicates = distinct

    def union(self, other: "DataFrame") -> "DataFrame":
        """UNION ALL (SQL): row-wise concatenation; column names must
        align. DISTINCT union = .union(o).distinct()."""
        from hyperspace_tpu.plan.nodes import Union as UnionNode
        return DataFrame(UnionNode([self.plan, other.plan]), self.session)

    union_all = union

    def intersect(self, other: "DataFrame") -> "DataFrame":
        """SQL INTERSECT (DISTINCT set semantics; NULL rows compare
        equal, unlike joins)."""
        from hyperspace_tpu.plan.nodes import Intersect
        return DataFrame(Intersect(self.plan, other.plan), self.session)

    def except_(self, other: "DataFrame") -> "DataFrame":
        """SQL EXCEPT (DISTINCT set semantics)."""
        from hyperspace_tpu.plan.nodes import Except
        return DataFrame(Except(self.plan, other.plan), self.session)

    def create_or_replace_temp_view(self, name: str) -> None:
        """Register this query as a named temp view on the session
        (Spark `createOrReplaceTempView` parity)."""
        if self.session is None:
            raise HyperspaceException("DataFrame has no session.")
        self.session.create_or_replace_temp_view(name, self)

    def as_scalar(self) -> E.Expression:
        """This (one-column, at-most-one-row) query as a scalar value
        expression — SQL's scalar subquery: `col("x") >
        df.agg(("avg","x","a")).as_scalar()`."""
        return E.ScalarSubquery(self.plan)

    def agg(self, *specs, **named) -> "DataFrame":
        """Global aggregation (no grouping); see GroupedData.agg."""
        return GroupedData(self, []).agg(*specs, **named)

    # -- actions (execute) ------------------------------------------------

    def _optimized_plan(self) -> LogicalPlan:
        if self.session is not None:
            return self.session.optimize(self.plan)
        return self.plan

    def _conf(self):
        return self.session.conf if self.session is not None else None

    def collect(self, with_metrics: bool = False,
                timeout: Optional[float] = None,
                tenant: Optional[str] = None):
        """Execute and return an Arrow table. `with_metrics=True` returns
        `(table, telemetry.QueryMetrics)` instead — per-operator timings
        and row counts, optimizer-rule and fusion-lane decision events,
        and index-usage records for THIS query. Metrics are recorded for
        every session-attached collect (the recorder is a handful of
        perf_counter reads per operator) and stashed as
        `session.last_query_metrics()`; the optimizer runs inside the
        recording so rule fired/skipped events are captured too.

        Every collect routes through the process-wide serving plane
        (`engine/scheduler.py`): admission control against the HBM
        budget (typed `QueryRejectedError` backpressure when the wait
        queue is full), a per-query deadline — `timeout` (seconds)
        overrides `spark.hyperspace.serve.deadline.seconds`; expiry or
        `session.cancel(query_id)` raises typed
        `QueryDeadlineExceededError` / `QueryCancelledError` at the
        next cooperative checkpoint — and the per-index degradation
        circuit breaker around the index-fallback path.

        `tenant` names the billing identity this query charges
        (admission quotas, weighted-fair dequeue, per-tenant SLO
        window, and the `tenant.<id>.*` chargeback counters); default
        None uses the session's sticky `session.tenant(...)` choice,
        else the "default" tenant."""
        from hyperspace_tpu.engine.scheduler import get_scheduler
        table, metrics = get_scheduler().collect(self, timeout=timeout,
                                                 tenant=tenant)
        return (table, metrics) if with_metrics else table

    def to_pandas(self):
        return self.collect().to_pandas()

    def count(self) -> int:
        return self.collect().num_rows

    def explain_plans(self):
        """(logical, optimized, physical) — used by plananalysis. The
        physical plan is UNFUSED: explain's contract is the operator
        tree (the Exchange/Sort elision diff); stage grouping is an
        execution detail (`engine/fusion.py`)."""
        from hyperspace_tpu.engine.executor import compile_plan
        optimized = self._optimized_plan()
        return self.plan, optimized, compile_plan(optimized,
                                                  conf=self._conf(),
                                                  fuse=False)

    def __repr__(self):
        return f"DataFrame[{', '.join(self.schema.names)}]"


class GroupedData:
    """`df.group_by(cols).agg(...)` builder.

    Aggregations are given as tuples `(func, column[, alias])` or keyword
    form `alias=(func, column)`; funcs: sum, count, min, max, avg; column
    "*" with count counts rows.

        df.group_by("k").agg(("sum", "x", "total"), cnt=("count", "*"))
    """

    def __init__(self, df: DataFrame, group_columns: Sequence[str]):
        self._df = df
        self._group_columns = list(group_columns)

    def agg(self, *specs, **named) -> DataFrame:
        parsed = []
        for spec in specs:
            if not isinstance(spec, (tuple, list)) or len(spec) not in (2, 3):
                raise HyperspaceException(
                    "Aggregation spec must be (func, column[, alias]); the "
                    "column may be a name or a value Expression.")
            func, column = spec[0], spec[1]
            if len(spec) == 3:
                alias = spec[2]
            elif isinstance(column, E.Expression):
                raise HyperspaceException(
                    "Expression aggregations need an explicit alias: "
                    "(func, expr, alias).")
            else:
                alias = f"{func}_{column}" if column != "*" else func
            parsed.append(AggSpec(func, column, alias))
        for alias, spec in named.items():
            if not isinstance(spec, (tuple, list)) or len(spec) != 2:
                raise HyperspaceException(
                    "Keyword aggregation must be alias=(func, column).")
            parsed.append(AggSpec(spec[0], spec[1], alias))
        return DataFrame(Aggregate(self._group_columns, parsed,
                                   self._df.plan), self._df.session)

    # Convenience verbs.
    def count(self) -> DataFrame:
        return self.agg(("count", "*", "count"))

    def sum(self, *columns: str) -> DataFrame:
        return self.agg(*[("sum", c) for c in columns])

    def avg(self, *columns: str) -> DataFrame:
        return self.agg(*[("avg", c) for c in columns])

    def min(self, *columns: str) -> DataFrame:
        return self.agg(*[("min", c) for c in columns])

    def max(self, *columns: str) -> DataFrame:
        return self.agg(*[("max", c) for c in columns])
