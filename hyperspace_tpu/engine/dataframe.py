"""User-facing DataFrame: a logical plan + session.

The equivalent of the Spark DataFrame surface the reference operates on.
Transformations are lazy plan builders; `collect`/`to_pandas`/`count` run
the optimizer (rewrite rules, when enabled) and execute on device.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan import expr as E
from hyperspace_tpu.plan.nodes import Filter, Join, LogicalPlan, Project
from hyperspace_tpu.plan.schema import Schema


class DataFrame:
    def __init__(self, plan: LogicalPlan, session=None):
        self.plan = plan
        self.session = session

    @property
    def schema(self) -> Schema:
        return self.plan.schema

    @property
    def columns(self) -> List[str]:
        return self.schema.names

    # -- transformations (lazy) ------------------------------------------

    def filter(self, condition: E.Expression) -> "DataFrame":
        if not isinstance(condition, E.Expression):
            raise HyperspaceException("filter() takes an Expression predicate.")
        return DataFrame(Filter(condition, self.plan), self.session)

    where = filter

    def select(self, *columns: str) -> "DataFrame":
        names = [c for col in columns
                 for c in (col if isinstance(col, (list, tuple)) else [col])]
        return DataFrame(Project(names, self.plan), self.session)

    def join(self, other: "DataFrame",
             on: Union[E.Expression, str, Sequence[str]],
             how: str = "inner") -> "DataFrame":
        if isinstance(on, str):
            on = [on]
        if isinstance(on, (list, tuple)):
            condition: Optional[E.Expression] = None
            for name in on:
                term = E.EqualTo(E.Column(name), E.Column(name))
                condition = term if condition is None else E.And(condition, term)
            if condition is None:
                raise HyperspaceException("join requires at least one key.")
        else:
            condition = on
        return DataFrame(Join(self.plan, other.plan, condition, how),
                         self.session)

    # -- actions (execute) ------------------------------------------------

    def _optimized_plan(self) -> LogicalPlan:
        if self.session is not None:
            return self.session.optimize(self.plan)
        return self.plan

    def collect(self):
        """Execute and return an Arrow table."""
        from hyperspace_tpu.engine.executor import execute_plan
        from hyperspace_tpu.io.columnar import to_arrow
        return to_arrow(execute_plan(self._optimized_plan()))

    def to_pandas(self):
        return self.collect().to_pandas()

    def count(self) -> int:
        return self.collect().num_rows

    def explain_plans(self):
        """(logical, optimized, physical) — used by plananalysis."""
        from hyperspace_tpu.engine.executor import compile_plan
        optimized = self._optimized_plan()
        return self.plan, optimized, compile_plan(optimized)

    def __repr__(self):
        return f"DataFrame[{', '.join(self.schema.names)}]"
