"""Expression -> XLA compiler.

Compiles IR expression trees (`plan/expr.py`) into jax computations over a
ColumnBatch. This replaces the reference's reliance on Spark's
WholeStageCodegen for predicate evaluation: XLA fuses the whole predicate
into one vectorized kernel over HBM-resident columns.

Null semantics follow SQL as the reference inherits them from Spark:
comparisons involving null are not-true (rows filtered out), IS [NOT] NULL
consults validity.

String comparisons against literals are translated to *code-space*
comparisons: because dictionaries are sorted (`io/columnar.py`), value
predicates become integer range tests on codes — `x > "m"` is
`code >= searchsorted(dict, "m", right)` — so string filters run at integer
scan speed on device.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import ColumnBatch, DeviceColumn
from hyperspace_tpu.plan import expr as E


def _col_and_validity(batch: ColumnBatch, name: str):
    col = batch.column(name)
    return col, col.validity


def _string_literal_compare(op: str, col: DeviceColumn, value: str, xp):
    d = col.dictionary
    left = int(np.searchsorted(d, value, side="left"))
    right = int(np.searchsorted(d, value, side="right"))
    present = left < right
    code = col.data
    if op == "eq":
        return (code == left) if present else xp.zeros(code.shape, bool)
    if op == "ne":
        return (code != left) if present else xp.ones(code.shape, bool)
    if op == "lt":
        return code < left
    if op == "le":
        return code < right
    if op == "gt":
        return code >= right
    if op == "ge":
        return code >= left
    raise HyperspaceException(f"Unsupported string comparison: {op}")


_CMP = {"eq": "__eq__", "ne": "__ne__", "lt": "__lt__", "le": "__le__",
        "gt": "__gt__", "ge": "__ge__"}


class ExpressionCompiler:
    """Compiles expressions over a batch. The array module (`xp`) follows
    the batch's residence: host batches evaluate with numpy (zero device
    round-trips — the adaptive host lane for small reads), device batches
    with jax.numpy (XLA-fused)."""

    def __init__(self, batch: ColumnBatch):
        self.batch = batch
        if batch.is_host:
            self.xp = np
        else:
            import jax.numpy as jnp
            self.xp = jnp

    # -- value expressions ------------------------------------------------

    def value(self, e: E.Expression) -> Tuple[object, Optional[object]]:
        """Compile to (array, validity|None). Strings yield their codes and
        may only feed comparisons handled in `predicate`."""
        if isinstance(e, E.Alias):
            return self.value(e.child)
        if isinstance(e, E.Column):
            col, validity = _col_and_validity(self.batch, e.name)
            return col.data, validity
        if isinstance(e, E.Literal):
            return e.value, None
        if isinstance(e, E.NullLiteral):
            n = self.batch.num_rows
            from hyperspace_tpu.io.columnar import HOST_NP_DTYPES
            zeros = self.xp.zeros(n, dtype=HOST_NP_DTYPES.get(e.dtype,
                                                              np.int64))
            return zeros, self.xp.zeros(n, dtype=bool)
        if isinstance(e, (E.Add, E.Sub, E.Mul, E.Div)):
            lv, lval = self.value(e.left)
            rv, rval = self.value(e.right)
            # Widen BEFORE computing (infer_dtype's rule: ints accumulate
            # as int64, any float promotes to float64, Div is float64) —
            # narrow int32/int16 operands must not wrap at their own
            # width.
            lv = self.xp.asarray(lv)
            rv = self.xp.asarray(rv)
            floats = (type(e).op == "div"
                      or lv.dtype.kind == "f" or rv.dtype.kind == "f")
            wide = self.xp.float64 if floats else self.xp.int64
            ops = {"add": self.xp.add, "sub": self.xp.subtract,
                   "mul": self.xp.multiply, "div": self.xp.divide}
            out = ops[type(e).op](lv.astype(wide), rv.astype(wide))
            return out, self._merge_validity(lval, rval)
        if isinstance(e, E.CaseWhen):
            return self._case_when(e)
        if isinstance(e, E.Floor):
            v, valid = self.value(e.child)
            arr = self.xp.asarray(v)
            return self.xp.floor(arr.astype(self.xp.float64)).astype(
                self.xp.int64), valid
        if isinstance(e, E.ScalarSubquery):
            # Resolved by the executor's subquery phase; compiles as the
            # value it produced (NULL for an empty subquery).
            return self.value(e.literal())
        raise HyperspaceException(f"Unsupported value expression: {e!r}")

    def _case_when(self, e: "E.CaseWhen"):
        """Numeric/bool CASE: one fused chain of `where`s, evaluated last
        branch first so the FIRST matching WHEN wins (SQL). A condition
        that is NULL does not match (Kleene not-true). Rows no branch
        matches take the ELSE value, or NULL when there is none — the
        conditional-aggregation idiom (`sum(CASE WHEN ... THEN x END)`)
        relies on sum/avg skipping those NULLs."""
        from hyperspace_tpu.plan.expr import infer_dtype

        xp = self.xp
        n = self.batch.num_rows
        out_dtype = infer_dtype(e, self.batch.schema)
        if out_dtype == "string":
            raise HyperspaceException(
                "String-valued CASE is not supported yet.")
        wide = {"bool": xp.bool_, "int64": xp.int64,
                "float64": xp.float64}[out_dtype]

        def as_wide(v):
            arr = xp.asarray(v)
            if arr.ndim == 0:
                arr = xp.full(n, arr)
            return arr.astype(wide)

        def as_mask(v):
            if v is None:
                return xp.ones(n, dtype=bool)
            arr = xp.asarray(v)
            return xp.full(n, arr) if arr.ndim == 0 else arr

        if e.otherwise_value is not None:
            data, validity = self.value(e.otherwise_value)
            data, validity = as_wide(data), as_mask(validity)
        else:
            data = xp.zeros(n, dtype=wide)
            validity = xp.zeros(n, dtype=bool)
        for cond, val in reversed(e.branches):
            t, _known = self.predicate3(cond)
            v_data, v_valid = self.value(val)
            data = xp.where(t, as_wide(v_data), data)
            validity = xp.where(t, as_mask(v_valid), validity)
        # all-valid result -> drop the mask (the common no-null fast path)
        if e.otherwise_value is not None:
            host_valid = validity if isinstance(validity, np.ndarray) else None
            if host_valid is not None and host_valid.all():
                return data, None
        return data, validity

    def string_column(self, e: E.Expression) -> Optional[DeviceColumn]:
        """Evaluate a string-VALUED expression to a dict-encoded column
        (sorted dictionary, so code-space comparisons stay valid), or None
        when `e` is not string-valued. Substr transforms the DICTIONARY —
        O(dictionary), not O(rows) — then re-sorts and remaps codes."""
        if isinstance(e, E.Alias):
            return self.string_column(e.child)
        if isinstance(e, E.Column):
            col = self.batch.column(e.name)
            return col if col.is_string else None
        if isinstance(e, E.NullLiteral) and e.dtype == "string":
            # All-NULL string column (ROLLUP's coarser granularities).
            return self._const_string_column("", valid=False)
        if isinstance(e, E.Literal) and isinstance(e.value, str):
            # Constant string column (q5/q33/q56-style channel tags): a
            # one-entry dictionary with all codes 0.
            return self._const_string_column(e.value, valid=True)
        if isinstance(e, E.Substr):
            child = self.string_column(e.child)
            if child is None:
                raise HyperspaceException(
                    f"SUBSTR over non-string expression: {e.child!r}")
            return self._substr(child, e.start, e.length)
        return None

    def _const_string_column(self, value: str, valid: bool) -> DeviceColumn:
        """One-entry-dictionary string column: every row carries `value`
        (valid=True) or NULL (valid=False)."""
        from hyperspace_tpu.io.columnar import _split_hashes, _string_hash64

        d = np.array([value])
        n = self.batch.num_rows
        host = self.xp is np
        return DeviceColumn(
            self.xp.zeros(n, dtype=np.int32), "string",
            None if valid else self.xp.zeros(n, dtype=bool), d,
            _split_hashes(_string_hash64(d), device=not host))

    def _substr(self, col: DeviceColumn, start: int,
                length: int) -> DeviceColumn:
        from hyperspace_tpu.io.columnar import (_split_hashes,
                                                _string_hash64)
        d = col.dictionary
        sliced = np.array([v[start - 1:start - 1 + length] for v in d])
        new_dict, inverse = np.unique(sliced, return_inverse=True)
        remap = inverse.astype(np.int32)
        if col.is_host:
            codes = remap[np.asarray(col.data)]
        else:
            import jax.numpy as jnp
            codes = jnp.take(jnp.asarray(remap), col.data)
        hashes = _split_hashes(_string_hash64(new_dict),
                               device=not col.is_host)
        return DeviceColumn(codes, "string", col.validity, new_dict, hashes)

    def value_column(self, e: E.Expression, out_dtype: str) -> DeviceColumn:
        """Evaluate a value expression to a full DeviceColumn of the given
        logical dtype (the projection entry point)."""
        from hyperspace_tpu.io.columnar import HOST_NP_DTYPES
        s = self.string_column(e)
        if s is not None:
            if out_dtype != "string":
                raise HyperspaceException(
                    f"Expression {e!r} is string-valued; expected "
                    f"{out_dtype}.")
            return s
        data, validity = self.value(e)
        np_dtype = HOST_NP_DTYPES[out_dtype]
        data = self.xp.asarray(data)
        if data.ndim == 0:  # literal broadcast
            data = self.xp.full(self.batch.num_rows, data)
        return DeviceColumn(data.astype(np_dtype), out_dtype,
                            validity=validity)

    @staticmethod
    def _merge_validity(a, b):
        if a is None:
            return b
        if b is None:
            return a
        return a & b

    def _column_of(self, e: E.Expression) -> Optional[DeviceColumn]:
        if isinstance(e, E.Column):
            return self.batch.column(e.name)
        return None

    # -- predicates -------------------------------------------------------
    #
    # SQL three-valued (Kleene) logic: each predicate compiles to a pair
    # (true_mask, known) where `true_mask` marks rows DEFINITELY true
    # (so true_mask implies known; `known & ~true_mask` is definitely
    # false; `~known` is NULL/unknown). `known is None` means all-known —
    # the common null-free fast path stays two fused vector ops per node.
    # NOT flips definite truth within the known rows, so NULL stays NULL
    # and a filter never passes it (the reference inherits exactly this
    # from Spark; previously `~mask` wrongly passed null rows).

    def predicate(self, e: E.Expression):
        """Compile to a bool mask (True = row DEFINITELY passes; SQL's
        not-true rows, including NULLs, are False)."""
        mask, _known = self.predicate3(e)
        return mask

    def predicate3(self, e: E.Expression):
        """Compile to (true_mask, known); known=None means all rows known."""
        xp = self.xp
        n = self.batch.num_rows
        if isinstance(e, E.And):
            lt, lk = self.predicate3(e.left)
            rt, rk = self.predicate3(e.right)
            mask = lt & rt
            if lk is None and rk is None:
                return mask, None
            # Known iff both known, or either side is definitely false.
            lk_ = xp.ones(n, bool) if lk is None else lk
            rk_ = xp.ones(n, bool) if rk is None else rk
            return mask, (lk_ & rk_) | (lk_ & ~lt) | (rk_ & ~rt)
        if isinstance(e, E.Or):
            return self._or3(self.predicate3(e.left),
                             self.predicate3(e.right), n, xp)
        if isinstance(e, E.Not):
            t, k = self.predicate3(e.child)
            if k is None:
                return ~t, None
            return k & ~t, k
        if isinstance(e, E.IsNull):
            col = self._column_of(e.child)
            if col is None:
                raise HyperspaceException("IS NULL requires a column.")
            if col.validity is None:
                return xp.zeros(n, bool), None
            return ~col.validity, None
        if isinstance(e, E.IsNotNull):
            col = self._column_of(e.child)
            if col is None:
                raise HyperspaceException("IS NOT NULL requires a column.")
            if col.validity is None:
                return xp.ones(n, bool), None
            return col.validity, None
        if isinstance(e, E.In):
            # Set-membership fast path: integer column IN (int literals...)
            # is ONE vectorized isin instead of an O(values) fold of
            # EqualTo masks — the hybrid-scan lineage exclusion can carry
            # hundreds of deleted-file ids. Kleene semantics match the
            # fold exactly for integers: a NULL row is unknown, everything
            # else is definitely known.
            col = self._column_of(e.child)
            int_vals = [v.value for v in e.values
                        if isinstance(v, E.Literal)
                        and type(v.value) is int]
            if (col is not None and e.values
                    and len(int_vals) == len(e.values)
                    and col.dtype in ("int8", "int16", "int32", "int64")):
                member = xp.isin(xp.asarray(col.data),
                                 xp.asarray(int_vals, dtype=np.int64))
                if col.validity is None:
                    return member, None
                return member & col.validity, col.validity
            folded = None
            for v in e.values:
                term = self.predicate3(E.EqualTo(e.child, v))
                folded = term if folded is None else (
                    self._or3(folded, term, n, xp))
            if folded is None:
                return xp.zeros(n, bool), None
            return folded
        if isinstance(e, E.Like):
            # LIKE in DICTIONARY space. Device lane: the per-dictionary
            # membership bitmask comes from the segment cache
            # (`parallel/spmd.string_like_mask` — host regex paid once
            # per (dictionary, pattern), mask resident in HBM), so the
            # row test is ONE take and a warm repeat is link-free
            # instead of re-running the regex and shipping a fresh
            # code list every evaluation. Host lane: numpy end to end,
            # no device round-trip (the adaptive small-read path).
            import re as _re
            s = self.string_column(e.child)
            if s is None:
                raise HyperspaceException(
                    f"LIKE requires a string operand: {e!r}")
            if xp is not np and len(s.dictionary):
                from hyperspace_tpu.parallel.spmd import string_like_mask
                mask_d = string_like_mask(s, e.regex())
                member = xp.take(xp.asarray(mask_d),
                                 xp.clip(xp.asarray(s.data), 0,
                                         len(s.dictionary) - 1))
            else:
                rx = _re.compile(e.regex(), _re.DOTALL)
                d = np.asarray(s.dictionary)
                codes = np.nonzero([rx.fullmatch(str(v)) is not None
                                    for v in d])[0]
                member = xp.isin(xp.asarray(s.data),
                                 xp.asarray(codes.astype(np.int32)))
            if s.validity is None:
                return member, None
            return member & s.validity, s.validity
        if isinstance(e, (E.EqualTo, E.NotEqualTo, E.LessThan,
                          E.LessThanOrEqual, E.GreaterThan,
                          E.GreaterThanOrEqual)):
            return self._comparison(e)
        if isinstance(e, E.Literal):
            if isinstance(e.value, bool):
                return xp.full(n, e.value, dtype=bool), None
            raise HyperspaceException(f"Non-boolean literal predicate: {e!r}")
        raise HyperspaceException(f"Unsupported predicate: {e!r}")

    @staticmethod
    def _or3(a, b, n, xp):
        """Kleene OR over (true_mask, known) pairs: known iff both known,
        or either side is definitely true."""
        at, ak = a
        bt, bk = b
        mask = at | bt
        if ak is None and bk is None:
            return mask, None
        ak_ = xp.ones(n, bool) if ak is None else ak
        bk_ = xp.ones(n, bool) if bk is None else bk
        return mask, (ak_ & bk_) | mask

    def _comparison(self, e):
        # Resolved scalar subqueries compare as the literal they produced
        # (so the string code-space fast path still applies).
        left = (e.left.literal() if isinstance(e.left, E.ScalarSubquery)
                else e.left)
        right = (e.right.literal() if isinstance(e.right, E.ScalarSubquery)
                 else e.right)
        if left is not e.left or right is not e.right:
            e = type(e)(left, right)
        op = type(e).op
        ls = (None if isinstance(e.left, E.Literal)
              else self.string_column(e.left))
        rs = (None if isinstance(e.right, E.Literal)
              else self.string_column(e.right))
        # string expression vs string literal -> code-space range test
        if ls is not None and isinstance(e.right, E.Literal):
            mask = _string_literal_compare(op, ls, str(e.right.value),
                                           self.xp)
            return self._with_validity(mask, ls.validity, None)
        if rs is not None and isinstance(e.left, E.Literal):
            flipped = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                       "eq": "eq", "ne": "ne"}[op]
            mask = _string_literal_compare(flipped, rs,
                                           str(e.left.value), self.xp)
            return self._with_validity(mask, rs.validity, None)
        if ls is not None and rs is not None:
            # String col-to-col compare: remap both onto one merged sorted
            # dictionary, then compare codes (order-preserving).
            lc, rc = self._unified_codes(ls, rs)
            mask = getattr(self.xp.asarray(lc), _CMP[op])(rc)
            return self._with_validity(mask, ls.validity, rs.validity)
        if ls is not None or rs is not None:
            raise HyperspaceException(
                f"Cannot compare a string expression with a non-string "
                f"operand: {e!r}")
        lv, lval = self.value(e.left)
        rv, rval = self.value(e.right)
        mask = getattr(self.xp.asarray(lv), _CMP[op])(rv)
        return self._with_validity(mask, lval, rval)

    def _unified_codes(self, a: DeviceColumn, b: DeviceColumn):
        from hyperspace_tpu.io.columnar import _merged_dictionary
        host = self.xp is np
        _, (ra, rb), _ = _merged_dictionary([a.dictionary, b.dictionary],
                                            device=not host)
        if host:
            return ra[np.asarray(a.data)], rb[np.asarray(b.data)]
        import jax.numpy as jnp
        return jnp.take(ra, a.data), jnp.take(rb, b.data)

    @staticmethod
    def _with_validity(mask, lval, rval):
        """(raw compare, operand validity) -> (true_mask, known)."""
        validity = ExpressionCompiler._merge_validity(lval, rval)
        if validity is None:
            return mask, None
        return mask & validity, validity


def compile_predicate(expression: E.Expression, batch: ColumnBatch):
    return ExpressionCompiler(batch).predicate(expression)


def apply_filter(batch: ColumnBatch, expression: E.Expression) -> ColumnBatch:
    """Filter a batch: fused mask eval + one compaction gather. On the
    device lane the row count is the single host sync (it sizes the
    result); on the host lane everything is numpy — no device traffic.

    Compile accounting: the expressions compiled here carry no jit
    entry point of their own — host batches evaluate eagerly in numpy,
    and device batches either run op-by-op (dispatch cost, no trace) or
    inside `engine/fusion.py`'s instrumented stage executable, where
    the `compile.*` counters and retrace events are recorded."""
    from hyperspace_tpu import telemetry

    mask = compile_predicate(expression, batch)
    if isinstance(mask, np.ndarray):
        return batch.take(np.nonzero(mask)[0].astype(np.int32))
    import jax.numpy as jnp

    count = int(jnp.sum(mask))  # host sync — sizes the output
    # The sync is a true span boundary: input + mask + output are all
    # device-resident here — fold an HBM sample into the watermark.
    telemetry.memory.maybe_sample()
    (indices,) = jnp.nonzero(mask, size=count, fill_value=0)
    return batch.take(indices)
