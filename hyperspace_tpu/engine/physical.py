"""Physical plan: executable operator tree.

The reference's observable win is Spark's physical planner *not* inserting
ShuffleExchange/Sort under a SortMergeJoin when both sides are bucketed
(`index/rules/JoinIndexRule.scala:41-43`; verified via operator-occurrence
diff, `plananalysis/PhysicalOperatorAnalyzer.scala:44-57`). This framework
owns that planning step: Join compiles to SortMergeJoinExec, with
ExchangeExec (hash repartition) + SortExec inserted only when a side is not
already bucketed+sorted on the join keys — so explain() can show the same
Exchange/Sort elision, and execution actually skips the work.
"""

from __future__ import annotations

import functools

from typing import List, Optional, Sequence, Set, Tuple

from hyperspace_tpu import telemetry
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io import columnar, parquet, segcache
from hyperspace_tpu.plan import expr as E
from hyperspace_tpu.plan.nodes import (Aggregate, BucketSpec, Except, Filter,
                                       Join, Limit, LogicalPlan, Project,
                                       Scan, SetOp, Sort, Union, Window)
from hyperspace_tpu.plan.schema import Schema


def _batch_rows(out) -> Optional[int]:
    """Output row count of an execute/execute_bucketed result, without
    forcing any device sync (ColumnBatch.num_rows is a static shape)."""
    if isinstance(out, columnar.ColumnBatch):
        return out.num_rows
    if isinstance(out, tuple) and out \
            and isinstance(out[0], columnar.ColumnBatch):
        return out[0].num_rows
    return None


def _instrument(fn, bucketed: bool):
    """Wrap an execute/execute_bucketed implementation with the telemetry
    operator hook: a per-query operator record (active recorder) and a
    trace span on the executing thread (active tracer). With neither,
    the cost is one ContextVar read + one global read + None checks.
    Applied automatically to every PhysicalNode subclass by
    `PhysicalNode.__init_subclass__`, so a new operator can never
    silently execute unmetered (`scripts/check_metrics_coverage.py`
    enforces the marker repo-wide)."""

    @functools.wraps(fn)
    def wrapper(self, arg=None):
        # Cooperative-cancellation checkpoints bracket every operator
        # (one contextvar read + None check each when no deadline is
        # active — same always-off contract as the recorder hooks).
        # BOTH ends matter in a pull-based executor: every operator
        # STARTS during the initial tree descent (microseconds), so the
        # entry check alone would see the whole plan before any real
        # work ran; the finish check below — after the operator's
        # actual compute, on the way up — is what stops a cancelled
        # query between operators.
        phase = "scan" if self.name == "Scan" else "operator"
        telemetry.check_deadline(phase)
        rec = telemetry.current()
        tr = telemetry.tracer()
        if rec is None and tr is None:
            out = fn(self, arg)
            telemetry.check_deadline(phase)
            return out
        op = None
        if rec is not None:
            op = rec.start_operator(self.name, self, bucketed=bucketed)
            if bucketed:
                op.detail["num_buckets"] = arg
            elif arg is not None:
                op.detail["bucket"] = arg
        ts = tr.now_us() if tr is not None else 0.0
        try:
            out = fn(self, arg)
        except BaseException as exc:
            if tr is not None:
                tr.complete(self.name, "operator", ts, tr.now_us() - ts,
                            args={"error": repr(exc)})
            if op is not None:
                rec.finish_operator(op, error=repr(exc))
            raise
        if tr is not None:
            rows = _batch_rows(out)
            tr.complete(self.name, "operator", ts, tr.now_us() - ts,
                        args=(None if rows is None else {"rows": rows}))
        if op is not None:
            rec.finish_operator(op, rows_out=_batch_rows(out))
        # Operator-span boundary: fold a device-memory sample into the
        # per-query HBM watermark (throttled; after the span close so
        # the accounting walk never inflates the operator's wall).
        telemetry.memory.maybe_sample()
        # The mid-query cancellation point (see entry comment): the
        # operator's record is already closed cleanly — the QUERY
        # aborts before the parent consumes the result.
        telemetry.check_deadline(phase)
        return out

    wrapper.__telemetry_instrumented__ = True
    return wrapper


class PhysicalNode:
    name: str = "Physical"

    def __init_subclass__(cls, **kwargs):
        # EVERY subclass's execute/execute_bucketed emits an operator
        # metrics record; opting out is not supported by design (the
        # metrics-coverage lint would flag it).
        super().__init_subclass__(**kwargs)
        for attr, bucketed in (("execute", False),
                               ("execute_bucketed", True)):
            fn = cls.__dict__.get(attr)
            if fn is not None and callable(fn) \
                    and not getattr(fn, "__telemetry_instrumented__",
                                    False):
                setattr(cls, attr, _instrument(fn, bucketed))

    @property
    def children(self) -> List["PhysicalNode"]:
        return []

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        raise NotImplementedError

    def execute_sharded(self, num_buckets: int, mesh, align_plan=None):
        """Born-sharded execution (`parallel/spmd.py`): produce this
        node's output as a device-resident `ShardedBatch` whose shard s
        holds bucket range s, or None when the shape does not qualify
        (unbucketed source, host-lane row counts). None is a ROUTING
        answer, not an error — callers fall back to the single-chip
        paths. Hot-bucket skew no longer declines: the scan splits the
        hot range into virtual sub-shards (`spmd.subshard_plan`) and
        stamps the split onto the batch; `align_plan` asks this side to
        read ALIGNED to the other side's split (intersected buckets
        replicated per covering shard). Default: not shardable."""
        return None

    def execute_bucketed(self, num_buckets: int):
        """Produce (batch concat'd in bucket order, per-bucket lengths) for
        the batched bucketed join. Only meaningful on chains over a
        bucketed scan."""
        raise HyperspaceException(
            f"{type(self).__name__} does not support bucketed execution.")

    def simple_string(self) -> str:
        return self.name

    def tree_string(self, depth: int = 0) -> str:
        lines = [("  " * depth) + ("+- " if depth else "") + self.simple_string()]
        for c in self.children:
            lines.append(c.tree_string(depth + 1))
        return "\n".join(lines)

    def collect(self) -> List["PhysicalNode"]:
        out = [self]
        for c in self.children:
            out.extend(c.collect())
        return out


def _empty_batch(schema: Schema) -> columnar.ColumnBatch:
    import pyarrow as pa
    return columnar.from_arrow(
        pa.table({f.name: pa.array([], type=t.type)
                  for f, t in zip(schema.fields, schema.to_arrow())}), schema)


class ScanExec(PhysicalNode):
    name = "Scan"

    def __init__(self, scan: Scan, columns: Sequence[str],
                 allowed_buckets: Optional[Set[int]] = None, conf=None,
                 shared_members: int = 0):
        self.scan = scan
        self.columns = list(columns)
        self.out_schema = scan.schema.select(columns)
        self.conf = conf
        # >0: this scan is the SHARED read of an inter-query batch
        # cohort (`engine/batcher.py`) — one read serving that many
        # concurrent queries. Threaded to the segment cache's shared-
        # read counters and onto the operator record so the differ can
        # attribute amortized reads.
        self.shared_members = shared_members
        # Bucket pruning: when a filter above constrains every bucket
        # column to literal values, only these buckets can contain matches
        # (set by the planner, `_prune_buckets`). The index read then
        # touches 1/num_buckets of the files per point value — the engine
        # analog of partition pruning, and the device-path win the bucketed
        # layout buys beyond the reference (whose filter swap stays
        # unbucketed purely for Spark scan parallelism,
        # `index/rules/FilterIndexRule.scala:112-120`).
        self.allowed_buckets = allowed_buckets

    def _budget(self, device: bool):
        """Session-conf cache budget for this scan's lane (None = the
        process-wide env default). The device lane is the HBM segment
        cache (`spark.hyperspace.cache.segments.bytes`)."""
        if self.conf is None:
            return None
        return (self.conf.segment_cache_bytes if device
                else self.conf.read_cache_bytes)

    def _read_device(self, files: List[str], bucket=None,
                     bucketed: bool = False) -> columnar.ColumnBatch:
        """Device-lane read THROUGH the HBM segment cache: a warm hit
        is link-free (no parquet decode, no H2D). Rule-selected index
        scans key by (index root, committed version, bucket selector);
        unversioned scans fall back to stamp validation inside the
        cache."""
        ref = segcache.segment_ref_for_scan(
            self.scan, bucket=bucket,
            allowed_buckets=self.allowed_buckets, bucketed=bucketed)
        return segcache.read_segment(files, self.columns,
                                     self.out_schema, ref=ref,
                                     conf=self.conf,
                                     budget=self._budget(device=True),
                                     shared_members=self.shared_members)

    def _annotate_read(self, files: List[str], host: bool,
                       files_total: Optional[int] = None) -> None:
        """Index-usage detail on this scan's operator record: lane, files
        scanned vs total, buckets scanned vs total. `files_total` is
        passed by the caller FROM THE LISTING IT ALREADY MADE — this
        hook performs no IO of its own (telemetry must not add a listing
        to the scan hot path)."""
        if telemetry.current() is None:
            return
        from hyperspace_tpu.plan import footprint as _footprint
        detail = {"lane": "host" if host else "device",
                  "files_scanned": len(files),
                  # Raw on-disk bytes behind this read, via the stamp-
                  # validated size cache admission control already
                  # populated this collect (warm: no extra listing, one
                  # cached stat per file). Feeds the regression differ
                  # and the index advisor's per-relation scan-bytes
                  # signal.
                  "bytes_scanned": _footprint.file_sizes_total(files),
                  "roots": list(self.scan.root_paths)}
        if self.shared_members:
            detail["shared_members"] = self.shared_members
        spec = self.scan.bucket_spec
        if spec is not None:
            detail["buckets_total"] = spec.num_buckets
            detail["buckets_scanned"] = (len(self.allowed_buckets)
                                         if self.allowed_buckets is not None
                                         else spec.num_buckets)
            if self.allowed_buckets is not None \
                    and len(self.allowed_buckets) <= 128:
                # Per-bucket access identity for the replica router's
                # hot-range miner (`parallel/replica.py`) — only when
                # pruning narrowed the read (full-range scans carry no
                # hotness signal) and small enough to ride the ring.
                detail["bucket_ids"] = sorted(self.allowed_buckets)
        if files_total is not None:
            detail["files_total"] = files_total
        telemetry.annotate(**detail)

    def simple_string(self) -> str:
        bucket = (f", buckets={self.scan.bucket_spec.num_buckets}"
                  if self.scan.bucket_spec else "")
        pruned = ""
        if self.allowed_buckets is not None and self.scan.bucket_spec:
            pruned = (f", prunedBuckets={len(self.allowed_buckets)}"
                      f"/{self.scan.bucket_spec.num_buckets}")
        return (f"Scan parquet [{', '.join(self.columns)}] "
                f"{self.scan.root_paths}{bucket}{pruned}")

    def _guard_index_read(self, fn):
        """Run one read attempt with the graceful-degradation contract:
        for a RULE-SELECTED index scan (scan.index_name set), data that
        turns out missing or unreadable — root dir gone, files corrupt,
        storage failing past the retry policy — raises the typed
        IndexDataUnavailableError that `DataFrame.collect` converts into
        a fallback to the source plan. Source-data scans keep their raw
        errors: there is nothing to degrade to. HyperspaceExceptions
        (planner contract violations) and BaseExceptions (injected
        crashes) pass through untouched."""
        from hyperspace_tpu.exceptions import IndexDataUnavailableError

        name = self.scan.index_name
        if name is None:
            return fn()
        from hyperspace_tpu.utils import file_utils
        missing = [r for r in self.scan.root_paths
                   if not file_utils.is_dir(r)
                   and not file_utils.is_file(r)]
        if missing:
            raise IndexDataUnavailableError(
                f"Index {name!r} data root(s) missing: "
                f"{', '.join(missing)}", index_name=name)
        try:
            return fn()
        except HyperspaceException:
            raise
        except Exception as exc:
            raise IndexDataUnavailableError(
                f"Index {name!r} data unreadable: {exc!r}",
                index_name=name) from exc

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        if self.scan.index_name is not None \
                and self.scan.pinned_version is not None:
            # Snapshot-pinned index read: hold the version directories
            # pinned for the read's duration so a concurrent vacuum
            # defers its delete instead of yanking files mid-read
            # (index/pins.py). If a delete wins anyway, the guard below
            # still converts the failure into the typed fallback.
            from hyperspace_tpu.index import pins
            with pins.pinned(self.scan.root_paths):
                return self._guard_index_read(lambda: self._execute(bucket))
        return self._guard_index_read(lambda: self._execute(bucket))

    def _per_bucket_files(self) -> dict:
        """{bucket id: files} for this scan. A plan-time-PINNED scan
        (snapshot isolation: `Rule.index_scan` resolved the committed
        version's listing once) and an explicit-file-list scan derive
        the map from that frozen listing — execution performs NO
        directory re-listing, so a writer racing the query between plan
        and scan cannot change what is read. Unpinned scans keep the
        live per-root listing."""
        if self.scan.pinned_version is not None \
                or self.scan._explicit_files:
            return parquet.bucket_map(self.scan.files())
        out: dict = {}
        for root in self.scan.root_paths:
            for b, fs in parquet.bucket_files(root).items():
                out.setdefault(b, []).extend(fs)
        return out

    def _execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        files_total: Optional[int] = None
        if bucket is not None:
            if self.scan.bucket_spec is None:
                raise HyperspaceException("Bucket read on unbucketed scan.")
            files: List[str] = self._per_bucket_files().get(bucket, [])
        elif self.allowed_buckets is not None and self.scan.bucket_spec:
            files = []
            per_bucket = self._per_bucket_files()
            files_total = sum(len(v) for v in per_bucket.values())
            for b in sorted(self.allowed_buckets):
                files.extend(per_bucket.get(b, []))
        else:
            files = self.scan.files()
            files_total = len(files)
        if not files:
            return _empty_batch(self.out_schema)
        # Adaptive lane: small reads (e.g. a pruned point-filter bucket)
        # stay in host memory — a device round-trip (~100 ms tunneled)
        # would dwarf the work. Downstream jnp operators promote host
        # batches to the device transparently when they need it. Host
        # batches come through the stamped decoded-batch cache.
        from hyperspace_tpu.constants import MIN_DEVICE_ROWS_DEFAULT
        min_dev = (self.conf.min_device_rows if self.conf is not None
                   else MIN_DEVICE_ROWS_DEFAULT)
        # Footer row counts only gate the lane choice, which per-bucket
        # reads don't make — keep the metadata pass off that hot path.
        host = (bucket is None
                and sum(parquet.file_row_counts(files)) < min_dev)
        self._annotate_read(files, host, files_total)
        if host:
            batch = parquet.read_host_batch(files, self.columns,
                                            self.out_schema,
                                            budget=self._budget(device=False))
        else:
            batch = self._read_device(files, bucket=bucket)
        if bucket is not None and len(files) > 1:
            # Multiple sorted runs in one bucket (incremental deltas): the
            # concat is not globally sorted — restore order on device.
            from hyperspace_tpu.ops.sort import sort_batch
            sort_cols = [c for c in self.scan.bucket_spec.sort_columns
                         if self.out_schema.contains(c)]
            if sort_cols:
                batch = sort_batch(batch, sort_cols)
        return batch

    def execute_bucketed(self, num_buckets: int):
        return self._guard_index_read(
            lambda: self._execute_bucketed(num_buckets))

    def execute_sharded(self, num_buckets: int, mesh, align_plan=None):
        return self._guard_index_read(
            lambda: self._execute_sharded(num_buckets, mesh,
                                          align_plan=align_plan))

    def _execute_sharded(self, num_buckets: int, mesh, align_plan=None):
        """Born-sharded bucket-range read: shard s's bucket range decodes
        and places onto DEVICE s through the per-device segment cache
        (per-bucket fill granularity), so each device's HBM holds only
        its range and a warm read is link-free per device. Returns a
        ShardedBatch, or None when the read belongs on another lane.

        Hot-bucket skew (`pad_blowup`) splits the hot range into
        VIRTUAL SUB-SHARDS instead of declining: equal row segments
        whose cuts may fall inside a hot bucket (`spmd.plan_skew_read`),
        stamped as `split_plan` so the join reads its other side
        aligned. `align_plan` IS that other side's read: each shard
        holds every row of the buckets intersecting the plan's segment
        (split buckets replicated per covering shard)."""
        import numpy as np

        from hyperspace_tpu.parallel import spmd
        from hyperspace_tpu.parallel.mesh import (bucket_ranges,
                                                  total_shards)

        if self.scan.bucket_spec is None:
            return None
        if not spmd.supports_sharded(self.out_schema):
            return None  # a dtype outside the host-lane map (defensive)
        per_bucket: dict = {}
        files_total = 0
        for b, files in self._per_bucket_files().items():
            files_total += len(files)
            if (self.allowed_buckets is not None
                    and b not in self.allowed_buckets):
                continue
            per_bucket.setdefault(b, []).extend(files)
        ordered = [(b, f) for b in range(num_buckets)
                   for f in per_bucket.get(b, [])]
        lengths = np.zeros(num_buckets, dtype=np.int64)
        counts = parquet.file_row_counts([f for _, f in ordered])
        for (b, _), c in zip(ordered, counts):
            lengths[b] += c
        total = int(lengths.sum())
        if total == 0:
            return None
        mode = self.conf.distribution if self.conf is not None else "auto"
        if mode == "auto":
            from hyperspace_tpu.constants import (
                DISTRIBUTION_MIN_ROWS_DEFAULT, MIN_DEVICE_ROWS_DEFAULT)
            min_dev = (self.conf.min_device_rows if self.conf is not None
                       else MIN_DEVICE_ROWS_DEFAULT)
            min_dist = (self.conf.distribution_min_rows
                        if self.conf is not None
                        else DISTRIBUTION_MIN_ROWS_DEFAULT)
            if total < max(min_dev, min_dist):
                return None  # host / single-chip lane territory
        n_shards = total_shards(mesh)
        ref = segcache.segment_ref_for_scan(
            self.scan, allowed_buckets=self.allowed_buckets,
            bucketed=True)
        budget = self._budget(device=True)
        self._annotate_read([f for _, f in ordered], host=False,
                            files_total=files_total)
        if align_plan is not None:
            # The other side of a sub-shard join: intersected buckets
            # replicated per covering shard. Decline when replication
            # would itself blow the padded layout (both sides hot).
            if (align_plan.num_buckets != num_buckets
                    or align_plan.n_shards != n_shards):
                return None
            specs = spmd.plan_aligned_read(per_bucket, lengths,
                                           align_plan)
            C = max(1, max(spec[2] for spec in specs))
            if C * n_shards > max(spmd.PAD_BLOWUP_FACTOR * total,
                                  1 << 16):
                return None
            return spmd.read_sharded([], lengths, self.columns,
                                     self.scan.schema, mesh,
                                     base_ref=ref, conf=self.conf,
                                     budget=budget, shard_specs=specs)
        split_plan = None
        shard_specs = None
        per_shard_files = None
        if spmd.pad_blowup(lengths, n_shards):
            # Hot-bucket skew: whole-bucket ownership would pad the
            # [S*C] layout past the blow-up bar — split the hot range
            # into row-balanced virtual sub-shards and stay on the
            # SPMD lane (the join reads its other side aligned).
            split_plan, shard_specs = spmd.plan_skew_read(
                per_bucket, lengths, n_shards)
            telemetry.get_registry().counter(
                "mesh.spmd.subshard_reads").inc()
            telemetry.annotate(subsharded=True)
        else:
            per_shard_files = [[f for b in range(lo, hi)
                                for f in per_bucket.get(b, [])]
                               for lo, hi in bucket_ranges(num_buckets,
                                                           n_shards)]
        return spmd.read_sharded(per_shard_files or [], lengths,
                                 self.columns, self.scan.schema, mesh,
                                 base_ref=ref, conf=self.conf,
                                 budget=budget,
                                 shard_specs=shard_specs,
                                 split_plan=split_plan)

    def _execute_bucketed(self, num_buckets: int):
        """Read all bucket files in bucket order; lengths come from parquet
        metadata — no device work. (The batched join sorts per-bucket ids
        itself, so multi-run buckets need no pre-sort here.)"""
        import numpy as np

        if self.scan.bucket_spec is None:
            raise HyperspaceException("Bucketed read on unbucketed scan.")
        per_bucket = {}
        files_total = 0
        for b, files in self._per_bucket_files().items():
            files_total += len(files)
            if (self.allowed_buckets is not None
                    and b not in self.allowed_buckets):
                # Pruned by the filter above: no row in this bucket can
                # survive it, so an empty bucket is equivalent.
                continue
            per_bucket.setdefault(b, []).extend(files)
        # ONE ordered concurrent read of all bucket files; per-bucket
        # lengths come from parquet footers (no data read).
        ordered = [(b, f) for b in range(num_buckets)
                   for f in per_bucket.get(b, [])]
        lengths = np.zeros(num_buckets, dtype=np.int64)
        if not ordered:
            return _empty_batch(self.out_schema), lengths
        counts = parquet.file_row_counts([f for _, f in ordered])
        for (b, _), c in zip(ordered, counts):
            lengths[b] += c
        files = [f for _, f in ordered]
        from hyperspace_tpu.constants import MIN_DEVICE_ROWS_DEFAULT
        min_dev = (self.conf.min_device_rows if self.conf is not None
                   else MIN_DEVICE_ROWS_DEFAULT)
        host = int(lengths.sum()) < min_dev
        self._annotate_read(files, host, files_total)
        if host:
            return parquet.read_host_batch(
                files, self.columns, self.out_schema,
                budget=self._budget(device=False)), lengths
        return self._read_device(files, bucketed=True), lengths


class FilterExec(PhysicalNode):
    name = "Filter"

    def __init__(self, condition: E.Expression, child: PhysicalNode,
                 conf=None):
        self.condition = condition
        self.child = child
        self.conf = conf

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        return f"Filter ({self.condition!r})"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        from hyperspace_tpu.engine.compiler import apply_filter
        from hyperspace_tpu.parallel.context import should_distribute
        batch = self.child.execute(bucket)
        if batch.num_rows == 0:
            return batch
        mesh = should_distribute(self.conf, batch.num_rows,
                                 host_batch=batch.is_host)
        if mesh is not None:
            from hyperspace_tpu.parallel.scan import distributed_filter
            return distributed_filter(batch, self.condition, mesh)
        return apply_filter(batch, self.condition)

    def execute_bucketed(self, num_buckets: int):
        """Filter preserves bucket grouping: the compaction gather is
        stable-ascending, so surviving rows stay in bucket order; new
        per-bucket lengths are segment sums of the mask."""
        import jax.numpy as jnp
        import numpy as np
        from hyperspace_tpu.engine.compiler import compile_predicate

        batch, lengths = self.child.execute_bucketed(num_buckets)
        if batch.num_rows == 0:
            return batch, lengths
        mask = compile_predicate(self.condition, batch)
        if isinstance(mask, np.ndarray):  # host lane
            row_bucket = np.searchsorted(np.cumsum(lengths),
                                         np.arange(batch.num_rows),
                                         side="right")
            new_lengths = np.bincount(row_bucket[mask],
                                      minlength=num_buckets).astype(np.int64)
            indices = np.nonzero(mask)[0].astype(np.int32)
            return batch.take(indices), new_lengths
        # Per-bucket survivor counts as ONE device segment-sum (row ->
        # bucket via searchsorted over the running lengths), then a single
        # [num_buckets] transfer sizes both the new lengths and the gather.
        import jax
        csum = jnp.cumsum(jnp.asarray(lengths, dtype=jnp.int64))
        row_bucket = jnp.searchsorted(
            csum, jnp.arange(batch.num_rows, dtype=jnp.int64), side="right")
        new_lengths = np.asarray(jax.ops.segment_sum(
            mask.astype(jnp.int32), row_bucket.astype(jnp.int32),
            num_segments=num_buckets)).astype(np.int64)
        count = int(new_lengths.sum())
        (indices,) = jnp.nonzero(mask, size=count, fill_value=0)
        return batch.take(indices), new_lengths

    def execute_sharded(self, num_buckets: int, mesh, align_plan=None):
        """Filter preserves the sharded layout: rows never move, the
        predicate mask just narrows `row_valid` — each device evaluates
        its shard, nothing crosses the link, and the downstream join /
        aggregate skips masked rows exactly as it skips padding. The
        per-bucket histogram is stale after filtering, so it is dropped
        (capacity heuristics fall back to the overflow-retry loop); a
        child's virtual-sub-shard split survives (row-local narrowing
        cannot move rows across shards)."""
        sh = self.child.execute_sharded(num_buckets, mesh,
                                        align_plan=align_plan)
        if sh is None:
            return None
        from hyperspace_tpu.engine.compiler import compile_predicate
        from hyperspace_tpu.parallel.spmd import (
            ShardedBatch, count_string_predicate_lookups)
        count_string_predicate_lookups(self.condition, sh.batch)
        mask = compile_predicate(self.condition, sh.batch)
        return ShardedBatch(sh.batch, sh.row_valid & mask, sh.mesh,
                            sh.rows_per_shard, sh.num_buckets,
                            lengths=None, split_plan=sh.split_plan)


class ProjectExec(PhysicalNode):
    """Projection over (out_name, source) entries, where source is a plain
    child column name (pass-through) or a value Expression compiled by the
    same XLA-fused compiler filters use. Computed entries preserve row
    order, so the bucketed contract (batch + lengths) carries through."""

    name = "Project"

    def __init__(self, entries, child: PhysicalNode):
        # Accept bare name strings (pass-through) or (out_name, source)
        # pairs; `source` is a child column name or an Expression.
        self.entries = [(e, e) if isinstance(e, str) else (e[0], e[1])
                        for e in entries]
        self.child = child

    @property
    def columns(self) -> List[str]:
        """Output names (the view older callers and the plan display use)."""
        return [name for name, _ in self.entries]

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        parts = [name if isinstance(src, str) and src == name
                 else f"{src!r} AS {name}" for name, src in self.entries]
        return f"Project [{', '.join(parts)}]"

    def _project(self, batch: columnar.ColumnBatch) -> columnar.ColumnBatch:
        if all(isinstance(src, str) for _, src in self.entries):
            return batch.select([src for _, src in self.entries])
        from hyperspace_tpu.engine.compiler import ExpressionCompiler
        from hyperspace_tpu.plan.expr import infer_dtype
        from hyperspace_tpu.plan.schema import Field
        compiler = ExpressionCompiler(batch)
        fields: List[Field] = []
        columns = {}
        for name, src in self.entries:
            if isinstance(src, str):
                f = batch.schema.field(src)
                columns[name] = batch.column(src)
                fields.append(Field(name, f.dtype, f.nullable))
            else:
                dtype = infer_dtype(src, batch.schema)
                columns[name] = compiler.value_column(src, dtype)
                fields.append(Field(name, dtype, True))
        return columnar.ColumnBatch(Schema(fields), columns)

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        return self._project(self.child.execute(bucket))

    def execute_bucketed(self, num_buckets: int):
        batch, lengths = self.child.execute_bucketed(num_buckets)
        return self._project(batch), lengths

    def execute_sharded(self, num_buckets: int, mesh, align_plan=None):
        """Pure column selection/renaming preserves the sharded layout
        (same rows, same residency); computed entries evaluate
        element-wise over the sharded columns, which XLA keeps
        shard-local."""
        sh = self.child.execute_sharded(num_buckets, mesh,
                                        align_plan=align_plan)
        if sh is None:
            return None
        from hyperspace_tpu.parallel.spmd import ShardedBatch
        projected = self._project(sh.batch)
        return ShardedBatch(projected, sh.row_valid, sh.mesh,
                            sh.rows_per_shard, sh.num_buckets,
                            lengths=sh.lengths,
                            split_plan=sh.split_plan)


class ExchangeExec(PhysicalNode):
    """Hash repartition — a REAL operator, not a marker. `execute` returns
    rows grouped by hash partition of the keys (the single-chip meaning of
    Spark's ShuffleExchange: same hash identity as the index build, so the
    output layout matches what a bucketed index read produces); with a
    mesh active it lowers to the all_to_all shuffle in `parallel/build.py`
    over ICI. Its presence/absence in the plan is the explain() observable
    — and the work it represents is actually performed or actually elided.
    """

    name = "Exchange"

    def __init__(self, keys: Sequence[str], num_partitions: int,
                 child: PhysicalNode, conf=None):
        self.keys = list(keys)
        self.num_partitions = num_partitions
        self.child = child
        self.conf = conf

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        return f"Exchange hashpartitioning({', '.join(self.keys)}, {self.num_partitions})"

    def execute_partitioned(self, bucket: Optional[int] = None):
        """(batch grouped by partition id, per-partition lengths)."""
        return self.partition(self.child.execute(bucket))

    def partition(self, batch: columnar.ColumnBatch):
        """Partition an already-executed batch (the join path unwraps the
        Exchange and feeds the child batch back in)."""
        import numpy as np

        if batch.num_rows == 0:
            return batch, np.zeros(self.num_partitions, dtype=np.int64)
        if batch.is_host:
            from hyperspace_tpu.ops.host_hash import (host_column_hash_lanes,
                                                      host_flat_hash32)
            lanes = []
            for k in self.keys:
                lanes.extend(host_column_hash_lanes(batch.column(k)))
            ids = (host_flat_hash32(lanes)
                   % np.uint32(self.num_partitions)).astype(np.int32)
            perm = np.argsort(ids, kind="stable").astype(np.int32)
            lengths = np.bincount(ids, minlength=self.num_partitions
                                  ).astype(np.int64)
            return batch.take(perm), lengths
        import jax
        import jax.numpy as jnp

        from hyperspace_tpu.ops.pallas.partition_kernel import (
            batch_partition, kernel_supported)
        if kernel_supported(self.num_partitions):
            # Fused Pallas kernel: ids + histogram in ONE HBM pass.
            ids, lengths_dev = batch_partition(batch, self.keys,
                                               self.num_partitions)
            lengths = np.asarray(lengths_dev).astype(np.int64)
        else:
            from hyperspace_tpu.ops.hash_partition import bucket_ids
            ids = bucket_ids(batch, self.keys, self.num_partitions)
            lengths = np.asarray(jax.ops.segment_sum(
                jnp.ones(batch.num_rows, dtype=jnp.int32), ids,
                num_segments=self.num_partitions)).astype(np.int64)
        iota = jnp.arange(batch.num_rows, dtype=jnp.int32)
        _, perm = jax.lax.sort([ids, iota], num_keys=1, is_stable=True)
        return batch.take(perm), lengths

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        return self.execute_partitioned(bucket)[0]

    def execute_bucketed(self, num_buckets: int):
        """An Exchange output satisfies the bucketed contract (batch in
        partition order + lengths) — it is how the planner re-buckets ONE
        side of a mismatched-bucket-count index join (the ranker's cost
        model: ride the larger layout, reshuffle the smaller)."""
        if num_buckets != self.num_partitions:
            raise HyperspaceException(
                f"Exchange partitions ({self.num_partitions}) != requested "
                f"buckets ({num_buckets}).")
        return self.execute_partitioned()


class WindowExec(PhysicalNode):
    name = "Window"

    def __init__(self, partition_by, order_by, specs, out_schema: Schema,
                 child: PhysicalNode):
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        self.specs = list(specs)
        self.out_schema = out_schema
        self.child = child

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        parts = [f"{s.func}({s.column}) AS {s.alias}" for s in self.specs]
        return (f"Window [{', '.join(parts)}] PARTITION BY "
                f"[{', '.join(self.partition_by)}]")

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        from hyperspace_tpu.ops.window import window_compute
        batch = self.child.execute(bucket)
        return window_compute(batch, self.partition_by, self.order_by,
                              self.specs, self.out_schema)


class SortExec(PhysicalNode):
    name = "Sort"

    def __init__(self, keys: Sequence[str], child: PhysicalNode):
        self.keys = list(keys)
        self.child = child

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        return f"Sort [{', '.join(self.keys)}]"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        from hyperspace_tpu.ops.sort import sort_batch
        batch = self.child.execute(bucket)
        if batch.num_rows == 0:
            return batch
        return sort_batch(batch, self.keys)


class TopKExec(PhysicalNode):
    """Sort+Limit collapsed (`ops/sort.topk_batch`): ORDER BY + LIMIT n
    computes the exact first n rows via a packed-prefix threshold pass
    plus a small candidate sort, instead of fully sorting (and, on a
    tunneled TPU, compiling the minutes-long wide chunked-LSD sort for)
    millions of rows that the limit immediately discards."""

    name = "TopK"

    def __init__(self, n: int, keys: Sequence[str], child: PhysicalNode):
        self.n = n
        self.keys = list(keys)
        self.child = child

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        return f"TopK {self.n} [{', '.join(self.keys)}]"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        from hyperspace_tpu.ops.sort import topk_batch
        batch = self.child.execute(bucket)
        if batch.num_rows == 0:
            return batch
        return topk_batch(batch, self.keys, self.n)


class AggregateExec(PhysicalNode):
    name = "Aggregate"

    def __init__(self, group_columns: Sequence[str], aggregates,
                 out_schema: Schema, child: PhysicalNode, conf=None):
        self.group_columns = list(group_columns)
        self.aggregates = list(aggregates)
        self.out_schema = out_schema
        self.child = child
        self.conf = conf

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        aggs = ", ".join(f"{a.func}({a.column})" for a in self.aggregates)
        return f"Aggregate [{', '.join(self.group_columns)}] [{aggs}]"

    def _materialize_inputs(self, batch: columnar.ColumnBatch):
        """Evaluate expression aggregation inputs (sum(x*y)) into temp
        columns so the segment reducers see plain columns; returns
        (augmented batch, rewritten specs)."""
        from hyperspace_tpu.plan.nodes import AggSpec
        if not any(getattr(s, "is_expression", False)
                   for s in self.aggregates):
            return batch, self.aggregates
        from hyperspace_tpu.engine.compiler import ExpressionCompiler
        from hyperspace_tpu.plan.expr import infer_dtype
        from hyperspace_tpu.plan.schema import Field
        compiler = ExpressionCompiler(batch)
        fields = list(batch.schema.fields)
        columns = dict(batch.columns)
        specs = []
        for i, spec in enumerate(self.aggregates):
            if not spec.is_expression:
                specs.append(spec)
                continue
            dtype = infer_dtype(spec.column, batch.schema)
            name = f"__agg_in_{i}"
            columns[name] = compiler.value_column(spec.column, dtype)
            fields.append(Field(name, dtype, True))
            specs.append(AggSpec(spec.func, name, spec.alias))
        return columnar.ColumnBatch(Schema(fields), columns), specs

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        from hyperspace_tpu.ops.aggregate import group_aggregate
        from hyperspace_tpu.parallel.context import should_distribute
        batch = self.child.execute(bucket)
        batch, specs = self._materialize_inputs(batch)
        mesh = None
        if (self.group_columns and batch.num_rows > 0 and specs
                # count_distinct is not decomposable into mergeable
                # per-shard partials (a value present on two shards must
                # not count twice); it — and pure DISTINCT (no aggregate
                # lanes) — stay on the single-device lane.
                and not any(s.func == "count_distinct" for s in specs)):
            mesh = should_distribute(self.conf, batch.num_rows,
                                     host_batch=batch.is_host)
        if mesh is not None:
            from hyperspace_tpu.parallel.aggregate import (
                distributed_group_aggregate)
            return distributed_group_aggregate(batch, self.group_columns,
                                               specs,
                                               self.out_schema, mesh)
        return group_aggregate(batch, self.group_columns, specs,
                               self.out_schema)


class LimitExec(PhysicalNode):
    name = "Limit"

    def __init__(self, n: int, child: PhysicalNode):
        self.n = n
        self.child = child

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        return f"Limit {self.n}"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        import numpy as np
        batch = self.child.execute(bucket)
        if batch.num_rows <= self.n:
            return batch
        if batch.is_host:
            return batch.take(np.arange(self.n, dtype=np.int32))
        import jax.numpy as jnp
        return batch.take(jnp.arange(self.n, dtype=jnp.int32))


class CrossJoinExec(PhysicalNode):
    """Cartesian product (CROSS JOIN). Exists for the scalar-subquery
    assembly idiom — TPC-DS q28/q61/q88 cross their independent one-row
    aggregates into a single result row — so it is guarded against
    accidental blow-ups rather than optimized for scale. Output naming
    matches the equi-join: right-side duplicates get a `_r` suffix."""

    name = "CrossJoin"
    MAX_ROWS = 50_000_000

    def __init__(self, left: PhysicalNode, right: PhysicalNode):
        self.left = left
        self.right = right

    @property
    def children(self):
        return [self.left, self.right]

    def simple_string(self) -> str:
        return "CrossJoin"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        import numpy as np

        from hyperspace_tpu.plan.schema import Field

        lbatch = self.left.execute(bucket)
        rbatch = self.right.execute(bucket)
        n = lbatch.num_rows * rbatch.num_rows
        if n > self.MAX_ROWS:
            raise HyperspaceException(
                f"Cross join would produce {n} rows "
                f"({lbatch.num_rows} x {rbatch.num_rows}); refusing.")
        lt = lbatch.take(np.repeat(
            np.arange(lbatch.num_rows, dtype=np.int32), rbatch.num_rows))
        rt = rbatch.take(np.tile(
            np.arange(rbatch.num_rows, dtype=np.int32), lbatch.num_rows))
        fields = list(lt.schema.fields)
        columns = dict(lt.columns)
        left_names = {f.name.lower() for f in fields}
        for f in rt.schema.fields:
            name = (f.name if f.name.lower() not in left_names
                    else f.name + "_r")
            fields.append(Field(name, f.dtype, f.nullable))
            columns[name] = rt.columns[f.name]
        return columnar.ColumnBatch(Schema(fields), columns)


class UnionExec(PhysicalNode):
    name = "Union"

    def __init__(self, children: Sequence[PhysicalNode]):
        self._children = list(children)

    @property
    def children(self):
        return list(self._children)

    def simple_string(self) -> str:
        return f"Union ({len(self._children)})"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        batches = [c.execute(bucket) for c in self._children]
        non_empty = [b for b in batches if b.num_rows > 0]
        if not non_empty:
            return batches[0]
        if len(non_empty) == 1:
            return non_empty[0]
        return columnar.concat_batches(non_empty)

    def execute_bucketed(self, num_buckets: int):
        """Hybrid scan as a bucketed source: each child produces the
        (batch, lengths) contract — the index side from its on-disk
        layout, the appended side through the ExchangeExec the planner
        wrapped it in — and the parts are interleaved bucket-major so the
        combined batch satisfies the layout the batched join expects."""
        import numpy as np

        parts = [c.execute_bucketed(num_buckets) for c in self._children]
        if len(parts) == 1:
            return parts[0]
        batches = [b for b, _ in parts]
        total_lengths = np.zeros(num_buckets, dtype=np.int64)
        for _, l in parts:
            total_lengths += np.asarray(l, dtype=np.int64)
        non_empty = [b for b in batches if b.num_rows > 0]
        if not non_empty:
            return batches[0], total_lengths
        combined = (non_empty[0] if len(non_empty) == 1
                    else columnar.concat_batches(batches))
        if len(non_empty) == 1:
            return combined, total_lengths
        # Interleave: rows of bucket b from every part become contiguous.
        base = np.concatenate(
            [[0], np.cumsum([b.num_rows for b in batches])])
        part_offsets = [np.concatenate([[0], np.cumsum(
            np.asarray(l, dtype=np.int64))]) for _, l in parts]
        total = int(total_lengths.sum())
        perm = np.empty(total, dtype=np.int64)
        pos = 0
        for bkt in range(num_buckets):
            for pi in range(len(parts)):
                cnt = int(part_offsets[pi][bkt + 1]
                          - part_offsets[pi][bkt])
                if cnt:
                    start = base[pi] + part_offsets[pi][bkt]
                    perm[pos:pos + cnt] = np.arange(start, start + cnt)
                    pos += cnt
        idx = perm.astype(np.int32)
        if not combined.is_host:
            import jax.numpy as jnp
            idx = jnp.asarray(idx)
        return combined.take(idx), total_lengths


class SetOpExec(PhysicalNode):
    """INTERSECT / EXCEPT (DISTINCT set semantics, NULL == NULL — see
    `ops/setops.py`). Output rows come from the left side in
    first-occurrence order; columns align across sides by name."""

    def __init__(self, left: PhysicalNode, right: PhysicalNode,
                 names: Sequence[str], anti: bool):
        self.left = left
        self.right = right
        self.names = list(names)
        self.anti = anti
        self.name = "Except" if anti else "Intersect"

    @property
    def children(self):
        return [self.left, self.right]

    def simple_string(self) -> str:
        return f"{self.name} [{', '.join(self.names)}]"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        from hyperspace_tpu.ops.setops import set_op_indices
        lbatch = self.left.execute(bucket)
        rbatch = self.right.execute(bucket)
        idx = set_op_indices(lbatch, rbatch, self.names, self.anti)
        return lbatch.select(self.names).take(idx)


class ReusedExec(PhysicalNode):
    """Common-subplan reuse (Spark's ReuseExchange/ReuseSubquery analog):
    the planner routes every occurrence of an identical logical subtree
    (same serialization, same required columns) through ONE shared node
    that memoizes its executed batch. q64-style self-joins of an
    aggregated subquery then compute it once. Physical plans are built
    fresh per query, so the memo's lifetime is a single execution."""

    name = "ReusedSubplan"

    def __init__(self, child: PhysicalNode):
        import threading
        self.child = child
        self._memo = None
        self._memo_bucketed = {}
        # A self-join submits both sides (the SAME instance) to the join's
        # thread pool; without the lock both threads would fill the memo.
        self._lock = threading.Lock()

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        return "ReusedSubplan"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        if bucket is not None:
            return self.child.execute(bucket)
        with self._lock:
            if self._memo is None:
                self._memo = self.child.execute()
            else:
                telemetry.annotate(reused=True)
            return self._memo

    def execute_bucketed(self, num_buckets: int):
        with self._lock:
            if num_buckets not in self._memo_bucketed:
                self._memo_bucketed[num_buckets] = \
                    self.child.execute_bucketed(num_buckets)
            else:
                telemetry.annotate(reused=True)
            return self._memo_bucketed[num_buckets]


class SortMergeJoinExec(PhysicalNode):
    name = "SortMergeJoin"

    def __init__(self, left: PhysicalNode, right: PhysicalNode,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 bucketed: bool, num_buckets: int = 0,
                 out_schema: Optional[Schema] = None, how: str = "inner",
                 conf=None, out_columns: Optional[Set[str]] = None):
        self.left = left
        self.right = right
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.bucketed = bucketed
        self.num_buckets = num_buckets
        self.out_schema = out_schema
        self.how = how
        self.conf = conf
        # Late projection: lowered OUTPUT column names the consumer needs;
        # assembly gathers only these (keys and dropped payload are never
        # materialized through the match expansion).
        self.out_columns = out_columns

    @property
    def children(self):
        return [self.left, self.right]

    def simple_string(self) -> str:
        keys = ", ".join(f"{l}={r}" for l, r in zip(self.left_keys, self.right_keys))
        mode = f"bucketed({self.num_buckets})" if self.bucketed else "global"
        return f"SortMergeJoin {self.how} [{keys}] {mode}"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        from hyperspace_tpu.ops.join import sort_merge_join
        if self.bucketed and bucket is None:
            # Born-sharded SPMD fast path — THE distributed execution
            # architecture: both sides resident per device by bucket
            # range, ONE jitted program for the match + expansion, no
            # host re-placement and no mid-join sizing sync. None = some
            # precondition failed (counted as `spmd.fallbacks` when a
            # mesh was available); the single-chip bucketed path below
            # remains fully capable.
            out = self._try_spmd()
            if out is not None:
                return out
        if self.how in ("left_semi", "left_anti"):
            # Membership joins: no expansion, no output from the right —
            # one encode + counting-match membership flags, then a
            # single left-side gather.
            from hyperspace_tpu.ops.join import semi_anti_indices
            anti = self.how == "left_anti"
            if self.bucketed:
                lbatch, rbatch, _l_lengths, _r_lengths = \
                    self._bucketed_inputs()
            else:
                lbatch = self.left.execute(bucket)
                rbatch = self.right.execute(bucket)
            idx = semi_anti_indices(lbatch, rbatch, self.left_keys,
                                    self.right_keys, anti=anti)
            return lbatch.take(idx)
        if self.bucketed:
            # Co-partitioned bucket joins, batched into ONE compiled program
            # (`ops/bucketed_join.py`): zero shuffle, zero global sort, no
            # per-bucket compile explosion.
            from hyperspace_tpu.ops.bucketed_join import (
                bucketed_sort_merge_join)
            lbatch, rbatch, l_lengths, r_lengths = self._bucketed_inputs()
            return bucketed_sort_merge_join(lbatch, rbatch, l_lengths,
                                            r_lengths, self.left_keys,
                                            self.right_keys, how=self.how,
                                            columns=self.out_columns)
        # General path: the planner wrapped each side in
        # Sort(Exchange(...)) — the Spark-shaped plan. BOTH wrappers are
        # unwrapped and genuinely elided at execution: the counting join
        # (`ops/join.py`) matches in ORIGINAL row space over unsorted
        # ids with ONE flat sort, so a real hash repartition + per-side
        # sort (what Spark must do, and what an earlier revision ran for
        # co-partitionable sides) is strictly extra work — it cost ~2s of
        # a 24s scale-30 q64 while feeding the same counting core.
        def unwrap(node):
            if isinstance(node, SortExec):
                node = node.child
            if isinstance(node, ExchangeExec):
                node = node.child
            return node

        lbatch = unwrap(self.left).execute(bucket)
        rbatch = unwrap(self.right).execute(bucket)
        return sort_merge_join(lbatch, rbatch, self.left_keys,
                               self.right_keys, how=self.how,
                               columns=self.out_columns)

    def _try_spmd(self) -> Optional[columnar.ColumnBatch]:
        """The born-sharded SPMD join (`parallel/spmd.py`), or None when
        any precondition fails: no mesh / bucket count not divisible /
        either side not shardable (host-lane sizing, skew). Strings are
        first-class (per-range dictionaries + in-program rank remaps).
        Covers every equi-join type of the sharded counting match;
        right_outer swaps sides. A decline WITH a mesh available is a
        real lane miss — counted as `spmd.fallbacks` (the TPC-DS bench
        asserts the flagship set runs fallback-free)."""
        from hyperspace_tpu.parallel import spmd
        from hyperspace_tpu.parallel.context import (distribution_mesh,
                                                     mesh_size)

        if self.num_buckets <= 0:
            return None
        if self.conf is not None and not self.conf.distribution_spmd:
            return None  # the operational escape hatch: single-chip only
        mesh = distribution_mesh(self.conf)
        if mesh is None:
            return None
        if self.how not in ("inner", "left_outer", "right_outer",
                            "full_outer", "left_semi", "left_anti"):
            spmd.spmd_fallback("join-type")
            return None
        if self.num_buckets % mesh_size(mesh) != 0:
            spmd.spmd_fallback("bucket-count-indivisible")
            return None
        # One device-queue scope for the whole sharded join (reads,
        # match program, output assembly): on emulated meshes two
        # concurrent multi-device programs over one device set can
        # interleave into a collective-rendezvous deadlock; the
        # reentrant per-device-set guard serializes them exactly as a
        # real device queue would, while queries pinned to DISJOINT
        # replica slices still run concurrently (no-op off CPU).
        with spmd.dispatch_guard(mesh):
            return self._run_spmd(mesh)

    def _run_spmd(self, mesh) -> Optional[columnar.ColumnBatch]:
        from hyperspace_tpu.parallel import spmd

        lsh = self.left.execute_sharded(self.num_buckets, mesh)
        if lsh is None:
            spmd.spmd_fallback("left-not-shardable")
            return None
        align = lsh.split_plan
        if align is not None:
            # Hot-bucket skew on the left: the right side reads ALIGNED
            # to the split (intersected buckets replicated per covering
            # shard). Replication breaks unmatched-right uniqueness, so
            # full_outer routes off the lane; membership/inner/left
            # shapes are bit-identical (each left row lives on exactly
            # one shard and meets every matching right row locally).
            if self.how == "full_outer":
                spmd.spmd_fallback("subshard-join-type")
                return None
            if self.how == "right_outer":
                spmd.spmd_fallback("subshard-right-outer")
                return None
            rsh = self.right.execute_sharded(self.num_buckets, mesh,
                                             align_plan=align)
        else:
            rsh = self.right.execute_sharded(self.num_buckets, mesh)
        if rsh is None:
            spmd.spmd_fallback("right-not-shardable")
            return None
        if align is None and rsh.split_plan is not None:
            # Right-side-only skew: the counting layout would need the
            # LEFT replicated, which breaks unmatched-left uniqueness
            # (outer) and duplicates membership take indices (semi /
            # anti). INNER has no unmatched-row semantics on either
            # side, so swap roles instead of declining: re-read the
            # left ALIGNED to the right's split (each right row lives
            # on exactly one shard; intersecting left buckets replicate
            # per covering shard) and run the counting match with the
            # right as the preserved side — bit-identical inner output,
            # one extra left read instead of a full lane miss.
            if self.how != "inner":
                spmd.spmd_fallback("subshard-right")
                return None
            lsh = self.left.execute_sharded(self.num_buckets, mesh,
                                            align_plan=rsh.split_plan)
            if lsh is None:
                spmd.spmd_fallback("subshard-right")
                return None
            telemetry.get_registry().counter(
                "mesh.spmd.side_swapped").inc()
            telemetry.annotate(lane="spmd")
            from hyperspace_tpu.ops.bucketed_join import (
                assemble_join_output)
            factor = (self.conf.distribution_capacity_factor
                      if self.conf is not None else None)
            ri, li = spmd.sharded_join_indices(
                rsh, lsh, self.right_keys, self.left_keys, how="inner",
                capacity_factor=factor, conf=self.conf)
            return assemble_join_output(lsh.batch, rsh.batch, li, ri,
                                        how="inner",
                                        columns=self.out_columns)
        telemetry.annotate(lane="spmd")
        if self.how in ("left_semi", "left_anti"):
            idx = spmd.sharded_semi_anti_indices(
                lsh, rsh, self.left_keys, self.right_keys,
                anti=self.how == "left_anti", conf=self.conf)
            return lsh.batch.take(idx)
        from hyperspace_tpu.ops.bucketed_join import assemble_join_output
        factor = (self.conf.distribution_capacity_factor
                  if self.conf is not None else None)
        if self.how == "right_outer":
            ri, li = spmd.sharded_join_indices(
                rsh, lsh, self.right_keys, self.left_keys,
                how="left_outer", capacity_factor=factor,
                conf=self.conf)
        else:
            li, ri = spmd.sharded_join_indices(
                lsh, rsh, self.left_keys, self.right_keys, how=self.how,
                capacity_factor=factor, conf=self.conf)
        return assemble_join_output(lsh.batch, rsh.batch, li, ri,
                                    how=self.how,
                                    columns=self.out_columns)

    def _bucketed_inputs(self):
        """Read both sides in bucket order (overlapped IO) for the
        single-chip batched bucketed join — the one general path under
        the SPMD lane (the legacy per-query-placement mesh join is
        gone; `parallel/mesh.py` is the sole sharding seam)."""
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=2) as pool:
            # telemetry.propagating: pool threads don't inherit the
            # query's recorder context — re-establish it so each side's
            # scans record under this join.
            lf = pool.submit(telemetry.propagating(
                self.left.execute_bucketed), self.num_buckets)
            rf = pool.submit(telemetry.propagating(
                self.right.execute_bucketed), self.num_buckets)
            lbatch, l_lengths = lf.result()
            rbatch, r_lengths = rf.result()
        telemetry.annotate(lane=("host" if lbatch.is_host
                                 and rbatch.is_host else "device"))
        return lbatch, rbatch, l_lengths, r_lengths


class BroadcastHashJoinExec(PhysicalNode):
    """Small-side join with NO Exchange/Sort on either side — the engine's
    analog of Spark's BroadcastHashJoin, which the reference leans on for
    every dimension join (`E2EHyperspaceRulesTests.scala:42` must disable
    it to exercise the SMJ path). The planner routes a join here when one
    side's estimated size is under `spark.hyperspace.broadcast.threshold`;
    execution replicates that side as a direct-address lookup table and
    matches probe rows with one gather (`ops/broadcast_join.py`). When the
    keys are ineligible at run time (strings/floats/duplicates/wide
    ranges), the counting join runs on the bare batches instead — still
    zero Exchange, just without the no-sort shortcut."""

    name = "BroadcastHashJoin"

    def __init__(self, left: PhysicalNode, right: PhysicalNode,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 build_side: str, how: str = "inner", conf=None,
                 out_columns: Optional[Set[str]] = None):
        self.left = left
        self.right = right
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.build_side = build_side  # "left" | "right"
        self.how = how
        self.conf = conf
        self.out_columns = out_columns

    @property
    def children(self):
        return [self.left, self.right]

    def simple_string(self) -> str:
        keys = ", ".join(f"{l}={r}"
                         for l, r in zip(self.left_keys, self.right_keys))
        return (f"BroadcastHashJoin {self.how} [{keys}] "
                f"build={self.build_side}")

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        from hyperspace_tpu.ops.broadcast_join import (broadcast_join_indices,
                                                       broadcast_membership)
        from hyperspace_tpu.ops.bucketed_join import assemble_join_output
        from hyperspace_tpu.ops.join import (semi_anti_indices,
                                             sort_merge_join)

        lbatch = self.left.execute(bucket)
        rbatch = self.right.execute(bucket)
        if self.how in ("left_semi", "left_anti"):
            anti = self.how == "left_anti"
            idx = broadcast_membership(lbatch, rbatch, self.left_keys,
                                       self.right_keys, anti=anti)
            if idx is None:
                idx = semi_anti_indices(lbatch, rbatch, self.left_keys,
                                        self.right_keys, anti=anti)
            return lbatch.take(idx)
        if self.build_side == "right":
            pair = broadcast_join_indices(lbatch, rbatch, self.left_keys,
                                          self.right_keys, self.how)
            if pair is not None:
                li, ri = pair
                return assemble_join_output(lbatch, rbatch, li, ri,
                                            how=self.how,
                                            columns=self.out_columns)
        else:
            pair = broadcast_join_indices(
                rbatch, lbatch, self.right_keys, self.left_keys,
                "left_outer" if self.how == "right_outer" else "inner")
            if pair is not None:
                ri, li = pair
                return assemble_join_output(lbatch, rbatch, li, ri,
                                            how=self.how,
                                            columns=self.out_columns)
        return sort_merge_join(lbatch, rbatch, self.left_keys,
                               self.right_keys, how=self.how,
                               columns=self.out_columns)


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------


_PRUNE_MAX_COMBOS = 64


def _literal_values_for(column: str, conjuncts) -> Optional[List]:
    """Literal values `column` may take under the conjunction, from the
    narrowest `col = lit` / `col IN (lits)` constraint; None if
    unconstrained (or only constrained through nulls, where pruning is
    skipped — `x = NULL` is never true, so correctness never depends on
    pruning)."""
    best: Optional[List] = None
    for c in conjuncts:
        values = None
        if isinstance(c, E.EqualTo):
            a, b = c.left, c.right
            if isinstance(a, E.Column) and isinstance(b, E.Literal):
                values = [b.value] if a.name.lower() == column else None
            elif isinstance(b, E.Column) and isinstance(a, E.Literal):
                values = [a.value] if b.name.lower() == column else None
        elif (isinstance(c, E.In) and isinstance(c.child, E.Column)
              and c.child.name.lower() == column):
            values = [v.value for v in c.values]
        if values is None or any(v is None for v in values):
            continue
        if best is None or len(values) < len(best):
            best = values
    return best


def _prune_buckets(condition: E.Expression,
                   scan: Scan) -> Optional[Set[int]]:
    """Bucket ids that can contain rows satisfying `condition`, or None
    when pruning does not apply. Sound because every bucket column must be
    pinned to literals by top-level conjuncts: any matching row hashes to
    one of the returned buckets. The literal tuples are hashed with THE
    build hash kernel (`ops/hash_partition.bucket_ids`) so the computed
    ids match the on-disk layout exactly."""
    import itertools

    spec = scan.bucket_spec
    if spec is None:
        return None
    conjuncts = E.split_conjunctive(condition)
    per_column: List[List] = []
    for c in spec.bucket_columns:
        values = _literal_values_for(c.lower(), conjuncts)
        if values is None:
            return None
        per_column.append(values)
    combos = list(itertools.product(*per_column))
    if not combos or len(combos) > _PRUNE_MAX_COMBOS:
        return None
    import numpy as np_

    from hyperspace_tpu.ops.host_hash import host_bucket_ids

    key_schema = scan.schema.select(list(spec.bucket_columns))
    np_of = {"int64": np_.int64, "int32": np_.int32, "int16": np_.int16,
             "int8": np_.int8, "bool": np_.bool_, "float64": np_.float64,
             "float32": np_.float32, "date32": np_.int32,
             "timestamp": np_.int64, "string": None}
    try:
        columns = []
        for i, f in enumerate(key_schema.fields):
            vals = [combo[i] for combo in combos]
            dt = np_of[f.dtype]
            columns.append(np_.asarray(vals, dtype=str) if dt is None
                           else np_.asarray(vals).astype(dt))
        # Host mirror of the build hash — no device round-trip; identity
        # pinned against `ops/hash_partition.bucket_ids` by test.
        ids = host_bucket_ids(columns, [f.dtype for f in key_schema.fields],
                              spec.num_buckets)
    except (ValueError, TypeError, OverflowError, HyperspaceException):
        return None  # literal not representable in the key type -> no prune
    return set(int(b) for b in ids)


def _apply_bucket_pruning(condition: E.Expression, child: PhysicalNode):
    """Descend Project/Filter chains — and Union fan-outs (hybrid scan:
    index UNION appended files) — to each ScanExec and attach the allowed
    bucket set derived from the filter condition (no-op on unbucketed
    scans). Descending through an intermediate Filter (e.g. the hybrid
    lineage exclusion) is sound: pruning only drops buckets no row of
    which can satisfy the OUTER condition, and inner filters only remove
    more rows."""
    node = child
    while isinstance(node, (ProjectExec, FilterExec)):
        node = node.child
    if isinstance(node, UnionExec):
        for c in node.children:
            _apply_bucket_pruning(condition, c)
    elif isinstance(node, ScanExec) and node.allowed_buckets is None:
        node.allowed_buckets = _prune_buckets(condition, node.scan)
    return child


def _hoist_union(plan: LogicalPlan) -> LogicalPlan:
    """Pull a Union above Filter/Project wrappers (both distribute over
    union row-wise) so join-over-union distribution can see it."""
    if isinstance(plan, (Project, Filter)):
        child = _hoist_union(plan.child)
        if isinstance(child, Union):
            return Union([plan.with_children([c])
                          for c in child.children])
    return plan


def _chain_has_bucketed_scan(node: PhysicalNode) -> bool:
    while isinstance(node, (ProjectExec, FilterExec, ReusedExec)):
        node = node.child
    return isinstance(node, ScanExec) and node.scan.bucket_spec is not None


def _bucketize_union_children(node: PhysicalNode, keys: List[str],
                              num_buckets: int, conf) -> None:
    """Descend a join side's Project/Filter chain; if it feeds a UnionExec
    (hybrid scan), wrap each child that does NOT ride a bucketed layout in
    an ExchangeExec over the join keys — the appended slice then arrives
    co-partitioned with the index buckets. Idempotent (a shared/reused
    union may be visited by both sides of a self-join)."""
    while isinstance(node, (ProjectExec, FilterExec, ReusedExec)):
        node = node.child
    if not isinstance(node, UnionExec):
        return
    wrapped = []
    for c in node._children:
        if _chain_has_bucketed_scan(c) or (
                isinstance(c, ExchangeExec)
                and c.num_partitions == num_buckets):
            wrapped.append(c)
        else:
            wrapped.append(ExchangeExec(keys, num_buckets, c, conf=conf))
    node._children = wrapped


def _split_join_required(required: Set[str], left_schema: Schema,
                         right_schema: Schema, left_keys=(), right_keys=()):
    """Split a join's required OUTPUT names into per-side input column
    sets. A required `<name>_r` maps back to the right-side source AND
    keeps the left-side copy alive — the executor renames the right
    column only when the left batch still carries the collision, so
    pruning the left copy would silently un-suffix the output. ONE home
    for this rule (equi and cross branches both had a hand copy; the
    cross copy had already drifted and dropped the left side)."""
    left_req = ({n for n in required if left_schema.contains(n)}
                | set(left_keys))
    right_req = ({n for n in required if right_schema.contains(n)}
                 | set(right_keys))
    for n in required:
        base = n[:-2] if n.lower().endswith("_r") else None
        if (base and right_schema.contains(base)
                and left_schema.contains(base)):
            right_req.add(base)
            left_req.add(base)
    return left_req, right_req


def _join_keys(condition: E.Expression, left_schema: Schema,
               right_schema: Schema) -> Tuple[List[str], List[str]]:
    """Extract equi-join key pairs from an AND-of-equalities condition
    (reference applicability: `JoinIndexRule.scala:179-185,278-317`)."""
    left_keys: List[str] = []
    right_keys: List[str] = []
    for conjunct in E.split_conjunctive(condition):
        if not isinstance(conjunct, E.EqualTo):
            raise HyperspaceException(
                f"Only equi-join conditions are supported; got {conjunct!r}")
        a, b = conjunct.left, conjunct.right
        if not isinstance(a, E.Column) or not isinstance(b, E.Column):
            raise HyperspaceException(
                "Join condition must compare columns directly.")
        if left_schema.contains(a.name) and right_schema.contains(b.name):
            left_keys.append(a.name)
            right_keys.append(b.name)
        elif left_schema.contains(b.name) and right_schema.contains(a.name):
            left_keys.append(b.name)
            right_keys.append(a.name)
        else:
            raise HyperspaceException(
                f"Join columns not found on both sides: {conjunct!r}")
    return left_keys, right_keys


def _underlying_bucket_spec(plan: LogicalPlan) -> Optional[BucketSpec]:
    """The bucket spec of the scan feeding a linear Filter/Project chain —
    filters and projections preserve bucketing and intra-bucket order. A
    Union whose FIRST child rides a bucketed layout (hybrid scan: index
    data UNION appended files) reports that spec; the planner re-buckets
    the remaining children through ExchangeExec at execution time."""
    node = plan
    while True:
        if isinstance(node, Scan):
            return node.bucket_spec
        if isinstance(node, (Filter, Project)):
            node = node.child
            continue
        if isinstance(node, Union):
            return _underlying_bucket_spec(node.children[0])
        return None


# Approximate in-memory bytes per value; strings budget code + a share of
# the dictionary. Only relative accuracy vs the broadcast threshold
# matters (Spark's estimate — raw file size — is no finer).
_DTYPE_WIDTH = {"bool": 1, "int8": 1, "int16": 2, "int32": 4, "date32": 4,
                "float32": 4, "int64": 8, "float64": 8, "timestamp": 8,
                "string": 16}


def _estimated_plan_bytes(plan: LogicalPlan,
                          required: Set[str]) -> Optional[int]:
    """Upper-bound decoded bytes of `plan`'s output restricted to
    `required`, from parquet footer row counts (cached; no data read).
    None when the subtree's cardinality is not statically bounded by its
    scans — aggregates/joins/windows can shrink OR grow, so they never
    qualify a side for broadcast. Mirrors what Spark's
    `autoBroadcastJoinThreshold` keys on (leaf statistics propagated
    through Filter/Project)."""
    if isinstance(plan, Scan):
        files = plan.files()
        if not files:
            return 0
        try:
            rows = sum(parquet.file_row_counts(files))
        except Exception:
            return None
        lowered = {r.lower() for r in required}
        width = sum(_DTYPE_WIDTH.get(f.dtype, 8) for f in plan.schema.fields
                    if f.name.lower() in lowered)
        return rows * max(width, 1)
    if isinstance(plan, (Filter, Sort, Limit)):
        # Row count bounded by the child's (Filter/Limit only shrink).
        return _estimated_plan_bytes(plan.child, required)
    if isinstance(plan, Project):
        # Map required OUTPUT names back through the projection to child
        # columns (Spark's statistics propagation does the same): a
        # renamed/computed column must contribute its SOURCE columns'
        # width, not silently zero — a side whose broadcast-relevant
        # columns are all computed would otherwise be underestimated and
        # admitted past the threshold. Unmappable entries fall back to
        # the full child width.
        lowered = {r.lower() for r in required}
        child_req: Set[str] = set()
        for c in plan.columns:
            if isinstance(c, str):
                if c.lower() in lowered:
                    child_req.add(c)
                continue
            if c.name.lower() not in lowered:
                continue
            try:
                refs = c.child.references()
            except Exception:
                return _estimated_plan_bytes(
                    plan.child, set(plan.child.schema.names))
            child_req |= refs
        return _estimated_plan_bytes(plan.child, child_req)
    if isinstance(plan, Union):
        total = 0
        for c in plan.children:
            est = _estimated_plan_bytes(c, required)
            if est is None:
                return None
            total += est
        return total
    return None


def _required_for(plan: LogicalPlan, required: Set[str]) -> List[str]:
    """required column names resolved against plan schema, in schema order."""
    schema = plan.schema
    lowered = {r.lower() for r in required}
    return [f.name for f in schema.fields if f.name.lower() in lowered]


def plan_physical(plan: LogicalPlan,
                  required: Optional[Set[str]] = None,
                  conf=None) -> PhysicalNode:
    """Logical -> physical with projection pushdown into scans. `conf`
    carries the session's distribution settings to the operators that can
    execute on the mesh (Filter scans, bucketed SMJ). Identical logical
    subtrees (by fingerprint + required columns) compile to ONE shared
    `ReusedExec` so repeated subqueries execute once."""
    counts: dict = {}
    keys: dict = {}

    def _count(node):
        key = _subtree_key(node, keys)
        counts[key] = counts.get(key, 0) + 1
        for c in node.children:
            _count(c)

    _count(plan)
    return _plan_physical(plan, required, conf,
                          {"counts": counts, "keys": keys, "built": {}})


def _subtree_key(node: LogicalPlan, memo: dict) -> str:
    """Bottom-up md5 fingerprint of a subtree: each node hashes its LOCAL
    fields plus its children's fingerprints, so the whole walk is O(nodes)
    instead of re-serializing every subtree per ancestor. Memoized by node
    identity (nodes stay alive for the duration of planning)."""
    import hashlib
    import json as _json

    k = memo.get(id(node))
    if k is not None:
        return k
    local = node.to_dict()
    for field in ("child", "children", "left", "right"):
        local.pop(field, None)
    payload = (type(node).__name__
               + _json.dumps(local, sort_keys=True)
               + "[" + ",".join(_subtree_key(c, memo)
                                for c in node.children) + "]")
    k = hashlib.md5(payload.encode()).hexdigest()
    memo[id(node)] = k
    return k


def _is_prunable_chain(plan: LogicalPlan) -> bool:
    """Project*/Scan chain over a bucketed scan with no Filter inside —
    the shape `_apply_bucket_pruning` prunes FROM ABOVE. Sharing it would
    either disable pruning or wrongly prune one consumer's rows with
    another's condition, so such chains are never reused (their IO is
    deduplicated by the decoded-read cache anyway)."""
    node = plan
    while isinstance(node, Project):
        node = node.child
    return isinstance(node, Scan) and node.bucket_spec is not None


def _plan_physical(plan: LogicalPlan,
                   required: Optional[Set[str]],
                   conf, ctx) -> PhysicalNode:
    if required is None:
        required = set(plan.schema.names)

    parent_count = ctx.get("parent_count", 1)
    reuse_key = None
    count = parent_count
    if plan.children and not _is_prunable_chain(plan):
        # (leaves are covered by the decoded-read cache)
        subtree = _subtree_key(plan, ctx["keys"])
        count = ctx["counts"].get(subtree, 0)
        # Only MAXIMAL shared subtrees get a ReusedExec: inside a shared
        # subtree every descendant repeats as often as its ancestor, but
        # the ancestor's memo already deduplicates the whole region —
        # inner wrappers would only chop the operator chain into 1-op
        # fragments (defeating whole-stage fusion) and pay per-node
        # locking. A descendant shared MORE widely than its ancestor
        # (used elsewhere too) still gets its own wrapper. The enclosing
        # share count scopes through ctx (saved/restored around the
        # subtree build).
        if count > parent_count:
            reuse_key = (subtree,
                         frozenset(r.lower() for r in required))
            shared = ctx["built"].get(reuse_key)
            if shared is not None:
                return shared

    ctx["parent_count"] = max(parent_count, count)
    try:
        built = _plan_physical_node(plan, required, conf, ctx)
    finally:
        ctx["parent_count"] = parent_count
    if reuse_key is not None:
        built = ReusedExec(built)
        ctx["built"][reuse_key] = built
    return built


def _plan_physical_node(plan: LogicalPlan,
                        required: Set[str],
                        conf, ctx) -> PhysicalNode:

    if isinstance(plan, Scan):
        return ScanExec(plan, _required_for(plan, required), conf=conf)

    if isinstance(plan, Filter):
        child_required = set(required) | plan.condition.references()
        child = _apply_bucket_pruning(
            plan.condition,
            _plan_physical(plan.child, child_required, conf, ctx))
        return FilterExec(plan.condition, child, conf=conf)

    if isinstance(plan, Project):
        child = _plan_physical(plan.child, plan.references(), conf, ctx)
        # Resolve names against the child schema but KEEP the declared
        # order; computed entries carry their expression.
        entries = []
        for c in plan.columns:
            if isinstance(c, str):
                f = plan.child.schema.field(c)
                entries.append((f.name, f.name))
            else:
                entries.append((c.name, c.child))
        return ProjectExec(entries, child)

    if isinstance(plan, Aggregate):
        child_required = set(plan.group_columns)
        for a in plan.aggregates:
            child_required |= a.references()
        if not child_required:
            # Bare count(*): a ColumnBatch carries its row count only
            # through its columns, so read at least one.
            child_required = {plan.child.schema.names[0]}
        return AggregateExec(plan.group_columns, plan.aggregates,
                             plan.schema,
                             _plan_physical(plan.child, child_required,
                                            conf, ctx),
                             conf=conf)

    if isinstance(plan, Window):
        from hyperspace_tpu.plan.nodes import sort_direction
        aliases = {s.alias.lower() for s in plan.specs}
        child_required = ({n for n in required if n.lower() not in aliases
                           and plan.child.schema.contains(n)}
                          | set(plan.partition_by)
                          | {sort_direction(c)[0] for c in plan.order_by})
        for s in plan.specs:
            child_required |= s.references()
        if not child_required:
            child_required = {plan.child.schema.names[0]}
        # Output schema restricted to what survives pruning: child columns
        # actually read + every window column.
        child_phys = _plan_physical(plan.child, child_required, conf, ctx)
        from hyperspace_tpu.plan.schema import Schema as _Schema
        kept = {n.lower() for n in child_required}
        fields = [f for f in plan.child.schema.fields
                  if f.name.lower() in kept]
        out_schema = _Schema(fields + [plan.schema.field(s.alias)
                                       for s in plan.specs])
        return WindowExec(plan.partition_by, plan.order_by, plan.specs,
                          out_schema, child_phys)

    if isinstance(plan, Sort):
        from hyperspace_tpu.plan.nodes import sort_direction
        child_required = (set(required)
                          | {sort_direction(c)[0] for c in plan.columns})
        return SortExec(plan.columns,
                        _plan_physical(plan.child, child_required, conf,
                                       ctx))

    if isinstance(plan, Limit):
        if isinstance(plan.child, Sort):
            from hyperspace_tpu.plan.nodes import sort_direction
            child_required = (set(required) | {sort_direction(c)[0]
                                               for c in plan.child.columns})
            return TopKExec(plan.n, plan.child.columns,
                            _plan_physical(plan.child.child, child_required,
                                           conf, ctx))
        return LimitExec(plan.n,
                         _plan_physical(plan.child, required, conf, ctx))

    if isinstance(plan, Union):
        # Children may expose different column orders for the same names
        # (index schema vs source schema): normalize through a Project.
        wanted = _required_for(plan, required)
        return UnionExec([
            ProjectExec([(c.schema.field(n).name, c.schema.field(n).name)
                         for n in wanted],
                        _plan_physical(c, set(wanted), conf, ctx))
            for c in plan.children])

    if isinstance(plan, SetOp):
        # Set-op identity is over FULL rows of the node schema: children
        # must produce every column regardless of what the parent needs.
        names = [f.name for f in plan.left.schema.fields]
        left_phys = _plan_physical(plan.left, set(names), conf, ctx)
        right_phys = _plan_physical(
            plan.right, set(plan.right.schema.names), conf, ctx)
        return SetOpExec(left_phys, right_phys, names,
                         anti=isinstance(plan, Except))

    if isinstance(plan, Join):
        if plan.join_type == "cross":
            left_req, right_req = _split_join_required(
                set(required), plan.left.schema, plan.right.schema)
            # A side no output column resolves to must still read ONE
            # column: a zero-column batch reports num_rows == 0 and would
            # collapse the whole product (same floor the Aggregate
            # planner applies for bare count(*)).
            if not left_req:
                left_req = {plan.left.schema.names[0]}
            if not right_req:
                right_req = {plan.right.schema.names[0]}
            return CrossJoinExec(
                _plan_physical(plan.left, left_req, conf, ctx),
                _plan_physical(plan.right, right_req, conf, ctx))
        # Join-over-union distribution: (A UNION B) JOIN R executes as
        # (A JOIN R) UNION (B JOIN R) when the join type distributes over
        # that side. The hybrid-scan Union then keeps its index part on
        # the native bucketed fast path while only the (small) appended
        # part pays a general join; the shared right subtree executes
        # once via ReusedExec. Filter/Project wrappers themselves
        # distribute over Union, so the union is hoisted through them
        # first.
        left_h = _hoist_union(plan.left)
        right_h = _hoist_union(plan.right)
        if (isinstance(left_h, Union)
                and plan.join_type in ("inner", "left_outer", "left_semi",
                                       "left_anti")):
            branches = len(left_h.children)
            k = _subtree_key(plan.right, ctx["keys"])
            ctx["counts"][k] = ctx["counts"].get(k, 0) + branches - 1
            return _plan_physical_node(
                Union([Join(c, plan.right, plan.condition, plan.join_type)
                       for c in left_h.children]), required, conf, ctx)
        if (isinstance(right_h, Union)
                and plan.join_type in ("inner", "right_outer")):
            branches = len(right_h.children)
            k = _subtree_key(plan.left, ctx["keys"])
            ctx["counts"][k] = ctx["counts"].get(k, 0) + branches - 1
            return _plan_physical_node(
                Union([Join(plan.left, c, plan.condition, plan.join_type)
                       for c in right_h.children]), required, conf, ctx)
        left_keys, right_keys = _join_keys(plan.condition, plan.left.schema,
                                           plan.right.schema)
        membership = plan.join_type in ("left_semi", "left_anti")
        if membership:
            # Membership join: the right side contributes only its keys.
            out_columns = None
            left_required = ({n for n in required
                              if plan.left.schema.contains(n)}
                             | set(left_keys))
            right_required = set(right_keys)
        else:
            out_columns = {n.lower() for n in required}
            left_required, right_required = _split_join_required(
                set(required), plan.left.schema, plan.right.schema,
                left_keys, right_keys)
        left_phys = _plan_physical(plan.left, left_required, conf, ctx)
        right_phys = _plan_physical(plan.right, right_required, conf, ctx)

        lspec = _underlying_bucket_spec(plan.left)
        rspec = _underlying_bucket_spec(plan.right)

        def _align_to_spec(spec: Optional[BucketSpec]):
            """Reorder the (left, right) key PAIRS so the left list matches
            `spec.bucket_columns`. The CONDITION's conjunct order is
            irrelevant to bucketing — each side hashes in its own
            indexed-column order — so a join written `b = b AND a = a`
            over an (a, b) layout must still take the bucketed path
            (q50's ticket-identity join was silently demoted to
            Exchange+Sort by the old exact-order check). None when the
            key set is not exactly the bucket column set."""
            if spec is None or len(spec.bucket_columns) != len(left_keys):
                return None
            lk_lower = [k.lower() for k in left_keys]
            order = []
            for bc in spec.bucket_columns:
                if bc.lower() not in lk_lower:
                    return None
                order.append(lk_lower.index(bc.lower()))
            if len(set(order)) != len(order):
                return None
            return ([left_keys[i] for i in order],
                    [right_keys[i] for i in order])

        def _key_dtypes_match() -> bool:
            # Co-partitioning assumes both layouts hashed with the SAME
            # lane decomposition; int32 vs int64 (or float32 vs float64)
            # keys bucket equal values differently, so any bucketed path
            # would silently drop matches — fall through to the general
            # path, which promotes dtypes before encoding.
            return all(plan.left.schema.field(lk).dtype
                       == plan.right.schema.field(rk).dtype
                       for lk, rk in zip(left_keys, right_keys))

        threshold = conf.broadcast_threshold if conf is not None else 0
        if membership and threshold > 0:
            # For MEMBERSHIP joins a small right side beats even an
            # aligned bucketed layout: the direct-address probe is one
            # gather over the left, no joint counting match — so
            # broadcast outranks the bucketed path here (unlike payload
            # joins, where the index pair's zero-work layout wins).
            est = _estimated_plan_bytes(plan.right, right_required)
            if est is not None and est <= threshold:
                return BroadcastHashJoinExec(left_phys, right_phys,
                                             left_keys, right_keys,
                                             build_side="right",
                                             how=plan.join_type, conf=conf,
                                             out_columns=out_columns)

        aligned = _align_to_spec(lspec)
        # The right layout must hash the MAPPED columns in the same
        # positions (the rule's order-compat requirement; checked here
        # too for hand-built bucketed joins).
        if (aligned is None or rspec is None
                or [c.lower() for c in rspec.bucket_columns]
                != [k.lower() for k in aligned[1]]):
            aligned = None

        if aligned is not None and _key_dtypes_match():
            left_keys, right_keys = aligned
            # Bucketed SMJ — the indexed fast path. With mismatched bucket
            # counts (the ranker's fallback, reference
            # `JoinIndexRanker.scala:40-55`) ONLY the coarser side is
            # re-bucketed through Exchange to the finer count; the
            # Exchange uses THE hash identity, so its output co-partitions
            # with the other side's on-disk buckets.
            target = max(lspec.num_buckets, rspec.num_buckets)
            if lspec.num_buckets != target:
                left_phys = ExchangeExec(left_keys, target, left_phys,
                                         conf=conf)
            elif rspec.num_buckets != target:
                right_phys = ExchangeExec(right_keys, target, right_phys,
                                          conf=conf)
            # Hybrid-scan sides: re-bucket the appended (unbucketed) Union
            # children through THE hash Exchange so they co-partition with
            # the index layout.
            _bucketize_union_children(left_phys, left_keys, target, conf)
            _bucketize_union_children(right_phys, right_keys, target, conf)
            return SortMergeJoinExec(left_phys, right_phys, left_keys,
                                     right_keys, bucketed=True,
                                     num_buckets=target,
                                     how=plan.join_type, conf=conf,
                                     out_columns=out_columns)
        # Broadcast path: one side estimated small (dimension tables) —
        # no Exchange/Sort on EITHER side; the build side replicates as a
        # direct-address table. The reference relies on Spark's
        # BroadcastHashJoin for exactly these joins; disable with
        # `spark.hyperspace.broadcast.threshold = -1` (the analog of the
        # reference E2E suite pinning autoBroadcastJoinThreshold to -1,
        # `E2EHyperspaceRulesTests.scala:42`). The probe side must keep
        # ALL its rows, so outer joins only broadcast their inner side.
        if threshold > 0:
            build = None
            if plan.join_type in ("inner", "left_outer"):
                est = _estimated_plan_bytes(plan.right, right_required)
                if est is not None and est <= threshold:
                    build = "right"
            if build is None and plan.join_type in ("inner", "right_outer"):
                est = _estimated_plan_bytes(plan.left, left_required)
                if est is not None and est <= threshold:
                    build = "left"
            if build is not None:
                return BroadcastHashJoinExec(left_phys, right_phys,
                                             left_keys, right_keys,
                                             build_side=build,
                                             how=plan.join_type, conf=conf,
                                             out_columns=out_columns)
        if membership:
            # Bare membership probe: Exchange/Sort wrappers would be pure
            # overhead — the counting match sorts only ids.
            return SortMergeJoinExec(left_phys, right_phys, left_keys,
                                     right_keys, bucketed=False,
                                     how=plan.join_type, conf=conf)
        # General path: hash exchange + sort on each side.
        num_partitions = max(lspec.num_buckets if lspec else 0,
                             rspec.num_buckets if rspec else 0, 200)
        left_sorted = SortExec(left_keys, ExchangeExec(left_keys,
                                                       num_partitions,
                                                       left_phys, conf=conf))
        right_sorted = SortExec(right_keys, ExchangeExec(right_keys,
                                                         num_partitions,
                                                         right_phys,
                                                         conf=conf))
        return SortMergeJoinExec(left_sorted, right_sorted, left_keys,
                                 right_keys, bucketed=False,
                                 how=plan.join_type, conf=conf,
                                 out_columns=out_columns)

    raise HyperspaceException(f"Cannot plan node: {plan!r}")
