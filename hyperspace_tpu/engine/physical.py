"""Physical plan: executable operator tree.

The reference's observable win is Spark's physical planner *not* inserting
ShuffleExchange/Sort under a SortMergeJoin when both sides are bucketed
(`index/rules/JoinIndexRule.scala:41-43`; verified via operator-occurrence
diff, `plananalysis/PhysicalOperatorAnalyzer.scala:44-57`). This framework
owns that planning step: Join compiles to SortMergeJoinExec, with
ExchangeExec (hash repartition) + SortExec inserted only when a side is not
already bucketed+sorted on the join keys — so explain() can show the same
Exchange/Sort elision, and execution actually skips the work.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Set, Tuple

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io import columnar, parquet
from hyperspace_tpu.plan import expr as E
from hyperspace_tpu.plan.nodes import (Aggregate, BucketSpec, Filter, Join,
                                       Limit, LogicalPlan, Project, Scan,
                                       Sort, Union)
from hyperspace_tpu.plan.schema import Schema


class PhysicalNode:
    name: str = "Physical"

    @property
    def children(self) -> List["PhysicalNode"]:
        return []

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        raise NotImplementedError

    def execute_bucketed(self, num_buckets: int):
        """Produce (batch concat'd in bucket order, per-bucket lengths) for
        the batched bucketed join. Only meaningful on chains over a
        bucketed scan."""
        raise HyperspaceException(
            f"{type(self).__name__} does not support bucketed execution.")

    def simple_string(self) -> str:
        return self.name

    def tree_string(self, depth: int = 0) -> str:
        lines = [("  " * depth) + ("+- " if depth else "") + self.simple_string()]
        for c in self.children:
            lines.append(c.tree_string(depth + 1))
        return "\n".join(lines)

    def collect(self) -> List["PhysicalNode"]:
        out = [self]
        for c in self.children:
            out.extend(c.collect())
        return out


def _empty_batch(schema: Schema) -> columnar.ColumnBatch:
    import pyarrow as pa
    return columnar.from_arrow(
        pa.table({f.name: pa.array([], type=t.type)
                  for f, t in zip(schema.fields, schema.to_arrow())}), schema)


class ScanExec(PhysicalNode):
    name = "Scan"

    def __init__(self, scan: Scan, columns: Sequence[str]):
        self.scan = scan
        self.columns = list(columns)
        self.out_schema = scan.schema.select(columns)

    def simple_string(self) -> str:
        bucket = (f", buckets={self.scan.bucket_spec.num_buckets}"
                  if self.scan.bucket_spec else "")
        return (f"Scan parquet [{', '.join(self.columns)}] "
                f"{self.scan.root_paths}{bucket}")

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        if bucket is not None:
            if self.scan.bucket_spec is None:
                raise HyperspaceException("Bucket read on unbucketed scan.")
            files: List[str] = []
            for root in self.scan.root_paths:
                files.extend(parquet.bucket_files(root).get(bucket, []))
        else:
            files = self.scan.files()
        if not files:
            return _empty_batch(self.out_schema)
        table = parquet.read_table(files, columns=self.columns)
        batch = columnar.from_arrow(table, self.out_schema)
        if bucket is not None and len(files) > 1:
            # Multiple sorted runs in one bucket (incremental deltas): the
            # concat is not globally sorted — restore order on device.
            from hyperspace_tpu.ops.sort import sort_batch
            sort_cols = [c for c in self.scan.bucket_spec.sort_columns
                         if self.out_schema.contains(c)]
            if sort_cols:
                batch = sort_batch(batch, sort_cols)
        return batch

    def execute_bucketed(self, num_buckets: int):
        """Read all bucket files in bucket order; lengths come from parquet
        metadata — no device work. (The batched join sorts per-bucket ids
        itself, so multi-run buckets need no pre-sort here.)"""
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq

        if self.scan.bucket_spec is None:
            raise HyperspaceException("Bucketed read on unbucketed scan.")
        per_bucket = {}
        for root in self.scan.root_paths:
            for b, files in parquet.bucket_files(root).items():
                per_bucket.setdefault(b, []).extend(files)
        tables = []
        lengths = np.zeros(num_buckets, dtype=np.int64)
        for b in range(num_buckets):
            for f in per_bucket.get(b, []):
                t = pq.read_table(f, columns=self.columns)
                lengths[b] += t.num_rows
                tables.append(t)
        if not tables:
            return _empty_batch(self.out_schema), lengths
        table = pa.concat_tables(tables, promote_options="default")
        return columnar.from_arrow(table, self.out_schema), lengths


class FilterExec(PhysicalNode):
    name = "Filter"

    def __init__(self, condition: E.Expression, child: PhysicalNode,
                 conf=None):
        self.condition = condition
        self.child = child
        self.conf = conf

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        return f"Filter ({self.condition!r})"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        from hyperspace_tpu.engine.compiler import apply_filter
        from hyperspace_tpu.parallel.context import should_distribute
        batch = self.child.execute(bucket)
        if batch.num_rows == 0:
            return batch
        mesh = should_distribute(self.conf, batch.num_rows)
        if mesh is not None:
            from hyperspace_tpu.parallel.scan import distributed_filter
            return distributed_filter(batch, self.condition, mesh)
        return apply_filter(batch, self.condition)

    def execute_bucketed(self, num_buckets: int):
        """Filter preserves bucket grouping: the compaction gather is
        stable-ascending, so surviving rows stay in bucket order; new
        per-bucket lengths are segment sums of the mask."""
        import jax.numpy as jnp
        import numpy as np
        from hyperspace_tpu.engine.compiler import compile_predicate

        batch, lengths = self.child.execute_bucketed(num_buckets)
        if batch.num_rows == 0:
            return batch, lengths
        mask = compile_predicate(self.condition, batch)
        # Per-bucket survivor counts as ONE device segment-sum (row ->
        # bucket via searchsorted over the running lengths), then a single
        # [num_buckets] transfer sizes both the new lengths and the gather.
        import jax
        csum = jnp.cumsum(jnp.asarray(lengths, dtype=jnp.int64))
        row_bucket = jnp.searchsorted(
            csum, jnp.arange(batch.num_rows, dtype=jnp.int64), side="right")
        new_lengths = np.asarray(jax.ops.segment_sum(
            mask.astype(jnp.int32), row_bucket.astype(jnp.int32),
            num_segments=num_buckets)).astype(np.int64)
        count = int(new_lengths.sum())
        (indices,) = jnp.nonzero(mask, size=count, fill_value=0)
        return batch.take(indices), new_lengths


class ProjectExec(PhysicalNode):
    name = "Project"

    def __init__(self, columns: Sequence[str], child: PhysicalNode):
        self.columns = list(columns)
        self.child = child

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        return f"Project [{', '.join(self.columns)}]"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        return self.child.execute(bucket).select(self.columns)

    def execute_bucketed(self, num_buckets: int):
        batch, lengths = self.child.execute_bucketed(num_buckets)
        return batch.select(self.columns), lengths


class ExchangeExec(PhysicalNode):
    """Hash-repartition marker. On one chip it is a pass-through; on a mesh
    it lowers to the all-to-all in `parallel/build.py`. Its presence/absence
    in the plan is the explain() observable, exactly like ShuffleExchange in
    the reference's plan diffs."""

    name = "Exchange"

    def __init__(self, keys: Sequence[str], num_partitions: int,
                 child: PhysicalNode):
        self.keys = list(keys)
        self.num_partitions = num_partitions
        self.child = child

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        return f"Exchange hashpartitioning({', '.join(self.keys)}, {self.num_partitions})"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        return self.child.execute(bucket)


class SortExec(PhysicalNode):
    name = "Sort"

    def __init__(self, keys: Sequence[str], child: PhysicalNode):
        self.keys = list(keys)
        self.child = child

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        return f"Sort [{', '.join(self.keys)}]"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        from hyperspace_tpu.ops.sort import sort_batch
        batch = self.child.execute(bucket)
        if batch.num_rows == 0:
            return batch
        return sort_batch(batch, self.keys)


class AggregateExec(PhysicalNode):
    name = "Aggregate"

    def __init__(self, group_columns: Sequence[str], aggregates,
                 out_schema: Schema, child: PhysicalNode):
        self.group_columns = list(group_columns)
        self.aggregates = list(aggregates)
        self.out_schema = out_schema
        self.child = child

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        aggs = ", ".join(f"{a.func}({a.column})" for a in self.aggregates)
        return f"Aggregate [{', '.join(self.group_columns)}] [{aggs}]"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        from hyperspace_tpu.ops.aggregate import group_aggregate
        return group_aggregate(self.child.execute(bucket),
                               self.group_columns, self.aggregates,
                               self.out_schema)


class LimitExec(PhysicalNode):
    name = "Limit"

    def __init__(self, n: int, child: PhysicalNode):
        self.n = n
        self.child = child

    @property
    def children(self):
        return [self.child]

    def simple_string(self) -> str:
        return f"Limit {self.n}"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        import jax.numpy as jnp
        batch = self.child.execute(bucket)
        if batch.num_rows <= self.n:
            return batch
        return batch.take(jnp.arange(self.n, dtype=jnp.int32))


class UnionExec(PhysicalNode):
    name = "Union"

    def __init__(self, children: Sequence[PhysicalNode]):
        self._children = list(children)

    @property
    def children(self):
        return list(self._children)

    def simple_string(self) -> str:
        return f"Union ({len(self._children)})"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        batches = [c.execute(bucket) for c in self._children]
        non_empty = [b for b in batches if b.num_rows > 0]
        if not non_empty:
            return batches[0]
        if len(non_empty) == 1:
            return non_empty[0]
        return columnar.concat_batches(non_empty)


class SortMergeJoinExec(PhysicalNode):
    name = "SortMergeJoin"

    def __init__(self, left: PhysicalNode, right: PhysicalNode,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 bucketed: bool, num_buckets: int = 0,
                 out_schema: Optional[Schema] = None, how: str = "inner",
                 conf=None):
        self.left = left
        self.right = right
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.bucketed = bucketed
        self.num_buckets = num_buckets
        self.out_schema = out_schema
        self.how = how
        self.conf = conf

    @property
    def children(self):
        return [self.left, self.right]

    def simple_string(self) -> str:
        keys = ", ".join(f"{l}={r}" for l, r in zip(self.left_keys, self.right_keys))
        mode = f"bucketed({self.num_buckets})" if self.bucketed else "global"
        return f"SortMergeJoin {self.how} [{keys}] {mode}"

    def execute(self, bucket: Optional[int] = None) -> columnar.ColumnBatch:
        from hyperspace_tpu.ops.join import sort_merge_join
        if self.bucketed:
            # Co-partitioned bucket joins, batched into ONE compiled program
            # (`ops/bucketed_join.py`): zero shuffle, zero global sort, no
            # per-bucket compile explosion. Buckets are independent ->
            # mesh-parallel in `parallel/join.py`.
            from hyperspace_tpu.ops.bucketed_join import (
                bucketed_sort_merge_join, padded_skew)
            lbatch, l_lengths = self.left.execute_bucketed(self.num_buckets)
            rbatch, r_lengths = self.right.execute_bucketed(self.num_buckets)
            # The mesh path shares the padded [B, L] layout; under hot-key
            # skew route single-chip so the global-join fallback applies.
            skewed = padded_skew(l_lengths, r_lengths, lbatch.num_rows,
                                 rbatch.num_rows)
            mesh = (None if skewed
                    else self._join_mesh(lbatch.num_rows + rbatch.num_rows))
            if mesh is not None:
                from hyperspace_tpu.ops.bucketed_join import (
                    assemble_join_output)
                from hyperspace_tpu.parallel.join import (
                    distributed_bucketed_join_indices)
                li, ri = distributed_bucketed_join_indices(
                    lbatch, rbatch, l_lengths, r_lengths, self.left_keys,
                    self.right_keys, mesh)
                return assemble_join_output(lbatch, rbatch, li, ri)
            return bucketed_sort_merge_join(lbatch, rbatch, l_lengths,
                                            r_lengths, self.left_keys,
                                            self.right_keys, how=self.how)
        lbatch = self.left.execute(bucket)
        rbatch = self.right.execute(bucket)
        # Children end in SortExec, so sides arrive key-sorted.
        return sort_merge_join(lbatch, rbatch, self.left_keys,
                               self.right_keys, presorted=True, how=self.how)

    def _join_mesh(self, total_rows: int):
        """Mesh for the distributed co-bucketed join, or None. Requires an
        inner join (the distributed index path has no outer expansion) and
        the bucket<->shard map (num_buckets divisible by mesh size)."""
        from hyperspace_tpu.parallel.context import (mesh_size,
                                                     should_distribute)
        if self.how != "inner":
            return None
        mesh = should_distribute(self.conf, total_rows)
        if mesh is None or self.num_buckets % mesh_size(mesh) != 0:
            return None
        return mesh


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------


def _join_keys(condition: E.Expression, left_schema: Schema,
               right_schema: Schema) -> Tuple[List[str], List[str]]:
    """Extract equi-join key pairs from an AND-of-equalities condition
    (reference applicability: `JoinIndexRule.scala:179-185,278-317`)."""
    left_keys: List[str] = []
    right_keys: List[str] = []
    for conjunct in E.split_conjunctive(condition):
        if not isinstance(conjunct, E.EqualTo):
            raise HyperspaceException(
                f"Only equi-join conditions are supported; got {conjunct!r}")
        a, b = conjunct.left, conjunct.right
        if not isinstance(a, E.Column) or not isinstance(b, E.Column):
            raise HyperspaceException(
                "Join condition must compare columns directly.")
        if left_schema.contains(a.name) and right_schema.contains(b.name):
            left_keys.append(a.name)
            right_keys.append(b.name)
        elif left_schema.contains(b.name) and right_schema.contains(a.name):
            left_keys.append(b.name)
            right_keys.append(a.name)
        else:
            raise HyperspaceException(
                f"Join columns not found on both sides: {conjunct!r}")
    return left_keys, right_keys


def _underlying_bucket_spec(plan: LogicalPlan) -> Optional[BucketSpec]:
    """The bucket spec of the scan feeding a linear Filter/Project chain —
    filters and projections preserve bucketing and intra-bucket order."""
    node = plan
    while True:
        if isinstance(node, Scan):
            return node.bucket_spec
        if isinstance(node, (Filter, Project)) :
            node = node.child
            continue
        return None


def _required_for(plan: LogicalPlan, required: Set[str]) -> List[str]:
    """required column names resolved against plan schema, in schema order."""
    schema = plan.schema
    lowered = {r.lower() for r in required}
    return [f.name for f in schema.fields if f.name.lower() in lowered]


def plan_physical(plan: LogicalPlan,
                  required: Optional[Set[str]] = None,
                  conf=None) -> PhysicalNode:
    """Logical -> physical with projection pushdown into scans. `conf`
    carries the session's distribution settings to the operators that can
    execute on the mesh (Filter scans, bucketed SMJ)."""
    if required is None:
        required = set(plan.schema.names)

    if isinstance(plan, Scan):
        return ScanExec(plan, _required_for(plan, required))

    if isinstance(plan, Filter):
        child_required = set(required) | plan.condition.references()
        return FilterExec(plan.condition,
                          plan_physical(plan.child, child_required, conf),
                          conf=conf)

    if isinstance(plan, Project):
        child = plan_physical(plan.child, set(plan.columns), conf)
        # Resolve names against the child schema but KEEP the declared order.
        resolved = [plan.child.schema.field(c).name for c in plan.columns]
        return ProjectExec(resolved, child)

    if isinstance(plan, Aggregate):
        child_required = (set(plan.group_columns)
                          | {a.column for a in plan.aggregates
                             if a.column != "*"})
        return AggregateExec(plan.group_columns, plan.aggregates,
                             plan.schema,
                             plan_physical(plan.child, child_required, conf))

    if isinstance(plan, Sort):
        child_required = set(required) | set(plan.columns)
        return SortExec(plan.columns,
                        plan_physical(plan.child, child_required, conf))

    if isinstance(plan, Limit):
        return LimitExec(plan.n, plan_physical(plan.child, required, conf))

    if isinstance(plan, Union):
        # Children may expose different column orders for the same names
        # (index schema vs source schema): normalize through a Project.
        wanted = _required_for(plan, required)
        return UnionExec([
            ProjectExec([c.schema.field(n).name for n in wanted],
                        plan_physical(c, set(wanted), conf))
            for c in plan.children])

    if isinstance(plan, Join):
        if plan.join_type not in ("inner", "left_outer", "right_outer"):
            raise HyperspaceException(
                f"Join type {plan.join_type} not yet supported by the executor.")
        left_keys, right_keys = _join_keys(plan.condition, plan.left.schema,
                                           plan.right.schema)
        left_required = ({n for n in required if plan.left.schema.contains(n)}
                         | set(left_keys))
        right_required = ({n for n in required if plan.right.schema.contains(n)}
                          | set(right_keys))
        left_phys = plan_physical(plan.left, left_required, conf)
        right_phys = plan_physical(plan.right, right_required, conf)

        lspec = _underlying_bucket_spec(plan.left)
        rspec = _underlying_bucket_spec(plan.right)

        def _covers(spec: Optional[BucketSpec], keys: List[str]) -> bool:
            return (spec is not None
                    and [c.lower() for c in spec.bucket_columns]
                    == [k.lower() for k in keys])

        if (_covers(lspec, left_keys) and _covers(rspec, right_keys)
                and lspec.num_buckets == rspec.num_buckets):
            # Shuffle-free, sort-free bucketed SMJ — the indexed fast path.
            return SortMergeJoinExec(left_phys, right_phys, left_keys,
                                     right_keys, bucketed=True,
                                     num_buckets=lspec.num_buckets,
                                     how=plan.join_type, conf=conf)
        # General path: hash exchange + sort on each side.
        num_partitions = max(lspec.num_buckets if lspec else 0,
                             rspec.num_buckets if rspec else 0, 200)
        left_sorted = SortExec(left_keys, ExchangeExec(left_keys,
                                                       num_partitions,
                                                       left_phys))
        right_sorted = SortExec(right_keys, ExchangeExec(right_keys,
                                                         num_partitions,
                                                         right_phys))
        return SortMergeJoinExec(left_sorted, right_sorted, left_keys,
                                 right_keys, bucketed=False,
                                 how=plan.join_type, conf=conf)

    raise HyperspaceException(f"Cannot plan node: {plan!r}")
