"""Whole-stage fusion: operator chains compiled into FEW XLA executables.

The reference's rewrite exists to remove per-stage data movement
(`index/rules/JoinIndexRule.scala:41-43`); on a TPU behind a dispatch
link the same principle applies to OPERATORS: eager per-operator
execution pays a dispatch round-trip per jnp op (~5 ms tunneled; a 26-join
TPC-DS q64 chain runs thousands of them) plus an output-sizing host sync
per operator (~100 ms each). This module fuses maximal chains of
shape-preserving operators — Filter, Project, BroadcastHashJoin — into ONE
jitted executable per chain with MASKED row semantics:

- a Filter contributes its predicate to a running boolean selection mask
  instead of compacting (no sizing sync, no mid-stage gather);
- a Project computes its columns full-length (dead rows compute garbage
  harmlessly — every operator in a region is row-local);
- a BroadcastHashJoin with a unique-keyed build side is ONE gather per
  output column plus a `matched` mask (the direct-address table from
  `ops/broadcast_join.py`, prepared host-side and cached); inner joins
  AND `matched` into the selection, outer joins null the build columns.

One host sync per stage (the selection count, fetched with the stage
output) replaces one-per-operator. Stage leaves (scans, sort-merge
joins, aggregates, unions — anything with data-dependent output shape)
execute eagerly as before and feed the stage as inputs.

Executable reuse: `jax.jit` keys on a canonical stage program
(`_StageProgram`) whose identity covers everything that shapes the trace
— operator structure, expressions (serde dicts), schemas, validity
presence, string-dictionary identity tokens, broadcast table packing —
so re-running the same query hits the in-memory executable cache even
though the physical plan objects are rebuilt per run.

Host-lane stages run the ORIGINAL eager operator graph instead: on
numpy a compaction is free, so eager filters cutting the row count early
beat masked full-length evaluation. The traced masked semantics get CPU
coverage through the device lane on the CPU backend (tests force it via
execution.min.device.rows=0).
"""

from __future__ import annotations

import itertools
import json
import weakref
from collections.abc import MutableMapping as _MutableMapping
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from hyperspace_tpu import telemetry
from hyperspace_tpu.engine.physical import PhysicalNode
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import (ColumnBatch, DeviceColumn,
                                        batch_to_tree, tree_to_batch)
from hyperspace_tpu.plan.schema import Field, Schema


class _FusionIneligible(Exception):
    """Raised at trace/prep time when a region cannot run masked (e.g.
    non-integer broadcast keys); the caller falls back to the original
    eager operator graph — same results, without the fused executable."""


# ---------------------------------------------------------------------------
# Identity tokens: stable per-object ids for arrays whose CONTENT shapes a
# trace (string dictionaries bake searchsorted constants; broadcast tables
# bake their packing). Object identity is enough: warm runs re-serve the
# same cached arrays, and a freed array can never reclaim its token.
# ---------------------------------------------------------------------------

_token_counter = itertools.count()
_tokens: Dict[int, tuple] = {}


def _token_of(obj) -> int:
    if obj is None:
        return -1
    key = id(obj)
    ent = _tokens.get(key)
    if ent is not None and ent[0]() is obj:
        return ent[1]
    tok = next(_token_counter)

    def _drop(_ref, k=key, t=tok):
        # Entry self-removes when its array dies — but only if the slot
        # still belongs to this token (the id may have been reused by a
        # newer array by the time the callback fires).
        cur = _tokens.get(k)
        if cur is not None and cur[1] == t:
            _tokens.pop(k, None)

    try:
        ref = weakref.ref(obj, _drop)
    except TypeError:  # non-weakrefable: pin it (rare)
        ref = (lambda o: (lambda: o))(obj)
    _tokens[key] = (ref, tok)
    return tok


# ---------------------------------------------------------------------------
# Device promotion cache: host (numpy) source columns — dimension tables
# ride the host lane — become device-resident jit arguments ONCE and are
# re-served by token while the host array lives. Without this every
# execution re-transfers dimension payloads over the link.
#
# Both fusion caches hold REAL device memory, so they evict on a BYTE
# budget (conf `spark.hyperspace.fusion.cache.{promote,broadcast}.bytes`
# — the effective values are refreshed from the session conf at each
# fused execution) and report `cache.fusion_{promote,bcast}.*` series
# to the metrics registry.
# ---------------------------------------------------------------------------

_promote_cache: Dict[int, tuple] = {}  # token -> (ref(host src), device)

from hyperspace_tpu import constants as _constants  # noqa: E402

_promote_budget = [_constants.FUSION_PROMOTE_CACHE_BYTES_DEFAULT]
_bcast_budget = [_constants.FUSION_BCAST_CACHE_BYTES_DEFAULT]


def _configure_cache_budgets(conf) -> None:
    """Refresh the effective byte budgets from the session conf (the
    caches are process-wide; sessions sharing a process should agree,
    same caveat as the parquet cache budgets). The transfer engine's
    io.transfer.* knobs refresh on the same cadence — one fused
    execution picks up a session's link tuning."""
    if conf is None:
        return
    _promote_budget[0] = conf.fusion_promote_cache_bytes
    _bcast_budget[0] = conf.fusion_bcast_cache_bytes
    from hyperspace_tpu.io import transfer
    transfer.configure(conf)


def _promote_nbytes(ent) -> int:
    return int(getattr(ent[1], "nbytes", 0))


def _promote_dead(ent) -> bool:
    return ent[0]() is None


def _bcast_nbytes(ent) -> int:
    return int(getattr(ent[0], "nbytes", 0)) if ent is not None else 0


def _evict(cache: dict, name: str, budget_bytes: int, nbytes_of,
           dead=None) -> None:
    """Byte-budget eviction, run on every insert: sweep dead-source
    entries FIRST and unconditionally (a GC'd host source must not pin
    its device buffer until byte pressure — that was a silent HBM
    leak), then drop oldest-inserted entries until held bytes fit the
    budget. Residency lands as `cache.<name>.{bytes_held,entries}`."""
    evicted = 0
    if dead is not None:
        for k in [k for k, v in cache.items() if dead(v)]:
            cache.pop(k, None)
            evicted += 1
    total = sum(nbytes_of(v) for v in cache.values())
    while total > budget_bytes and cache:
        total -= nbytes_of(cache.pop(next(iter(cache))))
        evicted += 1
    telemetry.memory.cache_eviction(name, evicted)
    telemetry.memory.cache_stats(name, total, len(cache))


def _to_device(arr):
    if arr is None or not isinstance(arr, np.ndarray):
        return arr
    tok = _token_of(arr)
    ent = _promote_cache.get(tok)
    if ent is not None and ent[0]() is arr:
        telemetry.memory.cache_hit("fusion_promote")
        return ent[1]
    telemetry.memory.cache_miss("fusion_promote")
    from hyperspace_tpu.io import transfer
    # Cache MISSES are exactly the executions that pay the link; the
    # engine's transfer record (registry histogram + optional span)
    # makes the promotion cost attributable instead of folded into
    # dispatch_s — and big dimension columns ship chunked/windowed like
    # every other crossing.
    out = transfer.get_engine().put(arr)
    try:
        ref = weakref.ref(arr)
    except TypeError:
        ref = (lambda o: (lambda: o))(arr)
    _promote_cache[tok] = (ref, out)
    _evict(_promote_cache, "fusion_promote", _promote_budget[0],
           _promote_nbytes, dead=_promote_dead)
    return out


def _promote_batch(batch: ColumnBatch) -> ColumnBatch:
    if not batch.is_host:
        return batch
    columns = {}
    for name, col in batch.columns.items():
        hashes = col.dict_hashes
        if hashes is not None:
            hashes = (_to_device(hashes[0]), _to_device(hashes[1]))
        columns[name] = DeviceColumn(_to_device(col.data), col.dtype,
                                     _to_device(col.validity),
                                     col.dictionary, hashes)
    return ColumnBatch(batch.schema, columns)


# ---------------------------------------------------------------------------
# Broadcast table prep (host side, cached by build-column identity).
# ---------------------------------------------------------------------------

_bcast_cache: Dict[tuple, object] = {}


def _prepare_broadcast(node, build_batch: ColumnBatch):
    """(table ndarray, mins, ranges) for this join's build side, or None
    when the direct-address path is ineligible (the caller then falls
    back to the eager operator graph, whose own runtime fallback covers
    duplicates/strings/wide ranges). Cached by build key-column identity
    so warm runs skip the host scatter AND the device transfer."""
    membership = node.how in ("left_semi", "left_anti")
    keys = (node.right_keys if node.build_side == "right"
            else node.left_keys)
    if build_batch.num_rows == 0:
        return None  # eager path has exact empty-side shortcuts
    try:
        ident = []
        for k in keys:
            col = build_batch.column(k)
            ident.append((_token_of(col.data), _token_of(col.validity)))
    except HyperspaceException:
        return None
    ck = (membership, tuple(k.lower() for k in keys), tuple(ident))
    if ck in _bcast_cache:
        telemetry.memory.cache_hit("fusion_bcast")
        return _bcast_cache[ck]
    telemetry.memory.cache_miss("fusion_bcast")
    from hyperspace_tpu.ops.broadcast_join import (build_broadcast_table,
                                                   build_membership_table)
    builder = build_membership_table if membership else build_broadcast_table
    out = builder(build_batch, keys)
    if out is not None:
        table, mins, ranges = out
        out = (table, tuple(int(m) for m in mins),
               tuple(int(r) for r in ranges))
    _bcast_cache[ck] = out
    _evict(_bcast_cache, "fusion_bcast", _bcast_budget[0], _bcast_nbytes)
    return out


_INT_KEY_DTYPES = ("int8", "int16", "int32", "int64", "date32",
                   "timestamp", "bool")


# ---------------------------------------------------------------------------
# Region nodes
# ---------------------------------------------------------------------------


class _SourceExec(PhysicalNode):
    """Region leaf: a materialized input. During a fused execution the
    batch slot is pre-loaded; outside one it delegates to the wrapped
    node (the eager-fallback and bucketed-protocol paths)."""

    name = "StageInput"

    def __init__(self, node, index: int):
        self.node = node
        self.index = index
        self._batch: Optional[ColumnBatch] = None

    @property
    def children(self):
        return [self.node]

    def simple_string(self):
        return "StageInput"

    def execute(self, bucket=None):
        if bucket is None and self._batch is not None:
            return self._batch
        return self.node.execute(bucket)

    def execute_bucketed(self, num_buckets: int):
        return self.node.execute_bucketed(num_buckets)


def _region_nodes(root) -> List:
    """All fused operator nodes of a region (stops at _SourceExec)."""
    from hyperspace_tpu.engine.physical import (BroadcastHashJoinExec,
                                                FilterExec, ProjectExec)
    out = []

    def walk(n):
        if isinstance(n, _SourceExec):
            return
        out.append(n)
        if isinstance(n, (FilterExec, ProjectExec)):
            walk(n.child)
        elif isinstance(n, BroadcastHashJoinExec):
            walk(n.left if n.build_side == "right" else n.right)
    walk(root)
    return out


class _StageProgram:
    """Hashable static argument for the jitted stage interpreter. Two
    equal programs MUST trace identically: the key covers the region
    structure and every host-side constant the trace bakes in."""

    def __init__(self, key: str, region, source_meta, tables_meta):
        self.key = key
        self.region = region
        self.source_meta = source_meta  # [(schema, aux, num_rows)] by index
        self.tables_meta = tables_meta  # {slot: (mins, ranges)}

    def __hash__(self):
        return hash(self.key)

    def __eq__(self, other):
        return (isinstance(other, _StageProgram)
                and other.key == self.key)

    def __repr__(self):
        # Stable across instances of the SAME program (the compile
        # tracker's retrace-cause diff keys on argument reprs; the
        # default object repr would make every run look like a delta).
        return f"_StageProgram({hash(self.key) & 0xFFFFFFFF:08x})"


# out-batch metadata captured at trace time, re-served on executable
# cache hits (the jit call only returns arrays).
_OUT_META: Dict[str, tuple] = {}


class _RegistryStats(_MutableMapping):
    """PROCESS-WIDE diagnostics aggregate — stage executions, trace
    misses, seconds dispatching / blocked on the output-sizing sync —
    now BACKED BY the metrics registry (counters `fusion.<key>`): one
    storage, two views. The dict-shaped surface keeps the existing
    consumer contract (`scripts/profile_tpcds.py` resets by key and
    reads after runs); the registry exposes the same numbers to
    `session.metrics_registry()` and the Prometheus dump. Per-QUERY
    attribution of the same quantities lands on the active
    `telemetry.QueryMetrics` (counters `fusion.*`) so concurrent
    queries don't smear each other."""

    _KEYS = ("stage_execs", "trace_misses", "sync_s", "dispatch_s")
    _INT_KEYS = ("stage_execs", "trace_misses")

    def _counter(self, key: str):
        if key not in self._KEYS:
            raise KeyError(key)
        return telemetry.get_registry().counter(f"fusion.{key}")

    def __getitem__(self, key):
        value = self._counter(key).value
        return int(value) if key in self._INT_KEYS else value

    def __setitem__(self, key, value):
        self._counter(key).set(float(value))

    def __delitem__(self, key):
        raise TypeError("fusion.STATS keys are fixed")

    def __iter__(self):
        return iter(self._KEYS)

    def __len__(self):
        return len(self._KEYS)

    def __repr__(self):
        return repr(dict(self))


STATS = _RegistryStats()


def _stat(key: str, value) -> None:
    """THE single mutation path for fusion stage statistics: the
    process registry (which `STATS` views) AND the per-query recorder,
    in one place — so the two scopes cannot drift."""
    telemetry.get_registry().counter(f"fusion.{key}").inc(value)
    if isinstance(value, float):
        telemetry.add_seconds(f"fusion.{key}", value)
    else:
        telemetry.add_count(f"fusion.{key}", value)
# program keys whose trace proved ineligible — skip straight to eager.
_INELIGIBLE_KEYS: set = set()


def _gather_build(src_data, src_validity, hit, matched, xp):
    """THE build-side gather semantics (data, validity) — shared by lazy
    materialization and the post-compaction finalize, so the sites can
    never diverge."""
    g = xp.clip(hit, 0, None)
    data = xp.take(src_data, g, axis=0)
    validity = (matched if src_validity is None
                else xp.take(src_validity, g, axis=0) & matched)
    return data, validity


class _LazyGatherColumn:
    """A broadcast join's build-side column inside a traced stage,
    DEFERRED: most dim payload is only CARRIED to the stage output, where
    the selection then discards the vast majority of rows — gathering it
    full-length through every join would be the stage's dominant data
    movement. The gather materializes lazily if a mid-stage expression
    actually reads the column (trace-time property access; the result is
    cached and re-used); columns still lazy at stage end ship only their
    join's (hit, matched) pair through the executable, and the runtime
    gathers them AFTER compaction — at selection size, not row count.

    Duck-types DeviceColumn (`io/columnar.py`); valid only within one
    traced stage execution."""

    __slots__ = ("_src", "hit", "matched", "dtype", "dictionary",
                 "pair_slot", "source_index", "src_name", "_mat")

    def __init__(self, src, hit, matched, pair_slot: int,
                 source_index: int, src_name: str):
        self._src = src
        self.hit = hit
        self.matched = matched
        self.dtype = src.dtype
        self.dictionary = src.dictionary
        self.pair_slot = pair_slot
        self.source_index = source_index
        self.src_name = src_name
        self._mat = None

    @property
    def materialized(self) -> bool:
        return self._mat is not None

    def _materialize(self):
        if self._mat is None:
            import jax.numpy as jnp
            self._mat = _gather_build(self._src.data, self._src.validity,
                                      self.hit, self.matched, jnp)
        return self._mat

    @property
    def data(self):
        return self._materialize()[0]

    @property
    def validity(self):
        return self._materialize()[1]

    @property
    def dict_hashes(self):
        return self._src.dict_hashes

    @property
    def is_string(self) -> bool:
        return self.dictionary is not None

    @property
    def is_host(self) -> bool:
        return False  # exists only inside the jitted device trace

    def __len__(self) -> int:
        return int(self.hit.shape[0])


# ---------------------------------------------------------------------------
# The masked interpreter (runs INSIDE the jitted device trace; the host
# lane routes to the eager operator graph instead).
# ---------------------------------------------------------------------------


def _interpret(node, env: Dict[int, ColumnBatch], tables: Dict[int, object]):
    from hyperspace_tpu.engine.compiler import compile_predicate
    from hyperspace_tpu.engine.physical import (BroadcastHashJoinExec,
                                                FilterExec, ProjectExec)

    if isinstance(node, _SourceExec):
        return env[node.index], None
    if isinstance(node, FilterExec):
        batch, sel = _interpret(node.child, env, tables)
        mask = compile_predicate(node.condition, batch)
        return batch, (mask if sel is None else sel & mask)
    if isinstance(node, ProjectExec):
        batch, sel = _interpret(node.child, env, tables)
        return node._project(batch), sel
    if isinstance(node, BroadcastHashJoinExec):
        return _interpret_bhj(node, env, tables)
    raise HyperspaceException(f"Unfusible node in region: {node!r}")


def _interpret_bhj(node, env, tables):
    from hyperspace_tpu.ops.broadcast_join import _probe_lookup

    probe_is_left = node.build_side == "right"
    probe_node = node.left if probe_is_left else node.right
    build_node = node.right if probe_is_left else node.left
    probe_keys = node.left_keys if probe_is_left else node.right_keys
    probe_batch, sel = _interpret(probe_node, env, tables)
    build_batch = env[build_node.index]
    table, mins, ranges = tables[node._table_slot]
    for k in probe_keys:
        col = probe_batch.column(k)
        if col.is_string or col.dtype not in _INT_KEY_DTYPES:
            raise _FusionIneligible(f"non-integer probe key {k}")
    looked = _probe_lookup(probe_batch, probe_keys, table, list(mins),
                           list(ranges))
    if looked is None:
        raise _FusionIneligible("probe lookup declined")
    hit, matched = looked
    if isinstance(hit, np.ndarray):
        xp = np
    else:
        import jax.numpy as xp

    if node.how in ("left_semi", "left_anti"):
        want = ~matched if node.how == "left_anti" else matched
        return probe_batch, (want if sel is None else sel & want)

    if node.how == "inner":
        sel = matched if sel is None else sel & matched
    # THE shared output-naming contract (`join_output_plan`) keeps the
    # fused lane and the eager assembly from ever diverging.
    from hyperspace_tpu.ops.bucketed_join import join_output_plan
    left_batch = probe_batch if probe_is_left else build_batch
    right_batch = build_batch if probe_is_left else probe_batch
    plan = join_output_plan(left_batch.schema, right_batch.schema,
                            node.out_columns)

    build_side_tag = "r" if probe_is_left else "l"
    fields, out_columns = [], {}
    for out, side, src, dtype in plan:
        if side == build_side_tag:
            col = build_batch.column(src)
            # Deferred: gathers only if a mid-stage expression reads it;
            # otherwise the stage end gathers at selection size
            # (post-sync) instead of full row count per join.
            out_columns[out] = _LazyGatherColumn(
                col, hit, matched, node._table_slot,
                build_node.index, src)
            fields.append(Field(out, dtype, True))
        else:
            # Probe rows are never unmatched-nulled (outer joins only
            # broadcast their inner side), so probe fields keep their
            # nullability.
            col = probe_batch.column(src)
            out_columns[out] = col
            fields.append(Field(out, dtype,
                                probe_batch.schema.field(src).nullable))
    return ColumnBatch(Schema(fields), out_columns), sel


# ---------------------------------------------------------------------------
# Jitted stage runner (built lazily so importing this module does not pull
# in jax — the package imports jax only at first device use).
# ---------------------------------------------------------------------------

_run_stage_jit = None


def _run_stage(prog: _StageProgram, trees, table_args):
    global _run_stage_jit
    if _run_stage_jit is None:
        # instrumented_jit: each actual trace records a compile span,
        # compile.* counters, and the retrace cause on the query.
        @partial(telemetry.instrumented_jit, "fusion.run_stage",
                 static_argnames=("prog",))
        def _run(prog: _StageProgram, trees, table_args):
            import jax.numpy as jnp

            env = {}
            for i, (schema, aux, _rows) in enumerate(prog.source_meta):
                env[i] = tree_to_batch(trees[i], schema, aux)
            tables = {slot: (table_args[slot], mins, ranges)
                      for slot, (mins, ranges) in prog.tables_meta.items()}
            out_batch, sel = _interpret(prog.region, env, tables)
            # Columns still lazy at stage end ship only their join's
            # (hit, matched) pair; the runtime gathers them at selection
            # size after the compaction sync.
            keep_fields, keep_cols = [], {}
            lazy_specs, lazy_pairs = [], {}
            for f in out_batch.schema.fields:
                col = out_batch.columns[f.name]
                if (isinstance(col, _LazyGatherColumn)
                        and not col.materialized):
                    lazy_pairs[col.pair_slot] = (col.hit, col.matched)
                    lazy_specs.append((f.name, col.pair_slot,
                                       col.source_index, col.src_name,
                                       f.dtype))
                else:
                    keep_fields.append(f)
                    keep_cols[f.name] = col
            reduced = ColumnBatch(Schema(keep_fields), keep_cols)
            out_tree, out_aux = batch_to_tree(reduced)
            _OUT_META[prog.key] = (out_batch.schema, reduced.schema,
                                   out_aux, tuple(lazy_specs))
            if sel is None:
                return out_tree, lazy_pairs, None, None
            return (out_tree, lazy_pairs, sel,
                    jnp.sum(sel.astype(jnp.int64)))

        _run_stage_jit = _run
    return _run_stage_jit(prog, trees, table_args)


_finalize_lazy_jit = None


def _finalize_lazy(idx, lazy_pairs, srcs, spec):
    """ONE jitted gather for every deferred build column of a stage:
    composes hit∘idx per slot and applies `_gather_build`. `spec` is the
    static structure ((slot, has_src_validity), ...); `srcs` pairs each
    spec entry with (src_data, src_validity|None). `idx` None = no
    compaction (full-length gathers)."""
    global _finalize_lazy_jit
    if _finalize_lazy_jit is None:
        @partial(telemetry.instrumented_jit, "fusion.finalize_lazy",
                 static_argnames=("spec", "has_idx"))
        def run(idx, lazy_pairs, srcs, spec, has_idx):
            import jax.numpy as jnp

            composed = {}
            for slot, _ in spec:
                if slot not in composed:
                    hit, matched = lazy_pairs[slot]
                    if has_idx:
                        hit = jnp.take(hit, idx)
                        matched = jnp.take(matched, idx)
                    composed[slot] = (hit, matched)
            out = []
            for (slot, _has_validity), (sd, sv) in zip(spec, srcs):
                hit, matched = composed[slot]
                out.append(_gather_build(sd, sv, hit, matched, jnp))
            return tuple(out)

        _finalize_lazy_jit = run
    import jax.numpy as jnp
    return _finalize_lazy_jit(
        idx if idx is not None else jnp.zeros(0, dtype=jnp.int32),
        lazy_pairs, srcs, spec, idx is not None)


# ---------------------------------------------------------------------------
# FusedStageExec
# ---------------------------------------------------------------------------


class FusedStageExec(PhysicalNode):
    """Physical node executing a fused region. Sources run eagerly first;
    the region then runs as ONE jitted executable with a single
    output-sizing sync (device lane) or as the eager operator graph
    (host lane — early compaction wins on numpy)."""

    name = "FusedStage"

    def __init__(self, root, sources: Sequence[_SourceExec], conf=None):
        self.root = root
        self.sources = list(sources)
        self.conf = conf
        from hyperspace_tpu.engine.physical import BroadcastHashJoinExec
        self._bhj_nodes = [n for n in _region_nodes(root)
                           if isinstance(n, BroadcastHashJoinExec)]
        for slot, n in enumerate(self._bhj_nodes):
            n._table_slot = slot

    @property
    def children(self):
        return [self.root]

    def simple_string(self):
        return f"FusedStage ({len(_region_nodes(self.root))} ops)"

    def execute_bucketed(self, num_buckets: int):
        """Bucketed-protocol passthrough (regions never contain joins on
        this path — only Filter/Project chains support it)."""
        return self.root.execute_bucketed(num_buckets)

    def execute(self, bucket: Optional[int] = None) -> ColumnBatch:
        if bucket is not None:
            return self.root.execute(bucket)
        # Stage-boundary seams: the fault point the chaos harness
        # drives (`fusion.stage`) and the cooperative-cancellation
        # checkpoint — both BEFORE source execution, so an injected
        # fault or an expired deadline costs nothing downstream.
        from hyperspace_tpu.utils import faults
        faults.fire("fusion.stage")
        telemetry.check_deadline("stage")
        _configure_cache_budgets(self.conf)
        for s in self.sources:
            s._batch = s.node.execute()
        try:
            out = self._execute_masked()
            if out is not None:
                return out
            # Eager fallback: the original operator graph, sources served
            # from the already-executed batches.
            return self.root.execute()
        finally:
            for s in self.sources:
                s._batch = None

    # -- masked execution -------------------------------------------------

    def _execute_masked(self) -> Optional[ColumnBatch]:
        batches = [s._batch for s in self.sources]
        if any(b.num_rows == 0 for b in batches):
            telemetry.event("fusion", "lane", lane="eager",
                            trigger="empty-source")
            return None  # eager path has exact empty-side shortcuts
        from hyperspace_tpu.parallel.context import should_distribute
        host = all(b.is_host for b in batches)
        if should_distribute(self.conf, max(b.num_rows for b in batches),
                             host_batch=host) is not None:
            telemetry.event("fusion", "lane", lane="eager",
                            trigger="mesh-distribution")
            return None  # mesh execution owns these operators instead
        if host:
            # Host lane: run the ORIGINAL eager operator graph (before
            # any broadcast-table prep — the eager join builds its own).
            # Masked execution exists to batch device dispatches and
            # syncs; on numpy a compaction is free, so eager filters
            # cutting the row count EARLY beat full-length masked
            # evaluation of every downstream operator (q27-class
            # selective star queries were ~4x slower masked). The traced
            # masked semantics still get CPU coverage through the device
            # lane on the CPU backend (tests force it via
            # execution.min.device.rows=0).
            telemetry.event("fusion", "lane", lane="eager-host",
                            trigger="host-resident sources")
            return self.root.execute()

        preps = {}
        for n in self._bhj_nodes:
            build_node = n.right if n.build_side == "right" else n.left
            prep = _prepare_broadcast(n, build_node._batch)
            if prep is None:
                telemetry.event("fusion", "lane", lane="eager",
                                trigger="broadcast-prep-declined")
                return None
            preps[n._table_slot] = prep
        return self._execute_device(batches, preps)

    def _execute_device(self, batches, preps) -> Optional[ColumnBatch]:
        import jax.numpy as jnp

        key = self._program_key(batches, preps)
        if key in _INELIGIBLE_KEYS:
            telemetry.event("fusion", "lane", lane="eager",
                            trigger="trace-ineligible (cached)")
            return None
        if len(_OUT_META) > 1024:
            # Metadata and executables retire TOGETHER: evicting only
            # _OUT_META would silently force evicted stages eager forever
            # (a jit cache hit never re-runs the traced body that
            # repopulates the metadata). Full reset -> next runs re-trace
            # and re-populate both.
            telemetry.memory.cache_eviction("fusion_trace",
                                            len(_OUT_META))
            _OUT_META.clear()
            try:
                if _run_stage_jit is not None:
                    _run_stage_jit.clear_cache()
            except Exception:
                pass
        source_meta = []
        trees = {}
        promoted = []
        for i, b in enumerate(batches):
            b = _promote_batch(b)
            promoted.append(b)
            tree, aux = batch_to_tree(b)
            trees[i] = tree
            source_meta.append((b.schema, aux, b.num_rows))
        table_args = {slot: _to_device(p[0]) for slot, p in preps.items()}
        tables_meta = {slot: (p[1], p[2]) for slot, p in preps.items()}
        prog = _StageProgram(key, self.root, source_meta, tables_meta)
        import time as _time
        _stat("stage_execs", 1)
        cache_hit = key in _OUT_META
        if not cache_hit and key not in _INELIGIBLE_KEYS:
            _stat("trace_misses", 1)
        if cache_hit:
            telemetry.memory.cache_hit("fusion_trace")
        else:
            telemetry.memory.cache_miss("fusion_trace")
        telemetry.memory.cache_stats("fusion_trace", None, len(_OUT_META))
        telemetry.event("fusion", "trace-cache",
                        hit=cache_hit, ops=len(_region_nodes(self.root)))
        # Last checkpoint before committing to the jitted dispatch (a
        # cold stage pays an XLA trace here — don't start one a
        # cancelled query will never consume).
        telemetry.check_deadline("stage")
        t0 = _time.perf_counter()
        try:
            with telemetry.span("fusion:dispatch", "fusion",
                                ops=len(_region_nodes(self.root)),
                                cache_hit=cache_hit):
                out_tree, lazy_pairs, sel, cnt = _run_stage(prog, trees,
                                                            table_args)
        except _FusionIneligible as exc:
            _INELIGIBLE_KEYS.add(key)
            telemetry.event("fusion", "lane", lane="eager",
                            trigger=f"trace-ineligible ({exc})")
            return None
        _stat("dispatch_s", _time.perf_counter() - t0)
        # Span boundary of the stage dispatch: the working set (sources,
        # broadcast tables, stage outputs) is device-resident here.
        telemetry.memory.maybe_sample()
        meta = _OUT_META.get(key)
        if meta is None:
            # Executable outlived its evicted metadata (>256 distinct
            # stage programs since): run this one eagerly.
            telemetry.event("fusion", "lane", lane="eager",
                            trigger="metadata-evicted")
            return None
        telemetry.event("fusion", "lane", lane="masked-device",
                        trigger="device-resident sources")
        schema, reduced_schema, aux, lazy_specs = meta
        base = tree_to_batch(out_tree, reduced_schema, aux)
        idx = None
        if sel is not None:
            t0 = _time.perf_counter()
            with telemetry.span("fusion:sync", "fusion"):
                count = int(cnt)  # THE stage sync
            _stat("sync_s", _time.perf_counter() - t0)
            (idx,) = jnp.nonzero(sel, size=count, fill_value=0)
            idx = idx.astype(jnp.int32)
            base = base.take(idx)
        if not lazy_specs:
            return base
        # Deferred build-side gathers, AT SELECTION SIZE: compose each
        # lazy column's hit chain with the compaction index and gather
        # from the promoted source batch (same arrays the trace saw) —
        # all columns through ONE jitted executable, not per-column
        # eager dispatches (`ColumnBatch.take`'s own rationale).
        spec = []
        srcs = []
        src_cols = []
        for out_name, slot, source_index, src_name, dtype in lazy_specs:
            src = promoted[source_index].column(src_name)
            spec.append((slot, src.validity is not None))
            srcs.append((src.data, src.validity))
            src_cols.append((out_name, dtype, src))
        gathered = _finalize_lazy(idx, lazy_pairs, tuple(srcs),
                                  tuple(spec))
        columns = dict(base.columns)
        for (out_name, dtype, src), (data, validity) in zip(src_cols,
                                                            gathered):
            columns[out_name] = DeviceColumn(data, dtype, validity,
                                             src.dictionary,
                                             src.dict_hashes)
        return ColumnBatch(schema, columns)

    def _program_key(self, batches, preps) -> str:
        parts = [_node_key(self.root)]
        for b in batches:
            cols = []
            for f in b.schema.fields:
                col = b.columns[f.name]
                cols.append((f.name, f.dtype, col.validity is not None,
                             _token_of(col.dictionary)))
            parts.append(repr(cols))
        for slot in sorted(preps):
            _t, mins, ranges = preps[slot]
            parts.append(f"T{slot}:{mins}:{ranges}")
        return "\x1e".join(parts)


def _node_key(node) -> str:
    from hyperspace_tpu.engine.physical import (BroadcastHashJoinExec,
                                                FilterExec, ProjectExec)
    if isinstance(node, _SourceExec):
        return f"S{node.index}"
    if isinstance(node, FilterExec):
        return (f"F({json.dumps(node.condition.to_dict(), sort_keys=True)})"
                f"[{_node_key(node.child)}]")
    if isinstance(node, ProjectExec):
        entries = [(name, src if isinstance(src, str)
                    else json.dumps(src.to_dict(), sort_keys=True))
                   for name, src in node.entries]
        return f"P({entries!r})[{_node_key(node.child)}]"
    if isinstance(node, BroadcastHashJoinExec):
        probe = node.left if node.build_side == "right" else node.right
        build = node.right if node.build_side == "right" else node.left
        cols = (sorted(node.out_columns)
                if node.out_columns is not None else None)
        return (f"B({node.how},{node.build_side},{node.left_keys},"
                f"{node.right_keys},{cols},{node._table_slot},"
                f"S{build.index})[{_node_key(probe)}]")
    raise HyperspaceException(f"Unfusible node in region: {node!r}")


# ---------------------------------------------------------------------------
# The fusion pass
# ---------------------------------------------------------------------------


def fuse_physical(root, conf=None):
    """Rewrite a physical tree, replacing maximal Filter/Project/
    BroadcastHashJoin regions with FusedStageExec. Sort-merge joins keep
    their subtrees intact on the bucketed path (the (batch, lengths)
    protocol and Exchange/Sort unwrapping are planner contracts); their
    general-path inner children still fuse."""
    from hyperspace_tpu.engine.physical import (BroadcastHashJoinExec,
                                                ExchangeExec, FilterExec,
                                                ProjectExec, ReusedExec,
                                                SortExec, SortMergeJoinExec)
    fusible = (FilterExec, ProjectExec, BroadcastHashJoinExec)
    seen: Dict[int, object] = {}

    def rec(node):
        hit = seen.get(id(node))
        if hit is not None:
            return hit
        if isinstance(node, fusible):
            sources: List[_SourceExec] = []
            new_root = build_region(node, sources)
            out = FusedStageExec(new_root, sources, conf=conf)
        elif isinstance(node, SortMergeJoinExec):
            if not node.bucketed:
                # General path: the join unwraps Sort(Exchange(child))
                # wrappers itself — fuse the inner children, keep the
                # wrapper chain.
                for attr in ("left", "right"):
                    side = getattr(node, attr)
                    inner_holder, inner_attr = None, None
                    probe = side
                    if isinstance(probe, SortExec):
                        inner_holder, inner_attr = probe, "child"
                        probe = probe.child
                    if isinstance(probe, ExchangeExec):
                        inner_holder, inner_attr = probe, "child"
                        probe = probe.child
                    if inner_holder is None:
                        setattr(node, attr, rec(side))
                    else:
                        setattr(inner_holder, inner_attr, rec(probe))
            out = node
        else:
            if isinstance(node, ReusedExec):
                node.child = rec(node.child)
            elif hasattr(node, "_children"):  # UnionExec
                node._children = [rec(c) for c in node._children]
            else:
                for attr in ("child", "left", "right"):
                    c = getattr(node, attr, None)
                    if c is not None and hasattr(c, "execute"):
                        setattr(node, attr, rec(c))
            out = node
        seen[id(node)] = out
        return out

    def build_region(node, sources: List[_SourceExec]):
        if isinstance(node, FilterExec):
            return FilterExec(node.condition, build_region(node.child,
                                                           sources),
                              conf=node.conf)
        if isinstance(node, ProjectExec):
            return ProjectExec(list(node.entries),
                               build_region(node.child, sources))
        if isinstance(node, BroadcastHashJoinExec):
            probe_attr = "left" if node.build_side == "right" else "right"
            build_attr = "right" if node.build_side == "right" else "left"
            probe = build_region(getattr(node, probe_attr), sources)
            build = _SourceExec(rec(getattr(node, build_attr)),
                                len(sources))
            sources.append(build)
            sides = {probe_attr: probe, build_attr: build}
            return BroadcastHashJoinExec(
                sides["left"], sides["right"], node.left_keys,
                node.right_keys, node.build_side, how=node.how,
                conf=node.conf, out_columns=node.out_columns)
        src = _SourceExec(rec(node), len(sources))
        sources.append(src)
        return src

    return rec(root)
