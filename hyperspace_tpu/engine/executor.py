"""Plan execution entry points."""

from __future__ import annotations

from typing import Optional, Sequence

import itertools
import uuid

import hyperspace_tpu.engine  # noqa: F401  (x64 config)
from hyperspace_tpu.engine.physical import PhysicalNode, plan_physical
from hyperspace_tpu.io.columnar import ColumnBatch
from hyperspace_tpu.plan.nodes import LogicalPlan

# Profiler capture naming: fast back-to-back queries can share a
# wall-clock stamp, so names carry a process-unique counter. The
# capture itself serializes inside `telemetry.profiler.device_trace`
# (jax permits one active profiler session per process).
_trace_seq = itertools.count()
_trace_run_id = uuid.uuid4().hex[:8]


def compile_plan(plan: LogicalPlan,
                 projection: Optional[Sequence[str]] = None,
                 conf=None, fuse: Optional[bool] = None) -> PhysicalNode:
    """Logical -> executable physical plan. `fuse=None` follows the conf
    (whole-stage fusion on by default); explain/analysis paths pass
    fuse=False — the operator tree IS the display contract (Exchange/Sort
    elision diff), and fusion groups operators without changing them."""
    required = set(projection) if projection is not None else None
    physical = plan_physical(plan, required, conf)
    if projection is not None:
        from hyperspace_tpu.engine.physical import ProjectExec
        physical = ProjectExec(list(projection), physical)
    if fuse is None:
        fuse = conf is None or conf.fusion_enabled
    if fuse:
        from hyperspace_tpu.engine.fusion import fuse_physical
        physical = fuse_physical(physical, conf=conf)
    return physical


def _scalar_subqueries(plan: LogicalPlan):
    """Every ScalarSubquery expression reachable from `plan` (conditions,
    projections, aggregate inputs) — subquery plans are NOT descended
    into here; resolution recurses through execute_plan instead."""
    from hyperspace_tpu.plan import expr as E
    from hyperspace_tpu.plan.nodes import (Aggregate, Filter, Join, Project,
                                           Window)

    found = []

    def walk_expr(e):
        if isinstance(e, E.ScalarSubquery):
            found.append(e)
            return
        # children already includes In values and CaseWhen branches.
        for c in e.children:
            walk_expr(c)

    def visit(node):
        if isinstance(node, Filter):
            walk_expr(node.condition)
        elif isinstance(node, Project):
            for c in node.columns:
                if not isinstance(c, str):
                    walk_expr(c)
        elif isinstance(node, Join) and node.condition is not None:
            walk_expr(node.condition)
        elif isinstance(node, (Aggregate, Window)):
            for spec in (node.aggregates if isinstance(node, Aggregate)
                         else node.specs):
                if spec.is_expression:
                    walk_expr(spec.column)
        for c in node.children:
            visit(c)

    visit(plan)
    return found


def _resolve_scalar_subqueries(plan: LogicalPlan, conf) -> None:
    """Execute every unresolved scalar subquery in `plan` and cache its
    value on the node (the subquery-execution phase; Spark does the same
    before the main plan runs). One column required; one row -> value,
    zero rows -> SQL NULL, more -> error. Nested subqueries resolve
    through the recursive execute_plan call."""
    import numpy as np

    for sub in _scalar_subqueries(plan):
        if sub._resolved:
            continue
        batch = execute_plan(sub.execution_plan(), conf=conf)
        if batch.num_rows > 1:
            from hyperspace_tpu.exceptions import HyperspaceException
            raise HyperspaceException(
                f"Scalar subquery returned {batch.num_rows} rows.")
        if batch.num_rows == 0:
            sub.resolve(None)
            continue
        (field,) = batch.schema.fields
        col = batch.columns[field.name]
        if col.validity is not None and not bool(
                np.asarray(col.validity)[0]):
            sub.resolve(None)
            continue
        raw = np.asarray(col.data)[0]
        if col.is_string:
            sub.resolve(str(col.dictionary[int(raw)]))
        elif field.dtype == "bool":
            sub.resolve(bool(raw))
        elif field.dtype in ("float32", "float64"):
            sub.resolve(float(raw))
        else:
            sub.resolve(int(raw))


def execute_plan(plan: LogicalPlan,
                 projection: Optional[Sequence[str]] = None,
                 conf=None) -> ColumnBatch:
    import time as _time

    from hyperspace_tpu import telemetry

    _resolve_scalar_subqueries(plan, conf)
    t0 = _time.perf_counter()
    physical = compile_plan(plan, projection, conf)
    # Physical planning + fusion grouping time, per query (device-side
    # XLA compiles happen lazily inside operators, not here).
    telemetry.add_seconds("plan_s", _time.perf_counter() - t0)
    trace_dir = conf.trace_dir if conf is not None else None
    if not trace_dir:
        return physical.execute()
    # Native tracing (SURVEY §5): one XLA profiler capture per executed
    # query — device compute, transfers, and host gaps land in the same
    # timeline; inspect with TensorBoard/XProf or Perfetto. The capture
    # routes through the ONE device-profiler seam
    # (`telemetry/profiler.py`), which serializes concurrent sessions.
    from hyperspace_tpu.telemetry import profiler

    seq = next(_trace_seq)
    capture = f"{trace_dir.rstrip('/')}/query-{_trace_run_id}-{seq:05d}"
    telemetry.event("profiler", "capture", path=capture)
    with profiler.device_trace(capture):
        out = physical.execute()
        # Materialize ALL device work inside the capture window —
        # validity masks and dictionary hashes included, or their
        # compute/transfers land after the capture closes.
        for col in out.columns.values():
            for arr in (col.data, col.validity,
                        *(col.dict_hashes or ())):
                if hasattr(arr, "block_until_ready"):
                    arr.block_until_ready()
    return out
