"""Plan execution entry points."""

from __future__ import annotations

from typing import Optional, Sequence

import itertools
import threading
import uuid

import hyperspace_tpu.engine  # noqa: F401  (x64 config)
from hyperspace_tpu.engine.physical import PhysicalNode, plan_physical
from hyperspace_tpu.io.columnar import ColumnBatch
from hyperspace_tpu.plan.nodes import LogicalPlan

# Profiler capture naming/serialization: jax permits one active profiler
# session per process, and fast queries can share a wall-clock stamp.
_trace_seq = itertools.count()
_trace_run_id = uuid.uuid4().hex[:8]
_trace_lock = threading.Lock()


def compile_plan(plan: LogicalPlan,
                 projection: Optional[Sequence[str]] = None,
                 conf=None, fuse: Optional[bool] = None) -> PhysicalNode:
    """Logical -> executable physical plan. `fuse=None` follows the conf
    (whole-stage fusion on by default); explain/analysis paths pass
    fuse=False — the operator tree IS the display contract (Exchange/Sort
    elision diff), and fusion groups operators without changing them."""
    required = set(projection) if projection is not None else None
    physical = plan_physical(plan, required, conf)
    if projection is not None:
        from hyperspace_tpu.engine.physical import ProjectExec
        physical = ProjectExec(list(projection), physical)
    if fuse is None:
        fuse = conf is None or conf.fusion_enabled
    if fuse:
        from hyperspace_tpu.engine.fusion import fuse_physical
        physical = fuse_physical(physical, conf=conf)
    return physical


def execute_plan(plan: LogicalPlan,
                 projection: Optional[Sequence[str]] = None,
                 conf=None) -> ColumnBatch:
    physical = compile_plan(plan, projection, conf)
    trace_dir = conf.trace_dir if conf is not None else None
    if not trace_dir:
        return physical.execute()
    # Native tracing (SURVEY §5): one XLA profiler capture per executed
    # query — device compute, transfers, and host gaps land in the same
    # timeline; inspect with TensorBoard/XProf or Perfetto. Capture names
    # use a process-unique counter (wall-clock ms collide for fast
    # back-to-back queries, and jax allows one active profiler session).
    import jax

    seq = next(_trace_seq)
    capture = f"{trace_dir.rstrip('/')}/query-{_trace_run_id}-{seq:05d}"
    with _trace_lock:
        with jax.profiler.trace(capture):
            out = physical.execute()
            # Materialize ALL device work inside the capture window —
            # validity masks and dictionary hashes included, or their
            # compute/transfers land after the capture closes.
            for col in out.columns.values():
                for arr in (col.data, col.validity,
                            *(col.dict_hashes or ())):
                    if hasattr(arr, "block_until_ready"):
                        arr.block_until_ready()
    return out
