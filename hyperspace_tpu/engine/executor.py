"""Plan execution entry points."""

from __future__ import annotations

from typing import Optional, Sequence

import hyperspace_tpu.engine  # noqa: F401  (x64 config)
from hyperspace_tpu.engine.physical import PhysicalNode, plan_physical
from hyperspace_tpu.io.columnar import ColumnBatch
from hyperspace_tpu.plan.nodes import LogicalPlan


def compile_plan(plan: LogicalPlan,
                 projection: Optional[Sequence[str]] = None,
                 conf=None) -> PhysicalNode:
    required = set(projection) if projection is not None else None
    physical = plan_physical(plan, required, conf)
    if projection is not None:
        from hyperspace_tpu.engine.physical import ProjectExec
        physical = ProjectExec(list(projection), physical)
    return physical


def execute_plan(plan: LogicalPlan,
                 projection: Optional[Sequence[str]] = None,
                 conf=None) -> ColumnBatch:
    return compile_plan(plan, projection, conf).execute()
