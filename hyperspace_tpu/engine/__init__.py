"""Execution engine: XLA-compiled columnar query execution.

x64 is enabled at engine import: lake data routinely carries int64 keys and
float64 measures, and silent 32-bit truncation would corrupt results. The
perf-critical kernels (hashing, sort keys) deliberately operate on 32-bit
lanes internally (see `ops/hash_partition.py`), so the TPU fast path is not
sacrificed.
"""

import hyperspace_tpu._jax_config  # noqa: F401
