"""Continuous-ingest coordinator — micro-batch appends + incremental
refresh WHILE the serve plane runs.

The paper's hybrid-scan story (appended files served as a remainder
scan until the next refresh) implies a loop nobody owns in the
reference: something must land source appends on a cadence and drive
the refresh that folds them into the index — without starving the
queries it is refreshing FOR. `IngestCoordinator` is that loop's body.

Design rules, in order of importance:

1. **Lease path only.** Every refresh goes through the session's
   collection manager (`refresh(name, mode='incremental')`), i.e. the
   exact transactional route a manual `hs.refresh_index` takes:
   stale-writer lease recovery in validate, one-winner OCC on the
   op-log slot in begin, commit-marker protocol, action reports.
   `scripts/check_metrics_coverage.py` bans direct maintenance-verb
   construction anywhere under `engine/` — a coordinator that bypassed
   the lease seam could corrupt an index the moment a manual verb raced
   it. The coordinator also never calls `recover` — forced recovery
   cancels LIVE writers; the lease decides staleness, not the cadence.
2. **Serve pressure defers refresh, never blocks appends.** The same
   gate shape the advisor uses: while queries wait for admission, or
   admitted bytes exceed `ingest.serve.headroom` of the serving HBM
   budget, the tick lands its appends (the source grows either way —
   hybrid scan keeps results correct) and defers the refresh
   (`ingest.deferred`). Freshness yields to latency; the staleness
   gauge and its alert rule make the cost visible.
3. **Conflicts concede.** Losing the op-log race to a manual refresher
   is a clean outcome, not an error: the refresh is retried under the
   shared `utils/retry` policy (bounded attempts, deterministic
   jittered backoff — no sleep-in-except) and, still losing, concedes
   with `ingest.conflicts` + a "conceded" decision. Exactly one writer
   ever wins; the appends are picked up next tick.
4. **Caller-threaded.** `run_once()` is synchronous; the owner (bench
   harness, a cron, a test) drives it on `ingest.interval.seconds`.
   The engine's thread seam keeps background threads in the scheduler;
   an injected crash (BaseException) propagates to the caller like a
   process death and the NEXT tick's lease recovery heals the log.

Staleness: `ingest.staleness.seconds` = now − t(newest append not yet
covered by a committed refresh), 0.0 when every index has caught up.
An append is covered once a refresh that STARTED after it commits, per
index; the gauge tracks the least-caught-up index. `telemetry/alerts`
ships a default `ingest_staleness` rule over this gauge.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from hyperspace_tpu.constants import STABLE_STATES, States
from hyperspace_tpu.exceptions import HyperspaceException

__all__ = ["IngestCoordinator"]

# Lifecycle states that mean "another writer is mid-flight right now" —
# a refresh hitting one of these lost a race, it did not fail.
_TRANSIENT_STATES = tuple(
    s for s in (States.CREATING, States.DELETING, States.REFRESHING,
                States.VACUUMING, States.RESTORING, States.CANCELLING,
                States.OPTIMIZING)
    if s not in STABLE_STATES)


class IngestCoordinator:
    """One micro-batch ingest loop body: append, gate, refresh, account.

    `producer` is an optional callable invoked once per tick; it appends
    the tick's micro-batch to the source and returns the appended file
    paths (empty/None for a quiet tick). External writers can instead
    report their appends via `record_append` so staleness accounting
    stays truthful. `indexes` names the indexes to refresh each tick —
    the collection manager dispatches mode='incremental' by kind
    (bucketed delta for covering, sketch append for skipping).
    """

    def __init__(self, session,
                 producer: Optional[Callable[[], Optional[Iterable[str]]]]
                 = None,
                 indexes: Sequence[str] = ()):
        self.session = session
        self.conf = session.conf
        self.producer = producer
        self.indexes: List[str] = list(indexes)
        self._lock = threading.Lock()
        # (t_appended, path) per append not yet trimmed; trimmed once
        # every index's last covering refresh started after it.
        self._append_log: List[Tuple[float, str]] = []
        # Per index: start time of the newest COMMITTED refresh (0.0
        # until the first one commits — everything is uncovered).
        self._covered: Dict[str, float] = {n: 0.0 for n in self.indexes}

    # -- gates -------------------------------------------------------------

    def serving_pressure(self) -> Optional[str]:
        """A human-readable reason to defer refresh this tick, or None
        when serving is quiet enough (the advisor's gate shape)."""
        from hyperspace_tpu.engine.scheduler import get_scheduler
        try:
            p = get_scheduler().pressure()
        except Exception:
            return None
        if p.get("queue_depth", 0) > 0:
            return f"{p['queue_depth']} queries waiting for admission"
        budget = self.conf.serve_hbm_budget_bytes
        if budget and budget > 0:
            headroom = max(0.0, min(self.conf.ingest_serve_headroom, 1.0))
            if p.get("admitted_bytes", 0) > budget * headroom:
                return (f"admitted {p['admitted_bytes']} B exceeds "
                        f"{headroom:.0%} of the {budget} B serving "
                        "budget")
        return None

    # -- staleness accounting ----------------------------------------------

    def record_append(self, paths: Iterable[str],
                      at: Optional[float] = None) -> None:
        """Report externally-landed appends for staleness accounting."""
        with self._lock:
            self._record_append(list(paths), at)
            self._update_staleness()

    def _record_append(self, paths: List[str],
                       at: Optional[float] = None) -> None:
        if not paths:
            return
        t = time.time() if at is None else float(at)
        self._append_log.extend((t, p) for p in paths)
        from hyperspace_tpu import telemetry
        telemetry.get_registry().counter("ingest.appends").inc(len(paths))

    def staleness_s(self, now: Optional[float] = None) -> float:
        with self._lock:
            return self._staleness(now)

    def _staleness(self, now: Optional[float] = None) -> float:
        now = time.time() if now is None else now
        floor = min(self._covered.values()) if self._covered else 0.0
        # Appends older than every index's last refresh start are
        # covered by a committed version; trim them.
        self._append_log = [e for e in self._append_log if e[0] > floor]
        if not self._append_log:
            return 0.0
        newest = max(t for t, _ in self._append_log)
        return max(0.0, now - newest)

    def _update_staleness(self) -> None:
        from hyperspace_tpu import telemetry
        telemetry.get_registry().gauge(
            "ingest.staleness.seconds").set(self._staleness())

    # -- conflict classification -------------------------------------------

    @staticmethod
    def _is_conflict(exc: BaseException) -> bool:
        """True when a refresh lost a one-winner race: the OCC op-log
        slot was taken (begin), or validate saw another writer's
        transient state. Both are clean concessions, not failures."""
        if not isinstance(exc, HyperspaceException):
            return False
        msg = str(exc)
        if "operation is in progress" in msg:
            return True
        return any(f"current state is {s}" in msg
                   for s in _TRANSIENT_STATES)

    # -- the tick ----------------------------------------------------------

    def run_once(self) -> dict:
        """One micro-batch tick: land the producer's appends, defer the
        refresh under serve pressure, otherwise refresh every owned
        index through the lease path with conflict concession. Returns
        a decision dict (the advisor's reporting shape). An injected
        crash propagates — the caller models process death; the next
        tick's lease recovery heals the op log."""
        from hyperspace_tpu import telemetry
        with self._lock:
            reg = telemetry.get_registry()
            reg.counter("ingest.ticks").inc()
            decision: dict = {"action": "refreshed", "appended": 0,
                              "refreshes": []}
            if self.producer is not None:
                try:
                    appended = list(self.producer() or [])
                except Exception as exc:
                    reg.counter("ingest.failures").inc()
                    decision.update(action="failed",
                                    reason=f"producer: {exc!r}")
                    telemetry.event("ingest", "decision", **decision)
                    self._update_staleness()
                    return decision
                self._record_append(appended)
                decision["appended"] = len(appended)
            reason = self.serving_pressure()
            if reason is not None:
                reg.counter("ingest.deferred").inc()
                decision.update(action="deferred", reason=reason)
                telemetry.event("ingest", "decision", action="deferred",
                                reason=reason,
                                appended=decision["appended"])
                self._update_staleness()
                return decision
            for name in self.indexes:
                decision["refreshes"].append(self._refresh_one(name))
            if any(r["action"] != "refreshed"
                   for r in decision["refreshes"]):
                decision["action"] = "partial"
            self._update_staleness()
            return decision

    def _refresh_one(self, name: str) -> dict:
        from hyperspace_tpu import telemetry
        from hyperspace_tpu.facade import Hyperspace
        from hyperspace_tpu.utils import retry

        reg = telemetry.get_registry()
        # The refresh lists the source when it runs; appends landed
        # before this point are covered once it commits.
        listed_at = time.time()
        manager = Hyperspace.get_context(
            self.session).index_collection_manager
        saw_conflict = [False]

        def classify(exc: Exception) -> bool:
            if self._is_conflict(exc):
                saw_conflict[0] = True
                return True
            return False

        policy = retry.RetryPolicy(
            attempts=max(1, self.conf.ingest_conflict_attempts),
            base_ms=self.conf.io_retry_base_ms,
            max_ms=self.conf.io_retry_max_ms)
        try:
            retry.call(lambda: manager.refresh(name, "incremental"),
                       operation=f"ingest.refresh.{name}",
                       policy=policy, retryable=classify)
        except Exception as exc:
            if self._is_conflict(exc):
                reg.counter("ingest.conflicts").inc()
                out = {"index": name, "action": "conceded",
                       "reason": str(exc)}
            else:
                reg.counter("ingest.failures").inc()
                out = {"index": name, "action": "failed",
                       "reason": repr(exc)}
            telemetry.event("ingest", "decision", **out)
            return out
        if saw_conflict[0]:
            # Raced a manual refresher and won after backoff — the
            # conflict happened even though this tick recovered.
            reg.counter("ingest.conflicts").inc()
        reg.counter("ingest.refreshes").inc()
        self._covered[name] = listed_at
        out = {"index": name, "action": "refreshed"}
        telemetry.event("ingest", "decision", **out)
        return out
