"""Session: the user's entry point to the engine + optimizer hook.

Parity: the reference plugs its rules into Spark's
`sessionState.experimentalMethods.extraOptimizations` via
`enableHyperspace()` (`package.scala:46-51`); here the session owns its
optimizer rule list directly. Rule ORDER matters and matches the reference
(`package.scala:23-34`): JoinIndexRule before FilterIndexRule, because once
a rule fires on a relation no second rule may fire on it.
"""

from __future__ import annotations

import os
from typing import List, Optional

import hyperspace_tpu.engine  # noqa: F401  (x64 config)
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.plan.schema import Schema


class HyperspaceSession:
    def __init__(self, conf: Optional[HyperspaceConf] = None):
        self.conf = conf or HyperspaceConf()
        self._rules: List = []
        self._hyperspace_enabled = False
        self._views: dict = {}
        self._last_query_metrics = None
        self._default_tenant = None
        self._closed = False
        # Session knobs -> the process-wide pipelined transfer engine
        # (io.transfer.{chunk,inflight,threads}); refreshed again at
        # each fused execution so late conf.set calls take effect.
        from hyperspace_tpu.io import transfer
        transfer.configure(self.conf)
        # Warm-start compilation: `spark.hyperspace.compile.cache.dir`
        # wires jax's persistent compilation cache so a fresh replica's
        # first canonical-shape query loads persisted executables
        # instead of tracing (no-op when the knob is unset).
        from hyperspace_tpu.telemetry import compilation
        compilation.configure_persistent_cache(self.conf)
        # Operations plane: `spark.hyperspace.telemetry.ops.port`
        # starts the background timeseries sampler and the pull-based
        # /metrics | /healthz | /timeseries HTTP server (localhost by
        # default; no-op when the knob is unset).
        from hyperspace_tpu.telemetry import ops_server
        ops_server.configure(self.conf)

    # -- serving plane ----------------------------------------------------

    def scheduler(self):
        """The PROCESS-WIDE query scheduler every `collect` routes
        through (`engine/scheduler.py`): admission control against the
        serving HBM budget, the bounded wait queue, per-query deadlines
        + cancellation, and the per-index degradation circuit breakers.
        Sessions share it, same caveat as the transfer engine."""
        from hyperspace_tpu.engine.scheduler import get_scheduler
        return get_scheduler()

    def tenant(self, tenant=None) -> "HyperspaceSession":
        """Set this session's STICKY billing tenant: every subsequent
        `collect` through this session charges `tenant` — admission
        quotas, weighted-fair dequeue weight, per-tenant SLO window,
        and the `tenant.<id>.*` chargeback counters all key on it.
        `collect(tenant=...)` overrides per call; `tenant(None)`
        reverts to the "default" tenant. This (with the scheduler it
        feeds) is the ONE sanctioned tenant seam — the metrics-coverage
        lint bans raw tenant-contextvar writes elsewhere. Returns self
        for chaining: `session.tenant("acme").read_parquet(...)`."""
        self._default_tenant = str(tenant) if tenant else None
        if self._default_tenant is not None:
            from hyperspace_tpu import telemetry
            telemetry._note_tenant(self._default_tenant)
        return self

    def active_queries(self) -> List[str]:
        """Ids of queries currently queued or running (process-wide) —
        the targets `cancel` accepts. A query learns its own id as
        `metrics.query_id` (`collect(with_metrics=True)`)."""
        return self.scheduler().active_queries()

    def cancel(self, query_id: str) -> bool:
        """Cooperatively cancel a queued or running query: its
        `collect` raises a typed `QueryCancelledError` at the next
        checkpoint (operator / fusion-stage / transfer-chunk / write
        boundary). True iff the id was live. Cancellation is a request,
        not preemption — in-flight device work unwinds through the
        normal release paths."""
        return self.scheduler().cancel(query_id)

    def close(self, timeout_s: float = 10.0) -> None:
        """Shut this session down, IDEMPOTENTLY: cancel its live
        queries, wait (bounded) for them to drain from the scheduler,
        and flush the flight recorder's pending slow-query dumps. The
        process-wide executors (scheduler, transfer engine, IO pool)
        stay up for co-resident sessions; interpreter teardown drains
        them via their atexit hooks. A closed session refuses new
        collects."""
        if self._closed:
            return
        self._closed = True
        sched = self.scheduler()
        sched.cancel_session(self)
        sched.drain_session(self, timeout_s=timeout_s)
        from hyperspace_tpu import telemetry
        telemetry.flight.get_recorder().drain()

    def last_query_metrics(self):
        """`telemetry.QueryMetrics` of the most recent query executed
        through this session (collect/to_pandas/count), or None. Each
        query records into its own instance — concurrent sessions (and
        concurrent queries on one session) never share a recorder; this
        slot simply holds whichever query on this session FINISHED
        last."""
        return self._last_query_metrics

    def flight_recorder(self):
        """The PROCESS-WIDE query flight recorder: the bounded ring of
        the last-K completed `QueryMetrics` across every session
        (always on), plus the slow-query dump policy driven by
        `spark.hyperspace.telemetry.slowlog.{seconds,dir,keep}` on the
        executing session's conf. `recorder.queries(5)` is the last
        five finished queries, newest last."""
        from hyperspace_tpu import telemetry
        return telemetry.get_recorder()

    def metrics_registry(self):
        """The PROCESS-WIDE metrics registry: counters, gauges, and
        log-bucketed histograms aggregating across every query, session,
        and index-maintenance action since process start (fusion stage
        stats, link-transfer bytes/seconds, action-report counters,
        mesh dispatch stats). One registry per process — sessions share
        it; `registry.to_text()` is the Prometheus scrape payload."""
        from hyperspace_tpu import telemetry
        return telemetry.get_registry()

    # -- data sources -----------------------------------------------------

    def read_parquet(self, *paths: str, schema: Optional[Schema] = None):
        from hyperspace_tpu.engine.dataframe import DataFrame
        if not paths:
            raise HyperspaceException("read_parquet requires at least one path.")
        if schema is None:
            import pyarrow.parquet as pq
            import glob as _glob
            from hyperspace_tpu.utils import storage
            probe = paths[0]
            if storage.is_url(probe):
                fs, real = storage.get_fs(probe)
                if fs.isdir(real):
                    candidates = sorted(
                        f for f in fs.find(real) if f.endswith(".parquet"))
                    if not candidates:
                        raise HyperspaceException(
                            f"No parquet files under {probe}")
                    real = candidates[0]
                with fs.open(real, "rb") as f:
                    schema = Schema.from_arrow(pq.read_schema(f))
                return DataFrame(Scan(list(paths), schema), self)
            # (local branch below probes with os paths)
            if os.path.isdir(probe):
                candidates = sorted(
                    _glob.glob(os.path.join(probe, "**", "*.parquet"),
                               recursive=True))
                if not candidates:
                    raise HyperspaceException(f"No parquet files under {probe}")
                probe = candidates[0]
            schema = Schema.from_arrow(pq.read_schema(probe))
        return DataFrame(Scan(list(paths), schema), self)

    def create_dataframe(self, table):
        """Arrow table / pandas DataFrame -> DataFrame backed by a temp
        parquet spill (all scans are file-backed, like the reference's
        relations)."""
        import tempfile
        import pyarrow as pa
        import pyarrow.parquet as pq
        if not isinstance(table, pa.Table):
            table = pa.Table.from_pandas(table, preserve_index=False)
        tmpdir = tempfile.mkdtemp(prefix="hyperspace_df_")
        pq.write_table(table, os.path.join(tmpdir, "part-0.parquet"))
        return self.read_parquet(tmpdir)

    # -- named sources (temp views) ---------------------------------------
    #
    # Spark temp-view parity (the reference's E2E suite covers view-served
    # index queries, `E2EHyperspaceRulesTests` view cases): a view is a
    # NAME bound to a logical plan, expanded at `table()` time — so the
    # rewrite rules see the underlying relations and index signatures
    # match exactly as for a directly-built DataFrame, and serialized
    # plans (log entries) capture the expansion, never the name.

    def create_or_replace_temp_view(self, name: str, df) -> None:
        self._views[name.lower()] = df.plan

    def create_temp_view(self, name: str, df) -> None:
        if name.lower() in self._views:
            raise HyperspaceException(f"Temp view already exists: {name}")
        self._views[name.lower()] = df.plan

    def table(self, name: str):
        """DataFrame over a registered temp view (expanded plan)."""
        from hyperspace_tpu.engine.dataframe import DataFrame
        plan = self._views.get(name.lower())
        if plan is None:
            raise HyperspaceException(f"Unknown table or view: {name}")
        return DataFrame(plan, self)

    def drop_temp_view(self, name: str) -> bool:
        return self._views.pop(name.lower(), None) is not None

    # -- optimizer plumbing ----------------------------------------------

    def enable_hyperspace(self) -> "HyperspaceSession":
        """Plug the rewrite rule batch (reference `package.scala:46-51`)."""
        from hyperspace_tpu.plan.rules.join_index import JoinIndexRule
        from hyperspace_tpu.plan.rules.filter_index import FilterIndexRule
        if not self._hyperspace_enabled:
            self._rules = [JoinIndexRule(self), FilterIndexRule(self)]
            self._hyperspace_enabled = True
        return self

    def disable_hyperspace(self) -> "HyperspaceSession":
        """Reference `package.scala:58-63`."""
        self._rules = []
        self._hyperspace_enabled = False
        return self

    @property
    def is_hyperspace_enabled(self) -> bool:
        return self._hyperspace_enabled

    def optimize(self, plan: LogicalPlan) -> LogicalPlan:
        for rule in self._rules:
            plan = rule.apply(plan)
        # Scalar subqueries embedded in expressions carry their own
        # plans; the rules rewrite those too (Spark applies the optimizer
        # to subquery plans the same way). The rewrite lands in a
        # side-slot (`_opt_plan`), refreshed EVERY optimize — including
        # rules-off, which restores the plain plan — so the original
        # expression the user holds is never mutated.
        from hyperspace_tpu.engine.executor import _scalar_subqueries
        for sub in _scalar_subqueries(plan):
            sub._opt_plan = (self.optimize(sub.plan) if self._rules
                             else None)
        return plan
