"""Explain: physical-plan diff with rules enabled vs disabled.

Parity: reference `index/plananalysis/PlanAnalyzer.scala:45-360` — plans the
query twice (rules on / rules off, saving and restoring the enabled state),
highlights differing subtrees, emits "Plan with indexes / Plan without
indexes / Indexes used" sections, and in verbose mode appends the operator
occurrence diff table.
"""

from __future__ import annotations

from typing import List, Sequence

from hyperspace_tpu.engine.physical import PhysicalNode, ScanExec
from hyperspace_tpu.plananalysis import op_analyzer
from hyperspace_tpu.plananalysis.buffer_stream import BufferStream
from hyperspace_tpu.plananalysis.display_mode import get_display_mode


class PlanAnalyzer:
    @staticmethod
    def explain_string(df, session, index_summaries: Sequence,
                       verbose: bool = False, metrics=None) -> str:
        """Reference `PlanAnalyzer.scala:45-126`. Pass a
        `telemetry.QueryMetrics` (e.g. `session.last_query_metrics()` or
        the `collect(with_metrics=True)` companion) as `metrics` to
        append the runtime numbers — per-operator timings/rows, lane and
        rule decision events — under the plan diff, so the what-changed
        and the what-it-cost views read as one report."""
        was_enabled = session.is_hyperspace_enabled
        try:
            session.enable_hyperspace()
            _, _, plan_with = df.explain_plans()
            session.disable_hyperspace()
            _, _, plan_without = df.explain_plans()
        finally:
            if was_enabled:
                session.enable_hyperspace()
            else:
                session.disable_hyperspace()

        mode = get_display_mode(session.conf)
        buffer = BufferStream(mode)

        with_lines: List[tuple] = []
        without_lines: List[tuple] = []
        PlanAnalyzer._lockstep_diff(plan_with, plan_without, 0,
                                    with_lines, without_lines)

        buffer.write_line("=============================================================")
        buffer.write_line("Plan with indexes:")
        buffer.write_line("=============================================================")
        for line, highlighted in with_lines:
            if highlighted:
                buffer.highlight_line(line)
            else:
                buffer.write_line(line)
        buffer.write_line()

        buffer.write_line("=============================================================")
        buffer.write_line("Plan without indexes:")
        buffer.write_line("=============================================================")
        for line, highlighted in without_lines:
            if highlighted:
                buffer.highlight_line(line)
            else:
                buffer.write_line(line)
        buffer.write_line()

        buffer.write_line("=============================================================")
        buffer.write_line("Indexes used:")
        buffer.write_line("=============================================================")
        for name, location in PlanAnalyzer._indexes_used(plan_with,
                                                         index_summaries):
            buffer.write_line(f"{name}:{location}")
        buffer.write_line()

        if verbose:
            buffer.write_line("=============================================================")
            buffer.write_line("Physical operator stats:")
            buffer.write_line("=============================================================")
            for line in op_analyzer.stats_table(plan_with,
                                                plan_without).splitlines():
                buffer.write_line(line)
            buffer.write_line()

        if metrics is not None:
            buffer.write_line("=============================================================")
            buffer.write_line("Runtime metrics (last execution):")
            buffer.write_line("=============================================================")
            for line in metrics.format_tree().splitlines():
                buffer.write_line(line)
            buffer.write_line()

        return buffer.to_string()

    # -- lockstep subtree diff -------------------------------------------
    #
    # Reference `PlanAnalyzer.scala:56-101`: both physical plans are
    # walked in lockstep top-down; while paired nodes are equal the line
    # prints plain and the walk recurses pairwise into the children, and
    # at the first difference BOTH differing subtrees are emitted fully
    # highlighted. Unlike a line-set diff, repeated identical operator
    # lines (e.g. two `Sort [key]` nodes of which only one was elided)
    # classify by POSITION, not by text membership.

    @staticmethod
    def _fmt(node: PhysicalNode, depth: int) -> str:
        # First line of tree_string at this depth — ONE source of truth
        # for plan rendering, so highlighted and plain sections align.
        return node.tree_string(depth).splitlines()[0]

    @staticmethod
    def _node_equal(a: PhysicalNode, b: PhysicalNode) -> bool:
        """Node-level equality; scans compare by root paths (reference
        `PlanAnalyzer.scala:189-200` — FileSourceScanExec equality is
        root-path equality)."""
        if type(a) is not type(b):
            return False
        if isinstance(a, ScanExec):
            return sorted(a.scan.root_paths) == sorted(b.scan.root_paths)
        return a.simple_string() == b.simple_string()

    @staticmethod
    def _emit_subtree(node: PhysicalNode, depth: int, out: List[tuple],
                      highlighted: bool) -> None:
        for line in node.tree_string(depth).splitlines():
            out.append((line, highlighted))

    @staticmethod
    def _lockstep_diff(a: PhysicalNode, b: PhysicalNode, depth: int,
                       out_a: List[tuple], out_b: List[tuple]) -> None:
        if (PlanAnalyzer._node_equal(a, b)
                and len(a.children) == len(b.children)):
            out_a.append((PlanAnalyzer._fmt(a, depth), False))
            out_b.append((PlanAnalyzer._fmt(b, depth), False))
            for ca, cb in zip(a.children, b.children):
                PlanAnalyzer._lockstep_diff(ca, cb, depth + 1, out_a, out_b)
        else:
            PlanAnalyzer._emit_subtree(a, depth, out_a, True)
            PlanAnalyzer._emit_subtree(b, depth, out_b, True)

    @staticmethod
    def _indexes_used(plan: PhysicalNode, index_summaries: Sequence
                      ) -> List[tuple]:
        """Match scan root paths against the index catalog (reference
        `PlanAnalyzer.scala:209-221`, scan equality = root path equality);
        the containment matching itself lives in `index/manager.py`
        (shared with the telemetry index-usage reports)."""
        from hyperspace_tpu.index.manager import summaries_for_roots

        roots = [root for node in plan.collect() if isinstance(node, ScanExec)
                 for root in node.scan.root_paths]
        return [(s.name, s.index_location)
                for s in summaries_for_roots(index_summaries, roots)]
