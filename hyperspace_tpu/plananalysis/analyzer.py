"""Explain: physical-plan diff with rules enabled vs disabled.

Parity: reference `index/plananalysis/PlanAnalyzer.scala:45-360` — plans the
query twice (rules on / rules off, saving and restoring the enabled state),
highlights differing subtrees, emits "Plan with indexes / Plan without
indexes / Indexes used" sections, and in verbose mode appends the operator
occurrence diff table.
"""

from __future__ import annotations

from typing import List, Sequence

from hyperspace_tpu.engine.physical import PhysicalNode, ScanExec
from hyperspace_tpu.plananalysis import op_analyzer
from hyperspace_tpu.plananalysis.buffer_stream import BufferStream
from hyperspace_tpu.plananalysis.display_mode import get_display_mode


class PlanAnalyzer:
    @staticmethod
    def explain_string(df, session, index_summaries: Sequence,
                       verbose: bool = False) -> str:
        """Reference `PlanAnalyzer.scala:45-126`."""
        was_enabled = session.is_hyperspace_enabled
        try:
            session.enable_hyperspace()
            _, _, plan_with = df.explain_plans()
            session.disable_hyperspace()
            _, _, plan_without = df.explain_plans()
        finally:
            if was_enabled:
                session.enable_hyperspace()
            else:
                session.disable_hyperspace()

        mode = get_display_mode(session.conf)
        buffer = BufferStream(mode)

        with_lines = plan_with.tree_string().splitlines()
        without_lines = plan_without.tree_string().splitlines()
        # Highlight lines unique to each side (differing subtrees).
        with_set, without_set = set(with_lines), set(without_lines)

        buffer.write_line("=============================================================")
        buffer.write_line("Plan with indexes:")
        buffer.write_line("=============================================================")
        for line in with_lines:
            if line in without_set:
                buffer.write_line(line)
            else:
                buffer.highlight_line(line)
        buffer.write_line()

        buffer.write_line("=============================================================")
        buffer.write_line("Plan without indexes:")
        buffer.write_line("=============================================================")
        for line in without_lines:
            if line in with_set:
                buffer.write_line(line)
            else:
                buffer.highlight_line(line)
        buffer.write_line()

        buffer.write_line("=============================================================")
        buffer.write_line("Indexes used:")
        buffer.write_line("=============================================================")
        for name, location in PlanAnalyzer._indexes_used(plan_with,
                                                         index_summaries):
            buffer.write_line(f"{name}:{location}")
        buffer.write_line()

        if verbose:
            buffer.write_line("=============================================================")
            buffer.write_line("Physical operator stats:")
            buffer.write_line("=============================================================")
            for line in op_analyzer.stats_table(plan_with,
                                                plan_without).splitlines():
                buffer.write_line(line)
            buffer.write_line()

        return buffer.to_string()

    @staticmethod
    def _indexes_used(plan: PhysicalNode, index_summaries: Sequence
                      ) -> List[tuple]:
        """Match scan root paths against the index catalog (reference
        `PlanAnalyzer.scala:209-221`, scan equality = root path equality)."""
        import os

        def contains(parent: str, child: str) -> bool:
            parent = os.path.normpath(parent)
            child = os.path.normpath(child)
            return child == parent or child.startswith(parent + os.sep)

        used = []
        roots = [root for node in plan.collect() if isinstance(node, ScanExec)
                 for root in node.scan.root_paths]
        for summary in index_summaries:
            if any(contains(summary.index_location, root)
                   or contains(root, summary.index_location)
                   for root in roots):
                used.append((summary.name, summary.index_location))
        return used
