"""Operator-occurrence diff of two physical plans.

Parity: reference `index/plananalysis/PhysicalOperatorAnalyzer.scala:30-58` —
counts operator occurrences in both plans and spells out the
shuffle/broadcast operators; the Exchange row is how shuffle elimination is
made visible to users.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Tuple

from hyperspace_tpu.engine.physical import PhysicalNode


def count_operators(plan: PhysicalNode) -> Counter:
    return Counter(node.name for node in plan.collect())


def compare(with_index: PhysicalNode, without_index: PhysicalNode
            ) -> List[Tuple[str, int, int]]:
    """(operator, count with indexes, count without indexes), sorted by
    name, only rows where either count is nonzero."""
    a = count_operators(with_index)
    b = count_operators(without_index)
    names = sorted(set(a) | set(b))
    return [(n, a.get(n, 0), b.get(n, 0)) for n in names]


def stats_table(with_index: PhysicalNode, without_index: PhysicalNode) -> str:
    rows = compare(with_index, without_index)
    header = ("Physical Operator", "Hyperspace Disabled", "Hyperspace Enabled",
              "Difference")
    table_rows = [(name, str(without), str(with_), str(with_ - without))
                  for name, with_, without in rows]
    widths = [max(len(header[i]), *(len(r[i]) for r in table_rows))
              for i in range(4)] if table_rows else [len(h) for h in header]

    def fmt(cells):
        return "| " + " | ".join(c.ljust(widths[i])
                                 for i, c in enumerate(cells)) + " |"

    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    lines = [sep, fmt(header), sep]
    lines += [fmt(r) for r in table_rows]
    lines.append(sep)
    return "\n".join(lines)
