"""String builder honoring the display mode.

Parity: reference `index/plananalysis/BufferStream.scala:23-83`
(`writeLine`/`write`/`highlight`/`withTag`).
"""

from __future__ import annotations

from hyperspace_tpu.plananalysis.display_mode import DisplayMode


class BufferStream:
    def __init__(self, mode: DisplayMode):
        self.mode = mode
        self._parts: list[str] = []

    def write(self, text: str = "") -> "BufferStream":
        self._parts.append(text)
        return self

    def write_line(self, text: str = "") -> "BufferStream":
        self._parts.append(text + self.mode.newline)
        return self

    def highlight(self, text: str) -> "BufferStream":
        self._parts.append(self.mode.highlight(text))
        return self

    def highlight_line(self, text: str) -> "BufferStream":
        self._parts.append(self.mode.highlight(text) + self.mode.newline)
        return self

    def to_string(self) -> str:
        return "".join(self._parts)
