"""Explain output display modes.

Parity: reference `index/plananalysis/DisplayMode.scala:24-89` —
PlainTextMode (`<----`/`---->`), HTMLMode (`<b style=...>`), ConsoleMode
(ANSI green), with tags configurable via
`spark.hyperspace.explain.displayMode.highlight.{begin,end}Tag`.
"""

from __future__ import annotations

from hyperspace_tpu import constants
from hyperspace_tpu.config import HyperspaceConf


class DisplayMode:
    begin_tag: str = ""
    end_tag: str = ""
    newline: str = "\n"

    def highlight(self, text: str) -> str:
        return f"{self.begin_tag}{text}{self.end_tag}"


class PlainTextMode(DisplayMode):
    def __init__(self, conf: HyperspaceConf | None = None):
        conf = conf or HyperspaceConf()
        self.begin_tag = conf.get(constants.HIGHLIGHT_BEGIN_TAG, "<----")
        self.end_tag = conf.get(constants.HIGHLIGHT_END_TAG, "---->")


class ConsoleMode(DisplayMode):
    def __init__(self, conf: HyperspaceConf | None = None):
        conf = conf or HyperspaceConf()
        self.begin_tag = conf.get(constants.HIGHLIGHT_BEGIN_TAG, "[32m")
        self.end_tag = conf.get(constants.HIGHLIGHT_END_TAG, "[0m")


class HTMLMode(DisplayMode):
    newline = "<br>"

    def __init__(self, conf: HyperspaceConf | None = None):
        conf = conf or HyperspaceConf()
        self.begin_tag = conf.get(constants.HIGHLIGHT_BEGIN_TAG,
                                  '<b style="background:LightGreen">')
        self.end_tag = conf.get(constants.HIGHLIGHT_END_TAG, "</b>")


def get_display_mode(conf: HyperspaceConf) -> DisplayMode:
    name = conf.get(constants.DISPLAY_MODE, constants.DisplayModeNames.PLAIN_TEXT)
    if name == constants.DisplayModeNames.HTML:
        return HTMLMode(conf)
    if name == constants.DisplayModeNames.CONSOLE:
        return ConsoleMode(conf)
    return PlainTextMode(conf)
