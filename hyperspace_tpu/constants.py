"""Framework-wide constants: config keys, op-log layout, lifecycle states.

Parity: reference `index/IndexConstants.scala:21-50` and
`actions/Constants.scala:19-33`. Config keys keep the reference's
`spark.hyperspace.*` spelling (so existing user configs translate 1:1) and the
`hyperspace.*` short form is accepted as an alias (see `config.py`).
"""

INDEXES_DIR = "indexes"

# Config keys (reference `index/IndexConstants.scala:24-35`).
INDEX_SYSTEM_PATH = "spark.hyperspace.system.path"
INDEX_CREATION_PATH = "spark.hyperspace.index.creation.path"
INDEX_SEARCH_PATHS = "spark.hyperspace.index.search.paths"
INDEX_NUM_BUCKETS = "spark.hyperspace.index.num.buckets"
# The reference defaults numBuckets to spark.sql.shuffle.partitions (= 200).
# On TPU the analogous width is chosen to divide evenly over typical mesh
# sizes; 200 is kept for drop-in config parity.
INDEX_NUM_BUCKETS_DEFAULT = 200

INDEX_CACHE_EXPIRY_DURATION_SECONDS = (
    "spark.hyperspace.index.cache.expiryDurationInSeconds")
INDEX_CACHE_EXPIRY_DURATION_SECONDS_DEFAULT = 300

# Decoded-batch cache budgets (no reference analog — Spark's block manager
# owns executor memory there). Session-conf keys; when unset, the
# HYPERSPACE_READ_CACHE_BYTES / HYPERSPACE_DEVICE_CACHE_BYTES env vars
# (read at `io/parquet.py` import) provide the process-wide defaults.
# The device budget shares HBM with join/sort working sets — size it
# against the largest query, not the chip.
READ_CACHE_BYTES_KEY = "spark.hyperspace.cache.read.bytes"
DEVICE_CACHE_BYTES_KEY = "spark.hyperspace.cache.device.bytes"

# HBM segment cache (`io/segcache.py`): byte budget for device-resident
# index segments (falls back to the legacy `cache.device.bytes` key,
# then the HYPERSPACE_SEGMENT_CACHE_BYTES / HYPERSPACE_DEVICE_CACHE_BYTES
# env defaults), and a comma-separated list of index names whose
# segments are PINNED — never evicted by byte pressure (invalidation on
# refresh/optimize/vacuum still drops them). When a serving budget
# (`serve.hbm.budget.bytes`) is set, the cache's effective budget is
# additionally capped by what that budget leaves after non-cache device
# residency (one truth with the admission controller).
SEGMENT_CACHE_BYTES_KEY = "spark.hyperspace.cache.segments.bytes"
SEGMENT_CACHE_PIN_INDEXES = "spark.hyperspace.cache.segments.pin.indexes"

# Tiered segment cache: host-RAM tier below HBM (`io/segcache.py`).
# When > 0, a segment evicted from the device tier by byte pressure is
# DEMOTED into a host-resident copy (decoded columns fetched D2H once)
# instead of dropped outright, up to this many host bytes (host LRU
# past the budget evicts for real). A later read of a demoted key
# re-promotes through the TransferEngine fill lane — H2D cost paid,
# parquet decode skipped. 0 (the default) disables the tier: eviction
# drops, exactly the pre-tier behavior. Invalidation (refresh/vacuum/
# drop) sweeps both tiers.
SEGMENT_CACHE_HOST_BYTES_KEY = "spark.hyperspace.cache.segments.host.bytes"
SEGMENT_CACHE_HOST_BYTES_DEFAULT = 0

# Fusion cache byte budgets: the device-promotion cache (host source
# columns promoted to device-resident jit arguments, keyed by host-array
# identity) and the broadcast-table cache (direct-address join tables,
# keyed by build-column identity) evict dead-source entries first, then
# oldest-inserted, until held bytes fit the budget. Both hold REAL HBM
# on device backends — size them against the chip, and read their
# residency as `cache.fusion_promote.*` / `cache.fusion_bcast.*` in the
# metrics registry.
FUSION_PROMOTE_CACHE_BYTES = "spark.hyperspace.fusion.cache.promote.bytes"
FUSION_PROMOTE_CACHE_BYTES_DEFAULT = 1 * 1024 ** 3
FUSION_BCAST_CACHE_BYTES = "spark.hyperspace.fusion.cache.broadcast.bytes"
FUSION_BCAST_CACHE_BYTES_DEFAULT = 256 * 1024 * 1024

# Broadcast-join size threshold in estimated decoded bytes; <= 0 disables
# (the analog of Spark's `spark.sql.autoBroadcastJoinThreshold`, which
# the reference leans on for dimension joins and its E2E suite pins to
# -1 to force the SMJ path, `E2EHyperspaceRulesTests.scala:42`). Default
# matches Spark's 10 MB.
BROADCAST_THRESHOLD = "spark.hyperspace.broadcast.threshold"
BROADCAST_THRESHOLD_DEFAULT = 10 * 1024 * 1024

# Object-store OCC: backends with no create precondition (neither GCS
# generation match nor S3 conditional put nor atomic exclusive create)
# make write_log RAISE, because check-then-create corrupts the op log
# under concurrency — unless this conf explicitly accepts single-writer
# semantics.
SINGLE_WRITER = "spark.hyperspace.single.writer"

# Storage-IO retry policy (`utils/retry.py`, the ONE backoff point in the
# package — the metrics-coverage lint fails any ad-hoc sleep-in-except
# loop elsewhere). Exponential backoff with deterministic per-operation
# jitter; transient errors (connection resets, timeouts, HTTP 429/5xx,
# torn reads of in-flight publishes) retry up to `attempts` total tries,
# permanent errors (not-found, permission, 4xx) fail immediately.
IO_RETRY_ATTEMPTS = "spark.hyperspace.io.retry.attempts"
IO_RETRY_ATTEMPTS_DEFAULT = 5
IO_RETRY_BASE_MS = "spark.hyperspace.io.retry.base.ms"
IO_RETRY_BASE_MS_DEFAULT = 20
IO_RETRY_MAX_MS = "spark.hyperspace.io.retry.max.ms"
IO_RETRY_MAX_MS_DEFAULT = 2000

# Pipelined transfer engine (`io/transfer.py`, THE host<->device link
# seam): chunk granularity of large H2D stagings, the bounded in-flight
# byte window across all outstanding puts, and the staging-thread pool
# width (decode/convert of chunk i+1 overlaps chunk i's transfer).
# Tune chunk.bytes against the link: small enough that several chunks
# pipeline, large enough that the per-put dispatch latency amortizes.
IO_TRANSFER_CHUNK_BYTES = "spark.hyperspace.io.transfer.chunk.bytes"
IO_TRANSFER_CHUNK_BYTES_DEFAULT = 4 * 1024 * 1024
IO_TRANSFER_INFLIGHT_BYTES = "spark.hyperspace.io.transfer.inflight.bytes"
IO_TRANSFER_INFLIGHT_BYTES_DEFAULT = 64 * 1024 * 1024
IO_TRANSFER_THREADS = "spark.hyperspace.io.transfer.threads"
IO_TRANSFER_THREADS_DEFAULT = 2
# Bound on how long a put may wait for in-flight-window headroom. A put
# that died without releasing its bytes (dead link, hung runtime) would
# otherwise block every later caller forever; past the timeout the
# waiter raises a TYPED transient error (`TransferAcquireTimeoutError`,
# a TimeoutError — `utils/retry.py` classifies it retryable) and counts
# `io.transfer.acquire_timeouts`. <= 0 disables the bound.
IO_TRANSFER_ACQUIRE_TIMEOUT_MS = \
    "spark.hyperspace.io.transfer.acquire.timeout.ms"
IO_TRANSFER_ACQUIRE_TIMEOUT_MS_DEFAULT = 30_000

# Serving plane (`engine/scheduler.py`): every DataFrame.collect routes
# through the process-wide QueryScheduler. Admission control budgets
# concurrent queries' projected HBM footprints against
# `serve.hbm.budget.bytes` (0, the default, disables budgeting — every
# query admits immediately); queries that do not fit wait in a bounded
# FIFO queue of depth `serve.queue.depth`, and when the queue is full
# the caller gets a typed QueryRejectedError at once — backpressure,
# not silent pile-up. `serve.deadline.seconds` gives every query a
# default deadline (0 = none; `collect(timeout=...)` overrides per
# call), enforced cooperatively at operator / fusion-stage / transfer-
# chunk / sorted-run-write boundaries.
SERVE_HBM_BUDGET_BYTES = "spark.hyperspace.serve.hbm.budget.bytes"
SERVE_HBM_BUDGET_BYTES_DEFAULT = 0
SERVE_QUEUE_DEPTH = "spark.hyperspace.serve.queue.depth"
SERVE_QUEUE_DEPTH_DEFAULT = 32
SERVE_DEADLINE_SECONDS = "spark.hyperspace.serve.deadline.seconds"
SERVE_DEADLINE_SECONDS_DEFAULT = 0.0

# Inter-query batched execution (`engine/batcher.py`): concurrent
# point/filter queries sharing one execution signature (same scan
# identity + pinned index version + predicate SHAPE, literals free)
# coalesce into ONE jitted predicate program over the shared resident
# segments — PR-8's coalescing dedupes the cache FILL, this dedupes the
# EXECUTION. The first query of a signature gathers joiners for
# `batch.window.ms` (skipped entirely when nothing else is in flight,
# so serial latency is untouched), up to `batch.max` cohort members per
# invocation; predicate constants ride padded power-of-two lanes so the
# cohort size is a compile-time bucket, not a retrace per K.
# `batch.aot.warmup` pre-compiles the canonical cohort-size buckets the
# first time a signature is seen (and via the explicit
# `engine.batcher.warmup(df)` replica API), riding the persistent
# compile cache (`compile.cache.dir`) so a fresh replica's first
# batched query loads executables instead of tracing.
SERVE_BATCH_ENABLED = "spark.hyperspace.serve.batch.enabled"
SERVE_BATCH_ENABLED_DEFAULT = "true"
SERVE_BATCH_WINDOW_MS = "spark.hyperspace.serve.batch.window.ms"
SERVE_BATCH_WINDOW_MS_DEFAULT = 2.0
SERVE_BATCH_MAX = "spark.hyperspace.serve.batch.max"
SERVE_BATCH_MAX_DEFAULT = 16
SERVE_BATCH_AOT_WARMUP = "spark.hyperspace.serve.batch.aot.warmup"
SERVE_BATCH_AOT_WARMUP_DEFAULT = "true"

# Degradation circuit breaker (per index): after `breaker.failures`
# IndexDataUnavailableError fallbacks within `breaker.window.seconds`,
# the breaker OPENS and queries selecting that index skip straight to
# the source plan without re-paying the failed index scan. After
# `breaker.cooldown.seconds` one probe query is allowed through
# (half-open); success closes the breaker, failure re-opens it.
SERVE_BREAKER_FAILURES = "spark.hyperspace.serve.breaker.failures"
SERVE_BREAKER_FAILURES_DEFAULT = 3
SERVE_BREAKER_WINDOW_SECONDS = "spark.hyperspace.serve.breaker.window.seconds"
SERVE_BREAKER_WINDOW_SECONDS_DEFAULT = 60.0
SERVE_BREAKER_COOLDOWN_SECONDS = \
    "spark.hyperspace.serve.breaker.cooldown.seconds"
SERVE_BREAKER_COOLDOWN_SECONDS_DEFAULT = 30.0

# Sliding-window SLO tracking (`engine/scheduler.py`): when
# `slo.p99.seconds` > 0, every completed query's wall is folded into a
# sliding window of `slo.window.seconds`, queries over the target count
# as `serve.slo.violations`, and the `serve.slo.burn_rate` gauge is the
# observed violation fraction over the 1% a p99 objective allows
# (burn 1.0 = burning the error budget exactly as fast as allowed; > 1
# = the SLO is failing). `slo.shed.enabled` (OFF by default) arms the
# shedding hook: while the burn rate exceeds 1.0, the admission wait
# queue is tightened to half its configured depth, and each query
# rejected by the tightened (rather than the configured) depth counts
# `serve.slo.shed` — controlled load shedding at the admission door
# instead of queue collapse under sustained overload.
SERVE_SLO_P99_SECONDS = "spark.hyperspace.serve.slo.p99.seconds"
SERVE_SLO_P99_SECONDS_DEFAULT = 0.0
SERVE_SLO_WINDOW_SECONDS = "spark.hyperspace.serve.slo.window.seconds"
SERVE_SLO_WINDOW_SECONDS_DEFAULT = 60.0
SERVE_SLO_SHED_ENABLED = "spark.hyperspace.serve.slo.shed.enabled"
SERVE_SLO_SHED_ENABLED_DEFAULT = "false"

# Multi-tenant serving (`engine/scheduler.py`): tenant-keyed knobs
# embed the tenant id in the conf key —
# `serve.tenant.<id>.weight` (float, default 1.0) is the tenant's
# deficit-round-robin share of the admission dequeue; a tenant with
# weight 2 drains its wait queue twice as fast as a weight-1 tenant
# under contention. `serve.tenant.<id>.hbm.fraction` (float in (0, 1],
# default 0 = unlimited) caps the tenant's concurrently-admitted
# footprint at that fraction of `serve.hbm.budget.bytes`;
# `serve.tenant.<id>.queue.depth` (int, default 0 = share the global
# depth) caps how many of the tenant's queries may WAIT at once. The
# default tenant is unlimited unless explicitly configured — existing
# single-tenant deployments see no behavior change.
# `advisor.tenant.<id>.budget.bytes` (default 0 = share the global
# advisor budget) caps auto-built index bytes attributed to that
# tenant's mined candidates.
SERVE_TENANT_PREFIX = "spark.hyperspace.serve.tenant."
SERVE_TENANT_WEIGHT_DEFAULT = 1.0
SERVE_TENANT_HBM_FRACTION_DEFAULT = 0.0
SERVE_TENANT_QUEUE_DEPTH_DEFAULT = 0
ADVISOR_TENANT_PREFIX = "spark.hyperspace.advisor.tenant."
ADVISOR_TENANT_BUDGET_BYTES_DEFAULT = 0

# Operations plane (`telemetry/timeseries.py`, `telemetry/ops_server.py`):
# the background sampler snapshots selected registry series every
# `timeseries.interval.seconds` into a bounded ring of
# `timeseries.capacity` samples, deriving counter rates and sliding-
# window quantiles (`window.<series>.*` gauges). Setting `ops.port`
# starts the in-process HTTP server (and the sampler with it) serving
# `/metrics` (Prometheus text), `/healthz` (scheduler/breaker/cache/
# replica state as JSON), and `/timeseries` (the ring as JSON). The
# server binds `ops.host` — 127.0.0.1 by default: the endpoints are
# unauthenticated operational surfaces, so exposing them beyond
# localhost is an explicit decision. Port 0 binds an ephemeral port
# (read it back from `get_server().port`); unset = no server.
TELEMETRY_OPS_PORT = "spark.hyperspace.telemetry.ops.port"
TELEMETRY_OPS_HOST = "spark.hyperspace.telemetry.ops.host"
TELEMETRY_OPS_HOST_DEFAULT = "127.0.0.1"
TELEMETRY_TIMESERIES_INTERVAL_SECONDS = \
    "spark.hyperspace.telemetry.timeseries.interval.seconds"
TELEMETRY_TIMESERIES_INTERVAL_SECONDS_DEFAULT = 1.0
TELEMETRY_TIMESERIES_CAPACITY = \
    "spark.hyperspace.telemetry.timeseries.capacity"
TELEMETRY_TIMESERIES_CAPACITY_DEFAULT = 600

# Crash recovery lease: a maintenance action that finds the op log's
# latest entry in a TRANSIENT state (CREATING/REFRESHING/...) treats the
# in-flight writer as crashed once the entry is older than this many
# seconds, and runs the Cancel FSM transition back to the last stable
# state before proceeding (`Hyperspace.recover_index` forces the same
# recovery immediately). Size it above the longest expected build.
MAINTENANCE_LEASE_SECONDS = "spark.hyperspace.maintenance.lease.seconds"
MAINTENANCE_LEASE_SECONDS_DEFAULT = 600

HYBRID_SCAN_ENABLED = "spark.hyperspace.index.hybridscan.enabled"

# Data-skipping indexes (`index/sketch.py`, `actions/skipping.py`,
# `plan/rules/skipping.py`): a second index kind flowing through the same
# log/action FSM — per-source-file min/max zone maps + blocked bloom
# filters persisted as a compact parquet sketch blob under the index
# root, consulted at plan time by FilterIndexRule to drop files whose
# zones/blooms refute the predicate. `skipping.enabled` gates the
# QUERY-side consult only (build verbs always work); the bloom knobs
# size the per-file split-block filter (bits from the standard
# -n*ln(p)/ln(2)^2 estimate, rounded up to whole 256-bit blocks and
# capped at `max.bytes` per file per column); `zorder.files` is how
# many clustered output files the optional build-time Z-order rewrite
# produces (more files = tighter zones = finer pruning, at small-file
# cost).
SKIPPING_ENABLED = "spark.hyperspace.index.skipping.enabled"
SKIPPING_ENABLED_DEFAULT = "true"
SKIPPING_BLOOM_FPP = "spark.hyperspace.index.skipping.bloom.fpp"
SKIPPING_BLOOM_FPP_DEFAULT = 0.01
SKIPPING_BLOOM_MAX_BYTES = "spark.hyperspace.index.skipping.bloom.max.bytes"
SKIPPING_BLOOM_MAX_BYTES_DEFAULT = 64 * 1024
SKIPPING_ZORDER_FILES = "spark.hyperspace.index.skipping.zorder.files"
SKIPPING_ZORDER_FILES_DEFAULT = 16

# Per-row lineage (extension; the reference's v0.2 direction): when enabled
# at build time, every index row carries the id of the source file it came
# from (`LINEAGE_COLUMN`, internal — never surfaced in query results) and
# the log entry stores per-file (size, stamp, id) records. Hybrid scan can
# then serve queries over a source with DELETED files by excluding those
# rows, and incremental refresh handles deletions as a per-bucket lineage
# filter instead of a full rebuild.
LINEAGE_ENABLED = "spark.hyperspace.index.lineage.enabled"
LINEAGE_COLUMN = "_hs_file_id"

# Mesh distribution of the data plane (no reference analog — Spark owns the
# cluster there; here the "cluster" is the jax device mesh). Values:
# "auto" (default: distribute when >1 device is visible), "true", "false".
DISTRIBUTION_ENABLED = "spark.hyperspace.distribution.enabled"
DISTRIBUTION_ENABLED_DEFAULT = "auto"
# Minimum row count before the sharded filter scan pays for itself.
DISTRIBUTION_MIN_ROWS = "spark.hyperspace.distribution.min.rows"
DISTRIBUTION_MIN_ROWS_DEFAULT = 4096
# Multi-host topology: number of slices (DCN rows) in the mesh. 1 (the
# default) = a flat single-axis ICI mesh; >1 builds a 2-axis
# (dcn, shard) mesh whose exchanges route hierarchically — the heavy
# re-bucket all_to_all confined to the inner ICI axis, one cross-slice
# hop over DCN (SURVEY §2.12 "DCN only across slices"). This covers the
# build exchange AND the in-program query-time repartitions
# (`parallel/spmd._repartition_lanes` / `repartition_sharded`), whose
# per-axis traffic is attributed as `spmd.repartition.{ici,dcn}.bytes`.
# `distribution.slices` is the canonical knob; the original
# `distribution.dcn.size` spelling is honored as a legacy fallback.
DISTRIBUTION_SLICES = "spark.hyperspace.distribution.slices"
DISTRIBUTION_DCN_SIZE = "spark.hyperspace.distribution.dcn.size"
DISTRIBUTION_DCN_SIZE_DEFAULT = 1

# Read replication across slices (`parallel/replica.py`): on a
# multi-slice mesh, each slice is a full REPLICA — its devices hold the
# whole bucket-range map at slice-local granularity — and the query
# scheduler routes each admitted query's fills + execution to the
# least-loaded replica slice (`serve.replica.*` series). Replicas are
# coherent by construction: the segment cache keys device residency by
# (index root, committed version, bucket range, device set), so a
# version commit invalidates every slice's entries through the same FSM
# hooks. "true" (default) replicates whenever the mesh has >= 2 slices.
DISTRIBUTION_REPLICATION = \
    "spark.hyperspace.distribution.replication.enabled"
DISTRIBUTION_REPLICATION_DEFAULT = "true"
# Minimum slice count before replica routing engages (below it the
# whole mesh executes each query, the PR-10/13 behavior).
DISTRIBUTION_REPLICATION_MIN_SLICES = \
    "spark.hyperspace.distribution.replication.min.slices"
DISTRIBUTION_REPLICATION_MIN_SLICES_DEFAULT = 2
# Hot-bucket mining threshold: a bucket whose flight-ring access count
# reaches this fraction of the hottest bucket's count is HOT — queries
# over hot buckets fan to the least-loaded replica (so hot ranges end
# up resident on >= 2 slices), while provably-cold-range queries pin to
# their range's home slice so cold data is not duplicated across HBMs.
DISTRIBUTION_REPLICATION_HOT_FRACTION = \
    "spark.hyperspace.distribution.replication.hot.fraction"
DISTRIBUTION_REPLICATION_HOT_FRACTION_DEFAULT = 0.5
# Born-sharded SPMD execution (`parallel/spmd.py`): bucketed SMJ /
# scan / aggregate over device-resident bucket-range shards as single
# jitted programs. "true" (default) uses it whenever the shape
# qualifies; "false" forces the legacy per-query placement mesh path
# (the escape hatch if a workload hits an SPMD-lane defect).
DISTRIBUTION_SPMD = "spark.hyperspace.distribution.spmd.enabled"
DISTRIBUTION_SPMD_DEFAULT = "true"
# First-attempt static per-shard output capacity factor of the SPMD
# join expansion (and the in-program repartition's per-peer slabs):
# capacity = factor x per-shard input rows, doubled on exact on-device
# overflow detection. Larger = fewer retries, more HBM per attempt.
DISTRIBUTION_CAPACITY_FACTOR = \
    "spark.hyperspace.distribution.capacity.factor"
DISTRIBUTION_CAPACITY_FACTOR_DEFAULT = 2.0
# Born-sharded string layout: a mesh build records each device range's
# sorted local string dictionary in `_shard_layout.json` so query-time
# global-dictionary resolution is pure JSON (no data read). A range
# whose dictionary exceeds this entry cap is recorded as null and the
# reader derives it from the parquet files instead (one host read per
# committed version, then cached). <= 0 disables recording entirely.
DISTRIBUTION_DICT_MAX_ENTRIES = \
    "spark.hyperspace.distribution.dictionary.max.entries"
DISTRIBUTION_DICT_MAX_ENTRIES_DEFAULT = 65536

# Warm-start compilation: when set to a directory, JAX's persistent
# compilation cache is enabled there (jax_compilation_cache_dir) via
# `telemetry/compilation.configure_persistent_cache`, wired at session
# init so every `instrumented_jit` entry point participates. A fresh
# replica pointed at a shared cache dir serves its first
# canonical-shape query from persisted executables instead of paying
# the trace+compile (PR-3's warm-trace==0 property, made to survive
# process restarts). Empty (default) = off. The size/compile-time
# eligibility floors are dropped to zero so the engine's small bucketed
# kernels qualify.
COMPILE_CACHE_DIR = "spark.hyperspace.compile.cache.dir"

# Self-driving index advisor (`hyperspace_tpu/advisor/`): mines the
# query flight ring for recurring un-indexed filter/join signatures,
# what-if scores hypothetical covering + data-skipping indexes by
# replaying recorded plans through the real rewrite rules, and
# auto-builds the winners through the normal Create actions (lease,
# OCC, action reports — the executor module is the ONLY sanctioned
# build caller inside advisor/, lint-enforced).
ADVISOR_ENABLED = "spark.hyperspace.advisor.enabled"
ADVISOR_ENABLED_DEFAULT = "true"
# Per-run ceiling on the summed ESTIMATED on-disk bytes of indexes the
# advisor may build (its per-warehouse build budget); candidates past
# the budget are recorded as rejected, not silently dropped.
ADVISOR_BUILD_BUDGET_BYTES = "spark.hyperspace.advisor.build.budget.bytes"
ADVISOR_BUILD_BUDGET_BYTES_DEFAULT = 1 * 1024 ** 3
# How many index builds one advisor run may start (a run that
# recommends ten indexes still builds incrementally over runs).
ADVISOR_MAX_BUILDS = "spark.hyperspace.advisor.max.builds"
ADVISOR_MAX_BUILDS_DEFAULT = 2
# Serving-pressure gate: the advisor defers every build while queries
# wait in the scheduler queue, or while admitted bytes exceed this
# fraction of `serve.hbm.budget.bytes` (advisor builds must never
# starve admission; deferred runs retry on the next cycle).
ADVISOR_SERVE_HEADROOM = "spark.hyperspace.advisor.serve.headroom"
ADVISOR_SERVE_HEADROOM_DEFAULT = 0.5
# Minimum estimated bytes avoided (amortized over the observed repeat
# count) before a candidate is recommended at all.
ADVISOR_MIN_BENEFIT_BYTES = "spark.hyperspace.advisor.min.benefit.bytes"
ADVISOR_MIN_BENEFIT_BYTES_DEFAULT = 0
# Assumed fraction of scan bytes a hypothetical DATA-SKIPPING index
# prunes (zone/bloom effectiveness is unknowable without building the
# sketches; the what-if math uses this conservative constant and the
# docs tell you to tune it against `skipping.bytes_pruned` telemetry).
ADVISOR_SKIPPING_PRUNE_FRACTION = \
    "spark.hyperspace.advisor.skipping.prune.fraction"
ADVISOR_SKIPPING_PRUNE_FRACTION_DEFAULT = 0.5
# Minimum observed repeat count of a workload signature before the
# advisor considers it recurring (one-off queries never justify a
# build).
ADVISOR_MIN_REPEATS = "spark.hyperspace.advisor.min.repeats"
ADVISOR_MIN_REPEATS_DEFAULT = 2

# Continuous-ingest coordinator (`engine/ingest.py`): cadence between
# micro-batch ticks when the caller drives `run_once` on a timer. The
# coordinator itself never spawns threads (the engine thread seam keeps
# background threads in `scheduler.py`); this is the interval the
# owning loop should sleep between ticks.
INGEST_INTERVAL_SECONDS = "spark.hyperspace.ingest.interval.seconds"
INGEST_INTERVAL_SECONDS_DEFAULT = 5.0
# Serving-pressure gate, same shape as the advisor's: refresh work is
# deferred while queries wait for admission, or while admitted bytes
# exceed this fraction of `serve.hbm.budget.bytes`. Appends still land
# (the source is append-only either way); only index refresh yields.
INGEST_SERVE_HEADROOM = "spark.hyperspace.ingest.serve.headroom"
INGEST_SERVE_HEADROOM_DEFAULT = 0.5
# Total tries the coordinator makes when a refresh loses the op-log
# race to a manual refresher (typed conflict → bounded jittered backoff
# via `utils/retry.py`, then a clean concession — never an error).
INGEST_CONFLICT_ATTEMPTS = "spark.hyperspace.ingest.conflict.attempts"
INGEST_CONFLICT_ATTEMPTS_DEFAULT = 3

# XLA profiler integration: when set to a directory, every executed
# query is captured as a profiler trace under it (one subdirectory per
# query), viewable in TensorBoard/XProf/Perfetto. Empty (default) = off.
TRACE_DIR = "spark.hyperspace.trace.dir"

# Query flight recorder (`telemetry/flight.py`): the bounded ring of
# the last-K completed QueryMetrics is ALWAYS on (it costs one deque
# append per query); the slow-query dump persists the full metric
# tree + registry snapshot + trace slice of any query whose wall
# exceeds `slowlog.seconds` (0, the default, disables dumping). Dumps
# land under `slowlog.dir` (default `<warehouse>/slowlog`); only the
# newest `slowlog.keep` dump files are retained.
TELEMETRY_SLOWLOG_SECONDS = "spark.hyperspace.telemetry.slowlog.seconds"
TELEMETRY_SLOWLOG_SECONDS_DEFAULT = 0.0
TELEMETRY_SLOWLOG_DIR = "spark.hyperspace.telemetry.slowlog.dir"
TELEMETRY_SLOWLOG_KEEP = "spark.hyperspace.telemetry.slowlog.keep"
TELEMETRY_SLOWLOG_KEEP_DEFAULT = 20

# Critical-path decomposition (`telemetry/critical_path.py`): every
# scheduled query's wall is decomposed into the closed segment set
# (queue_wait/batch_window/.../host_python residual), stamped onto its
# QueryMetrics, and published as `critpath.<segment>.seconds` counters.
# "false" skips the per-query stamp (the source counters still record).
TELEMETRY_CRITPATH_ENABLED = "spark.hyperspace.telemetry.critpath.enabled"
TELEMETRY_CRITPATH_ENABLED_DEFAULT = "true"

# Sampling profiler (`telemetry/profiler.py`): when enabled, a daemon
# thread samples every live thread's stack at `profiler.hz` and
# aggregates host time by collapsed stack (served at `/profile`).
# Off by default; the overhead when on is gated (<2% closed-loop QPS)
# by `bench_regress.py --serve`.
TELEMETRY_PROFILER_ENABLED = "spark.hyperspace.telemetry.profiler.enabled"
TELEMETRY_PROFILER_ENABLED_DEFAULT = "false"
TELEMETRY_PROFILER_HZ = "spark.hyperspace.telemetry.profiler.hz"
TELEMETRY_PROFILER_HZ_DEFAULT = 19.0

# Triggered device-trace capture: when `capture.seconds` > 0, SLO burn
# crossing 1.0 or a slowlog dump fires a background device-trace
# capture of that many seconds of device activity, written as a
# `profile-*` directory next to the slow-query dumps (atomic rename;
# only the newest `capture.keep` retained; at most one capture per
# `capture.min.interval.seconds`). 0 (the default) disarms capture.
TELEMETRY_PROFILER_CAPTURE_SECONDS = \
    "spark.hyperspace.telemetry.profiler.capture.seconds"
TELEMETRY_PROFILER_CAPTURE_SECONDS_DEFAULT = 0.0
TELEMETRY_PROFILER_CAPTURE_KEEP = \
    "spark.hyperspace.telemetry.profiler.capture.keep"
TELEMETRY_PROFILER_CAPTURE_KEEP_DEFAULT = 4
TELEMETRY_PROFILER_CAPTURE_MIN_INTERVAL_SECONDS = \
    "spark.hyperspace.telemetry.profiler.capture.min.interval.seconds"
TELEMETRY_PROFILER_CAPTURE_MIN_INTERVAL_SECONDS_DEFAULT = 30.0

# Durable on-lake telemetry history (`telemetry/history.py`): when
# enabled, the sampler's tick hook periodically flushes the registry
# snapshot, the new ring samples, SLO/burn state, and a flight-ring
# digest as append-only schema-versioned segment files under
# `history.dir` (default `<warehouse>/.hyperspace_telemetry` — history
# is metadata, and metadata lives on the lake). Segments older than
# `keep.seconds` or beyond `keep.bytes` total are pruned oldest-first;
# a crash-torn final segment is skipped on read.
TELEMETRY_HISTORY_ENABLED = "spark.hyperspace.telemetry.history.enabled"
TELEMETRY_HISTORY_ENABLED_DEFAULT = "false"
TELEMETRY_HISTORY_DIR = "spark.hyperspace.telemetry.history.dir"
# The one place the on-lake history directory NAME is spelled —
# `scripts/check_metrics_coverage.py` bans the literal everywhere but
# here and `telemetry/history.py`, so every segment write routes
# through the history seam.
TELEMETRY_HISTORY_DIRNAME = ".hyperspace_telemetry"
TELEMETRY_HISTORY_INTERVAL_SECONDS = \
    "spark.hyperspace.telemetry.history.interval.seconds"
TELEMETRY_HISTORY_INTERVAL_SECONDS_DEFAULT = 60.0
TELEMETRY_HISTORY_KEEP_SECONDS = \
    "spark.hyperspace.telemetry.history.keep.seconds"
TELEMETRY_HISTORY_KEEP_SECONDS_DEFAULT = 7 * 24 * 3600.0
TELEMETRY_HISTORY_KEEP_BYTES = \
    "spark.hyperspace.telemetry.history.keep.bytes"
TELEMETRY_HISTORY_KEEP_BYTES_DEFAULT = 64 * 1024 * 1024

# Rule-driven alerting (`telemetry/alerts.py`): declarative rules over
# the sampler's windowed series, evaluated on every tick. A firing
# rule opens a structured incident with an attached evidence bundle
# (served at `/alerts`, persisted into the history store). Per-rule
# overrides live under `alerts.rule.<name>.{enabled,threshold,clear,
# sustain.seconds,window.seconds}`; `alerts.enabled=false` disables
# evaluation entirely.
TELEMETRY_ALERTS_ENABLED = "spark.hyperspace.telemetry.alerts.enabled"
TELEMETRY_ALERTS_ENABLED_DEFAULT = "true"
TELEMETRY_ALERTS_RULE_PREFIX = "spark.hyperspace.telemetry.alerts.rule."

# Adaptive host/device execution lane: batches below this row count are
# evaluated with host numpy, larger batches run on the accelerator. The
# default is tuned for a high-latency (tunneled) device link where each
# blocking sync costs ~100 ms — there the crossover for query operators
# sits in the millions of rows (index reads are pruned/pre-sorted, so the
# host work per row is tiny). On a directly-attached TPU set this lower,
# or 0 to force everything onto the device.
MIN_DEVICE_ROWS = "spark.hyperspace.execution.min.device.rows"
MIN_DEVICE_ROWS_DEFAULT = 4_194_304

# Whole-stage fusion: compile Filter/Project/BroadcastHashJoin chains
# into one jitted executable per chain (engine/fusion.py). "false"
# restores eager per-operator execution.
FUSION_ENABLED = "spark.hyperspace.execution.fusion.enabled"
FUSION_ENABLED_DEFAULT = "true"

WAREHOUSE_PATH = "spark.hyperspace.warehouse.dir"
WAREHOUSE_PATH_DEFAULT = "warehouse"

# Operation log layout (reference `index/IndexConstants.scala:38-39`).
HYPERSPACE_LOG = "_hyperspace_log"
INDEX_VERSION_DIRECTORY_PREFIX = "v__"
LATEST_STABLE_LOG = "latestStable"

# Commit marker written LAST into every `v__=N` data dir (the Delta-style
# finalize): readers (`IndexDataManager.get_latest_version_id`, optimize/
# incremental refresh picking the "current" version) only see versions
# carrying it, so a crashed build's partially-written dir is invisible —
# it is skipped for the next version number and hard-deleted by vacuum.
# The leading underscore keeps it out of every parquet file listing.
INDEX_DATA_COMMIT_MARKER = "_committed"

# Explain display mode (reference `index/IndexConstants.scala:42-49`).
DISPLAY_MODE = "spark.hyperspace.explain.displayMode"
HIGHLIGHT_BEGIN_TAG = "spark.hyperspace.explain.displayMode.highlight.beginTag"
HIGHLIGHT_END_TAG = "spark.hyperspace.explain.displayMode.highlight.endTag"


class DisplayModeNames:
    CONSOLE = "console"
    PLAIN_TEXT = "plaintext"
    HTML = "html"


class States:
    """Index lifecycle states (reference `actions/Constants.scala:20-30`)."""

    ACTIVE = "ACTIVE"
    CREATING = "CREATING"
    DELETING = "DELETING"
    DELETED = "DELETED"
    REFRESHING = "REFRESHING"
    VACUUMING = "VACUUMING"
    RESTORING = "RESTORING"
    DOESNOTEXIST = "DOESNOTEXIST"
    CANCELLING = "CANCELLING"
    OPTIMIZING = "OPTIMIZING"  # extension: incremental merge-compaction


STABLE_STATES = (States.ACTIVE, States.DELETED, States.DOESNOTEXIST)
