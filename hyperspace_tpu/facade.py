"""The Hyperspace user facade.

Parity: reference `Hyperspace.scala:24-133` — lifecycle verbs delegated to
the index collection manager, `indexes` catalog view, `explain`, plus the
session-keyed context holding a CachingIndexCollectionManager
(`Hyperspace.scala:107-133`).
"""

from __future__ import annotations

import threading
import weakref
from typing import Optional

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.manager import CachingIndexCollectionManager


class HyperspaceContext:
    """Per-session context (reference `Hyperspace.scala:131-133`).

    Holds no strong reference back to the session (it is the weak key in
    `Hyperspace._contexts`); only the conf-derived manager lives here.
    """

    def __init__(self, session: HyperspaceSession):
        self.index_collection_manager = CachingIndexCollectionManager(session.conf)


def index_usage_report(manager, last_n: Optional[int] = None):
    """Per-index rule-usage rows for `manager`'s catalog (the body of
    `Hyperspace.index_usage`, module-level so the `/healthz`
    `index_usage` section can render the same report from a bare
    conf-built manager — an HTTP handler thread has no facade)."""
    from hyperspace_tpu import telemetry

    counters = telemetry.get_registry().counters_dict()
    ring = telemetry.get_recorder().queries(last_n)
    ring_counts: dict = {}
    for qm in ring:
        try:
            for use in qm.index_usage():
                name = use.get("name")
                if name:
                    ring_counts[name] = ring_counts.get(name, 0) + 1
        except Exception:
            continue  # a foreign recorder shape never breaks the report
    out = []
    for entry in manager.indexes():
        name = entry.name
        served_ring = ring_counts.get(name, 0)
        out.append({
            "index": name,
            "state": entry.state,
            "served_total": int(
                counters.get(f"rules.served.{name}", 0)),
            "served_in_ring": served_ring,
            "ring_entries": len(ring),
            "unused": served_ring == 0,
        })
    return out


class Hyperspace:
    # Weak keys: a dropped session must not be pinned by its context.
    _contexts: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
    _lock = threading.Lock()

    def __init__(self, session: Optional[HyperspaceSession] = None):
        self.session = session or HyperspaceSession()
        self._context = Hyperspace.get_context(self.session)

    @staticmethod
    def get_context(session: HyperspaceSession) -> HyperspaceContext:
        """Session-keyed context cache (reference `Hyperspace.scala:107-129`
        uses a thread-local keyed on the active session)."""
        with Hyperspace._lock:
            ctx = Hyperspace._contexts.get(session)
            if ctx is None:
                ctx = HyperspaceContext(session)
                Hyperspace._contexts[session] = ctx
            return ctx

    @property
    def _manager(self) -> CachingIndexCollectionManager:
        return self._context.index_collection_manager

    # -- lifecycle verbs (reference `Hyperspace.scala:33-92`) -------------

    def create_index(self, df, index_config) -> None:
        """Build an index over `df`'s relation. The config type selects
        the KIND: `IndexConfig` builds a covering index (bucketed,
        sorted derived dataset); `DataSkippingIndexConfig` builds a
        data-skipping index (per-file zone-map + bloom sketch blob,
        optional Z-order clustering — docs/data-skipping.md). Both flow
        through the same transactional log FSM."""
        self._manager.create(df, index_config)

    def delete_index(self, index_name: str) -> None:
        self._manager.delete(index_name)

    def restore_index(self, index_name: str) -> None:
        self._manager.restore(index_name)

    def vacuum_index(self, index_name: str) -> None:
        self._manager.vacuum(index_name)

    def refresh_index(self, index_name: str, mode: str = "full") -> None:
        """mode='full' rebuilds (reference behavior); mode='incremental'
        indexes only appended source files (reference roadmap, exceeded)."""
        self._manager.refresh(index_name, mode)

    def optimize_index(self, index_name: str) -> None:
        """Merge-compact incremental deltas (extension; reference roadmap)."""
        self._manager.optimize(index_name)

    def cancel(self, index_name: str) -> None:
        self._manager.cancel(index_name)

    def recover_index(self, index_name: str) -> bool:
        """Force crash recovery: if a writer died mid-operation (the log's
        latest entry is transient), run the Cancel FSM transition back to
        the last stable state immediately — no waiting for the
        `spark.hyperspace.maintenance.lease.seconds` lease that gates
        AUTOMATIC recovery by the next create/refresh/optimize. Returns
        True iff a recovery ran (False: index already stable)."""
        return self._manager.recover(index_name)

    def indexes(self):
        """Catalog as a pandas DataFrame (reference `Hyperspace.scala:33-36`)."""
        return self._manager.indexes_df()

    # -- self-driving indexes ---------------------------------------------

    def advisor(self):
        """The session's self-driving index advisor
        (`hyperspace_tpu/advisor/`): mines the flight ring for
        recurring un-indexed filter/join shapes, what-if scores
        hypothetical indexes by replaying recorded plans through the
        real rewrite rules, and auto-builds winners through the normal
        lease-gated Create path. `advisor().run_once()` is one
        mine→score→build cycle; `advisor().start(interval_s)` runs it
        in the background. One advisor per facade instance (the miner
        holds an incremental cursor over the process flight ring)."""
        if not hasattr(self, "_advisor"):
            from hyperspace_tpu.advisor import IndexAdvisor
            self._advisor = IndexAdvisor(self.session)
        return self._advisor

    def ingest(self, producer=None, indexes=()):
        """A continuous-ingest coordinator (`engine/ingest.py`) bound
        to this session: each `run_once()` tick lands `producer`'s
        micro-batch appends, defers under serve pressure, and drives
        mode='incremental' refresh of `indexes` through the lease-gated
        manager path with typed conflict concession. Caller-threaded —
        drive it on `spark.hyperspace.ingest.interval.seconds`; the
        coordinator never owns a thread. Fresh instance per call (the
        staleness ledger belongs to one append stream)."""
        from hyperspace_tpu.engine.ingest import IngestCoordinator
        return IngestCoordinator(self.session, producer=producer,
                                 indexes=indexes)

    # -- observability ----------------------------------------------------

    def index_usage(self, last_n: Optional[int] = None):
        """Per-index rule-usage report — the drop advisor's raw
        material (ROADMAP: "storage is a budget too"). For every index
        in this session's catalog: how many queries a rewrite rule
        served from it over the PROCESS lifetime
        (`rules.served.<index>` counters) and within the last `last_n`
        flight-ring entries (None = the whole ring), plus an `unused`
        flag for indexes no ring entry selected. Report only — nothing
        is vacuumed; an index idle here may still serve a workload that
        rotated out of the bounded ring, so treat `unused` as a
        candidate list, not a verdict."""
        return index_usage_report(self._manager, last_n)

    def incidents(self, active_only: bool = False):
        """The incident plane's structured incidents (rule-driven
        alerting, `telemetry/alerts.py`): each carries its rule, fire
        and resolve times, breaching value, and the evidence bundle
        captured at fire time. `active_only` keeps the still-firing
        ones. The same documents the `/alerts` ops endpoint serves."""
        from hyperspace_tpu.telemetry import alerts

        return alerts.get_manager().incidents(active_only=active_only)

    def metrics_registry(self):
        """The process-wide metrics registry (delegates to the
        session; see `HyperspaceSession.metrics_registry`)."""
        return self.session.metrics_registry()

    def tenant_report(self) -> dict:
        """Per-tenant usage/cost chargeback report: for every tenant
        seen since process start, the device cost it was billed
        (modeled flops + bytes accessed and measured dispatch-seconds
        from `instrumented_jit`'s per-dispatch charges), the link bytes
        it moved, the segment-cache fills it paid for, and its serving
        state (admitted bytes, in-flight/queued counts, SLO window,
        configured quota knobs). EXACT by construction: every charge
        site mirrors its global counter inc onto the active tenant's
        `tenant.<id>.*` series at the same line, so `totals` (the
        per-tenant sums) equals `global` (the process counters) to the
        bit — the contract `bench_regress.py --serve` gates. Unscoped
        work bills the "default" tenant; nothing is ever dropped."""
        from hyperspace_tpu import telemetry

        usage = telemetry.tenant_digest()
        counters = telemetry.get_registry().counters_dict()
        totals = {name: sum(u.get(name, 0) for u in usage.values())
                  for name in telemetry.TENANT_CHARGE_COUNTERS}
        global_ = {name: counters.get(name, 0)
                   for name in telemetry.TENANT_CHARGE_COUNTERS}
        sched = self.session.scheduler()
        serving = sched.tenant_snapshot(self.session.conf)
        tenants = {}
        for t in sorted(set(usage) | set(serving)):
            tenants[t] = {"usage": usage.get(t, {})}
            if t in serving:
                tenants[t]["serving"] = serving[t]
        return {
            "tenants": tenants,
            "totals": totals,
            "global": global_,
            # Byte/flop/fill counters are integer-valued and sum
            # exactly; dispatch-seconds is the one genuinely fractional
            # series, where float summation order costs at most a few
            # ulps — hence the relative epsilon instead of ==.
            "exact": all(abs(totals[n] - global_[n])
                         <= 1e-9 * max(1.0, abs(global_[n]))
                         for n in totals),
        }

    def export_trace(self, path: str) -> dict:
        """Export collected spans as Chrome trace-event JSON (requires
        a prior `telemetry.enable_tracing()`); loads in
        chrome://tracing and ui.perfetto.dev."""
        from hyperspace_tpu import telemetry
        return telemetry.export_trace(path)

    def device_memory(self) -> dict:
        """Snapshot of the device-memory accountant: per-device
        live/peak HBM bytes and which backend measured them
        (`memory_stats` on real accelerators, the live-arrays
        accounting fallback on CPU/virtual meshes). Takes a fresh
        sample first so the answer is current, not last-span-stale."""
        from hyperspace_tpu import telemetry
        telemetry.memory.sample()
        return telemetry.memory.snapshot()

    def explain(self, df, verbose: bool = False, redirect=None,
                metrics=None) -> None:
        """Plan diff with rules on vs off (reference
        `Hyperspace.scala:101-104`). Pass `metrics` (a
        `telemetry.QueryMetrics`, e.g. `session.last_query_metrics()`)
        to append the runtime numbers of an actual execution under the
        diff — plan change and cost in one view."""
        from hyperspace_tpu.plananalysis.analyzer import PlanAnalyzer
        out = PlanAnalyzer.explain_string(df, self.session,
                                          self._manager.indexes(), verbose,
                                          metrics=metrics)
        (redirect or print)(out)
