"""Plan <-> JSON serde.

The reference Kryo-serializes Catalyst plans with a zoo of wrapper nodes for
non-serializable internals (`index/serde/LogicalPlanSerDeUtils.scala:40-217`,
`index/serde/package.scala:29-167`). Owning the IR makes serde trivial —
plans round-trip through plain JSON — while keeping the reference's
*unanalyzed-plan-logged, re-resolved-on-refresh* semantics: Scan nodes store
root paths only (like `InMemoryFileIndexWrapper` keeping rootPathStrings),
and the file listing is re-enumerated at deserialization time so refresh
picks up appended/changed data (reference `LogicalPlanSerDeUtils.scala:150-217`).
"""

from __future__ import annotations

import json

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.expr import Expression
from hyperspace_tpu.plan.nodes import (Aggregate, AggSpec, BucketSpec, Except,
                                       Filter, Intersect, Join, Limit,
                                       LogicalPlan, Project, Scan, Sort,
                                       Union, Window)
from hyperspace_tpu.plan.schema import Field, Schema


def plan_to_json(plan: LogicalPlan) -> str:
    return json.dumps(plan.to_dict())


def plan_from_dict(d: dict) -> LogicalPlan:
    node = d.get("node")
    if node == "scan":
        # Root paths only by default; the file listing is re-resolved lazily
        # (fresh enumeration = refresh sees new data). An explicit "files"
        # restriction (hybrid scan / delta scans) is preserved verbatim.
        return Scan(root_paths=d["rootPaths"],
                    schema=Schema([Field.from_dict(f) for f in d["schema"]]),
                    file_format=d.get("format", "parquet"),
                    bucket_spec=BucketSpec.from_dict(d.get("bucketSpec")),
                    files=d.get("files"))
    if node == "filter":
        return Filter(Expression.from_dict(d["condition"]),
                      plan_from_dict(d["child"]))
    if node == "project":
        return Project([c if isinstance(c, str) else Expression.from_dict(c)
                        for c in d["columns"]], plan_from_dict(d["child"]))
    if node == "union":
        return Union([plan_from_dict(c) for c in d["children"]])
    if node == "aggregate":
        return Aggregate(d["groupBy"],
                         [AggSpec.from_dict(a) for a in d["aggregates"]],
                         plan_from_dict(d["child"]))
    if node == "window":
        return Window(d["partitionBy"], d["orderBy"],
                      [AggSpec.from_dict(s) for s in d["specs"]],
                      plan_from_dict(d["child"]))
    if node == "sort":
        return Sort(d["columns"], plan_from_dict(d["child"]))
    if node == "limit":
        return Limit(d["n"], plan_from_dict(d["child"]))
    if node == "intersect":
        return Intersect(plan_from_dict(d["left"]),
                         plan_from_dict(d["right"]))
    if node == "except":
        return Except(plan_from_dict(d["left"]), plan_from_dict(d["right"]))
    if node == "join":
        cond = d["condition"]
        return Join(plan_from_dict(d["left"]), plan_from_dict(d["right"]),
                    Expression.from_dict(cond) if cond is not None else None,
                    d.get("type", "inner"))
    raise HyperspaceException(f"Unknown plan node kind: {node}")


def plan_from_json(text: str) -> LogicalPlan:
    return plan_from_dict(json.loads(text))
