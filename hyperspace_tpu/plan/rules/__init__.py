from hyperspace_tpu.plan.rules.filter_index import FilterIndexRule
from hyperspace_tpu.plan.rules.join_index import JoinIndexRule
from hyperspace_tpu.plan.rules.ranker import JoinIndexRanker

__all__ = ["FilterIndexRule", "JoinIndexRule", "JoinIndexRanker"]
