"""JoinIndexRule: redirect equi-joins to bucketed covering indexes.

Parity: reference `index/rules/JoinIndexRule.scala:54-595`.
Applicability (reference `:163-166`):
- equi-join condition in AND-only CNF of column equalities (`:179-185`);
- both subplans *linear* (<=1 child per node) — guards against signature
  collisions since the file-based signature ignores plan structure
  (`:194-205, 210-211`);
- join attributes resolve directly to base relations with a strict
  one-to-one left<->right column mapping (`:278-317`).
Index selection (reference `:328-594`):
- per-side candidates by signature match;
- an index is usable iff its indexed columns are SET-equal to that side's
  join columns and it covers every column the side needs;
- left/right indexes are compatible iff their indexed-column ORDER agrees
  under the left<->right mapping;
- best pair chosen by JoinIndexRanker.
Replacement swaps each side's scan for the index scan WITH its bucket spec
so the physical planner elides Exchange+Sort (reference `:124-153`).
Errors degrade to a no-op with a warning (reference `:66-69`).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

from hyperspace_tpu import telemetry
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.plan import expr as E
from hyperspace_tpu.plan.nodes import Join, LogicalPlan, Scan
from hyperspace_tpu.plan.rules.base import Rule
from hyperspace_tpu.plan.rules.ranker import JoinIndexRanker

logger = logging.getLogger(__name__)


def _skip(reason: str, **detail) -> None:
    """Structured whyNot record (the reference's `PlanAnalyzer.whyNot`
    analog): the rule looked at a join and declined, with the reason."""
    telemetry.event("rule", "JoinIndexRule", action="skipped",
                    reason=reason, **detail)


class JoinIndexRule(Rule):
    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        self._sig_cache = {}
        try:
            return plan.transform_up(self._rewrite)
        except Exception as exc:
            logger.warning("JoinIndexRule failed; skipping: %s", exc)
            return plan

    def _rewrite(self, node: LogicalPlan) -> LogicalPlan:
        # The reference rule matches ANY `Join(l, r, Some(cond))` with a
        # supported equi condition (`JoinIndexRule.scala:55-71`) — outer
        # equi-joins are index-served too.
        if not isinstance(node, Join):
            return node
        join = node
        if join.condition is None:
            return node  # cross join: nothing to bucket on
        mapping = self._column_mapping(join)
        if mapping is None:
            _skip("condition is not an AND-only CNF of one-to-one "
                  "column equalities")
            return node
        if not (join.left.is_linear() and join.right.is_linear()):
            _skip("non-linear join subplan")
            return node
        left_scan = self._base_scan(join.left)
        right_scan = self._base_scan(join.right)
        if left_scan is None or right_scan is None:
            _skip("join side does not resolve to a single base relation")
            return node
        if left_scan.bucket_spec is not None or right_scan.bucket_spec is not None:
            _skip("relation already bucketed (rule already applied)")
            return node  # already rewritten

        pair = self._best_index_pair(join, mapping)
        if pair is None:
            # whyNot with enough detail for the advisor to synthesize a
            # candidate PAIR: per-side relation roots, join keys in
            # mapping order, and the full column set each side's index
            # would have to cover.
            left_cols = sorted(mapping)
            _skip("no usable/compatible index pair",
                  join_columns=left_cols,
                  left_join_columns=left_cols,
                  right_join_columns=[mapping[c] for c in left_cols],
                  left_roots=list(left_scan.root_paths),
                  right_roots=list(right_scan.root_paths),
                  left_referenced=self._referenced_columns(join.left),
                  right_referenced=self._referenced_columns(join.right))
            return node
        ((left_index, left_appended, left_deleted),
         (right_index, right_appended, right_deleted)) = pair
        logger.info("JoinIndexRule: applying indexes %s%s%s, %s%s%s",
                    left_index.name,
                    f" (+{len(left_appended)} appended)" if left_appended
                    else "",
                    f" (-{len(left_deleted)} deleted)" if left_deleted
                    else "",
                    right_index.name,
                    f" (+{len(right_appended)} appended)" if right_appended
                    else "",
                    f" (-{len(right_deleted)} deleted)" if right_deleted
                    else "")
        telemetry.event(
            "rule", "JoinIndexRule", action="applied",
            indexes=[{"name": e.name, "root": e.content.root,
                      "num_buckets": e.num_buckets, "side": side,
                      "appended_files": len(app or ()),
                      "deleted_files": len(dele or ())}
                     for e, app, dele, side in
                     ((left_index, left_appended, left_deleted, "left"),
                      (right_index, right_appended, right_deleted,
                       "right"))])

        def swap(side_plan: LogicalPlan, entry: IndexLogEntry,
                 appended, deleted_ids) -> LogicalPlan:
            from hyperspace_tpu.plan.nodes import Filter, Project, Union
            replacement: LogicalPlan = self.index_scan(entry, bucketed=True)
            if deleted_ids:
                # Deleted source files (lineage-enabled index): exclude
                # their rows right above the bucketed scan — filters
                # preserve bucketing, so the SMJ path is kept.
                replacement = Filter(self.lineage_exclusion(deleted_ids),
                                     replacement)
            if appended or deleted_ids or entry.has_lineage:
                # Hybrid scan (join path): index data (UNION the appended
                # source files, re-bucketed at execution time through the
                # planner's ExchangeExec so the bucketed SMJ still applies
                # — reference roadmap, Hybrid Scan item). The Project also
                # drops the internal lineage column from the join input —
                # needed even on an exact match of a lineage-enabled index,
                # or `_hs_file_id` would leak into the join output schema.
                scan = self._base_scan(side_plan)
                needed = self._referenced_columns(side_plan)
                # Filter preserves its child's schema, so `replacement`
                # still exposes the index scan's fields here.
                names = [f.name for f in replacement.schema.fields
                         if f.name.lower() in set(needed)]
                branches = [Project(names, replacement)]
                if appended:
                    branches.append(Project(names, Scan(
                        scan.root_paths, scan.schema, files=appended)))
                replacement = (Union(branches) if len(branches) > 1
                               else branches[0])

            def f(n: LogicalPlan) -> LogicalPlan:
                return replacement if isinstance(n, Scan) else n

            return side_plan.transform_up(f)

        return Join(swap(join.left, left_index, left_appended, left_deleted),
                    swap(join.right, right_index, right_appended,
                         right_deleted),
                    join.condition, join.join_type)

    # -- applicability ----------------------------------------------------

    @staticmethod
    def _base_scan(plan: LogicalPlan) -> Optional[Scan]:
        leaves = plan.collect_leaves()
        if len(leaves) == 1 and isinstance(leaves[0], Scan):
            return leaves[0]
        return None

    def _column_mapping(self, join: Join) -> Optional[Dict[str, str]]:
        """Strict one-to-one left->right join column mapping from an
        AND-only CNF of column equalities (reference `:179-185, 278-317`)."""
        left_schema, right_schema = join.left.schema, join.right.schema
        mapping: Dict[str, str] = {}
        reverse: Dict[str, str] = {}
        for conjunct in E.split_conjunctive(join.condition):
            if not isinstance(conjunct, E.EqualTo):
                return None
            a, b = conjunct.left, conjunct.right
            if not isinstance(a, E.Column) or not isinstance(b, E.Column):
                return None
            if left_schema.contains(a.name) and right_schema.contains(b.name):
                l, r = a.name.lower(), b.name.lower()
            elif left_schema.contains(b.name) and right_schema.contains(a.name):
                l, r = b.name.lower(), a.name.lower()
            else:
                return None
            if mapping.get(l, r) != r or reverse.get(r, l) != l:
                return None  # one-to-many mapping
            mapping[l] = r
            reverse[r] = l
        return mapping or None

    # -- index selection --------------------------------------------------

    @staticmethod
    def _referenced_columns(plan: LogicalPlan) -> List[str]:
        """BASE-relation columns the side needs (reference `:446-457`):
        the output resolved top-down through projections — computed
        entries contribute their references, not their alias names — plus
        every filter/sort/aggregate reference along the chain."""
        from hyperspace_tpu.plan.nodes import (Aggregate, Filter as FilterNode,
                                               Limit, Project as ProjectNode,
                                               Scan as ScanNode, Sort,
                                               sort_direction)

        def walk(node: LogicalPlan, required: set) -> set:
            if isinstance(node, ScanNode):
                return {r.lower() for r in required}
            if isinstance(node, FilterNode):
                return walk(node.child,
                            set(required) | node.condition.references())
            if isinstance(node, ProjectNode):
                return walk(node.child, node.references())
            if isinstance(node, Aggregate):
                req = set(node.group_columns)
                for a in node.aggregates:
                    req |= a.references()
                return walk(node.child, req)
            if isinstance(node, Sort):
                return walk(node.child, set(required)
                            | {sort_direction(c)[0] for c in node.columns})
            if isinstance(node, Limit):
                return walk(node.child, required)
            out = {r.lower() for r in required}
            for c in node.children:
                out |= walk(c, set(c.schema.names))
            return out

        return sorted(walk(plan, set(plan.schema.names)))

    def _usable_indexes(self, plan: LogicalPlan, join_cols: Sequence[str]):
        """(entry, appended_files|None, deleted_ids) candidates for one
        join side: signature-matching ACTIVE indexes whose indexed columns
        are set-equal to the join columns and that cover the side's
        referenced columns (reference `:328-353, 399-409, 515-524`). With
        hybrid scan enabled, an index over a CHANGED source is usable too:
        appended files ride along as a union branch, and (lineage-enabled
        indexes) deleted files' rows are excluded by a lineage filter."""
        from hyperspace_tpu import constants
        from hyperspace_tpu.index.source_delta import (classify_current,
                                                       restricted_scan,
                                                       split_current)

        hybrid = (self.session.conf.get(constants.HYBRID_SCAN_ENABLED,
                                        "false").lower() == "true")
        referenced = set(self._referenced_columns(plan))
        join_set = {c.lower() for c in join_cols}
        scan = self._base_scan(plan)
        out = []
        for entry in self._covering_indexes():
            indexed = [c.lower() for c in entry.indexed_columns]
            if set(indexed) != join_set:
                continue
            covered = {c.lower() for c in
                       (entry.indexed_columns + entry.included_columns)}
            if not referenced <= covered:
                continue
            if self.signature_matches(entry, plan):
                out.append((entry, None, []))
                continue
            if not hybrid or scan is None:
                continue
            delta = classify_current(entry, scan.files())
            if delta is not None:
                appended, deleted_ids, modified = delta
                if modified or not (appended or deleted_ids):
                    continue
                out.append((entry, appended or None, deleted_ids))
                continue
            appended, missing, stored = split_current(entry, scan.files())
            if missing or not appended or not stored:
                continue
            if self.signature_matches(entry,
                                      restricted_scan(entry, scan,
                                                      sorted(stored))):
                out.append((entry, appended, []))
        return out

    def _best_index_pair(self, join: Join, mapping: Dict[str, str]):
        left_join_cols = list(mapping.keys())
        right_join_cols = [mapping[c] for c in left_join_cols]
        left_candidates = self._usable_indexes(join.left, left_join_cols)
        right_candidates = self._usable_indexes(join.right, right_join_cols)
        if not left_candidates or not right_candidates:
            return None
        compatible = []
        for lc in left_candidates:
            for rc in right_candidates:
                if self._compatible(lc[0], rc[0], mapping):
                    compatible.append((lc, rc))
        if not compatible:
            return None
        ranked = JoinIndexRanker.rank([(l[0], r[0]) for l, r in compatible])
        best = ranked[0]
        for pair in compatible:
            if pair[0][0] is best[0] and pair[1][0] is best[1]:
                return pair
        return compatible[0]

    @staticmethod
    def _compatible(left_index: IndexLogEntry, right_index: IndexLogEntry,
                    mapping: Dict[str, str]) -> bool:
        """Indexed-column ORDER must agree under the left<->right mapping —
        bucket b of each side must hold the same key hashes (reference
        `:547-594`)."""
        left_order = [c.lower() for c in left_index.indexed_columns]
        right_order = [c.lower() for c in right_index.indexed_columns]
        mapped = [mapping.get(c) for c in left_order]
        return mapped == right_order
