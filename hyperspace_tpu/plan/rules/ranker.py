"""JoinIndexRanker: order candidate index pairs.

Parity: reference `index/rankers/JoinIndexRanker.scala:24-56` — pairs with
EQUAL bucket counts first (zero re-bucket traffic: every bucket pair joins
chip-locally), then larger bucket counts (more parallelism / finer shards
across the mesh).
"""

from __future__ import annotations

from typing import List, Tuple

from hyperspace_tpu.index.log_entry import IndexLogEntry


class JoinIndexRanker:
    @staticmethod
    def rank(pairs: List[Tuple[IndexLogEntry, IndexLogEntry]]
             ) -> List[Tuple[IndexLogEntry, IndexLogEntry]]:
        def key(pair):
            left, right = pair
            equal = left.num_buckets == right.num_buckets
            return (0 if equal else 1, -(left.num_buckets + right.num_buckets))
        return sorted(pairs, key=key)
