"""Sketch consultation: which files can a predicate refute?

THE one home of data-skipping pruning decisions (the
`check_metrics_coverage.py` sketch-seam lint bans `load_sketches` /
`prune_files` outside `plan/rules/` and the blob-IO module
`index/sketch.py`). `FilterIndexRule` calls `prune_files` at PLAN time
with the filter condition and a scan's file listing; every decision
here is a REFUTATION — a file is dropped only when no row in it can
make the predicate true — so pruning is bit-identical by construction,
and anything uncertain (unsketched column, unrepresentable literal,
rewritten file, unsupported operator) keeps the file.

Soundness notes (pinned by the no-false-negative property test in
`tests/test_skipping.py`):

- Zone bounds exclude NULLs and NaNs. Comparison predicates cannot be
  satisfied by either (SQL null semantics; IEEE NaN compares false), so
  range refutation over the ok-rows' min/max is exact. `ne` is the one
  operator NaN CAN satisfy (`NaN != v` is true) — it consults
  `has_nan`.
- Literals canonicalize into the column's value space the same way the
  compiled engine does (float32 columns round the literal to float32;
  integer columns with a non-integral float literal never match
  anything, but canonicalization declines rather than guessing — the
  file is kept).
- Conjunctions refute conjunct-wise (a file failing ANY conjunct
  cannot satisfy the AND); disjunctions keep a file ANY disjunct might
  match. Both are over-approximations of satisfiability — sound, just
  not complete.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from hyperspace_tpu.index.sketch import FileSketch, SketchSet
from hyperspace_tpu.plan import expr as E

__all__ = ["prune_files", "predicate_possible"]

_INT_NP = {"int8": np.int8, "int16": np.int16, "int32": np.int32,
           "int64": np.int64, "date32": np.int32, "timestamp": np.int64,
           "bool": np.int64}


def _canon_exact(value, dtype: str):
    """The literal as an exact member of the column's value space, or
    None when it cannot be represented exactly (eq/bloom probes must
    then decline — keeping the file is always safe)."""
    if value is None:
        return None
    if dtype == "string":
        return value if isinstance(value, str) else None
    if isinstance(value, str):
        return None
    if isinstance(value, bool):
        value = int(value)
    if dtype in ("float32", "float64"):
        return (np.float32(value) if dtype == "float32"
                else np.float64(value)).item()
    np_dtype = _INT_NP.get(dtype)
    if np_dtype is None:
        return None
    if isinstance(value, float):
        if not value.is_integer():
            return None
        value = int(value)
    info = np.iinfo(np_dtype)
    if not (info.min <= value <= info.max):
        return None
    return int(value)


def _zone_value(value, dtype: str):
    """The literal in the comparison space the ENGINE evaluates ranges
    in: float32 columns round it (the compiled compare does), strings
    stay strings, other numerics compare raw (int-vs-float python
    comparison is exact). None = incomparable (keep the file)."""
    if value is None:
        return None
    if dtype == "string":
        return value if isinstance(value, str) else None
    if isinstance(value, str):
        return None
    if isinstance(value, bool):
        return int(value)
    if dtype == "float32":
        return np.float32(value).item()
    return value


def _column_literal(expr) -> Optional[Tuple[str, object, bool]]:
    """(column name, literal value, column_on_left) of a comparison's
    operands, or None when the shape is not column-vs-literal."""
    if isinstance(expr.left, E.Column) and isinstance(expr.right, E.Literal):
        return expr.left.name, expr.right.value, True
    if isinstance(expr.left, E.Literal) and isinstance(expr.right, E.Column):
        return expr.right.name, expr.left.value, False
    return None


def _eq_possible(cs, value) -> bool:
    v = _canon_exact(value, cs.dtype)
    if v is None:
        return True
    if cs.ok == 0:
        return False  # only NULL/NaN rows: nothing compares equal
    zv = _zone_value(value, cs.dtype)
    if cs.min is not None and zv is not None \
            and (zv < cs.min or zv > cs.max):
        return False
    if cs.bloom is not None and len(cs.bloom):
        from hyperspace_tpu.exceptions import HyperspaceException
        from hyperspace_tpu.ops.sketch import (bloom_maybe_contains,
                                               probe_hash_pair)
        try:
            h1, h2 = probe_hash_pair(v, cs.dtype)
        except HyperspaceException:
            return True
        return bloom_maybe_contains(cs.bloom, h1, h2)
    return True


def predicate_possible(cond: E.Expression, fsk: FileSketch) -> bool:
    """True when `fsk`'s file MAY contain a row satisfying `cond`;
    False only when the sketches REFUTE it. Unknown shapes answer
    True."""
    if fsk.rows == 0:
        return False
    if isinstance(cond, E.And):
        return (predicate_possible(cond.left, fsk)
                and predicate_possible(cond.right, fsk))
    if isinstance(cond, E.Or):
        return (predicate_possible(cond.left, fsk)
                or predicate_possible(cond.right, fsk))
    if isinstance(cond, E.IsNull) and isinstance(cond.child, E.Column):
        cs = fsk.columns.get(cond.child.name.lower())
        return True if cs is None else cs.nulls > 0
    if isinstance(cond, E.IsNotNull) and isinstance(cond.child, E.Column):
        cs = fsk.columns.get(cond.child.name.lower())
        return True if cs is None else (fsk.rows - cs.nulls) > 0
    if isinstance(cond, E.In) and isinstance(cond.child, E.Column):
        cs = fsk.columns.get(cond.child.name.lower())
        if cs is None:
            return True
        return any(_eq_possible(cs, v.value) for v in cond.values)
    if isinstance(cond, (E.EqualTo, E.NotEqualTo, E.LessThan,
                         E.LessThanOrEqual, E.GreaterThan,
                         E.GreaterThanOrEqual)):
        shape = _column_literal(cond)
        if shape is None:
            return True
        name, value, col_left = shape
        cs = fsk.columns.get(name.lower())
        if cs is None:
            return True
        if isinstance(cond, E.EqualTo):
            return _eq_possible(cs, value)
        if isinstance(cond, E.NotEqualTo):
            if cs.has_nan:
                return True  # NaN != v is TRUE (IEEE)
            v = _canon_exact(value, cs.dtype)
            if cs.ok == 0:
                return False  # only NULL rows: col != v is NULL
            if v is None:
                return True
            return not (cs.min is not None and cs.min == cs.max == v)
        # Range comparison; mirror literal-on-left (v < col  ==  col > v).
        zv = _zone_value(value, cs.dtype)
        if cs.ok == 0 or cs.min is None or zv is None:
            return cs.ok > 0 and (cs.min is None or zv is None)
        op = type(cond)
        if not col_left:
            op = {E.LessThan: E.GreaterThan,
                  E.GreaterThan: E.LessThan,
                  E.LessThanOrEqual: E.GreaterThanOrEqual,
                  E.GreaterThanOrEqual: E.LessThanOrEqual}[op]
        try:
            if op is E.LessThan:
                return cs.min < zv
            if op is E.LessThanOrEqual:
                return cs.min <= zv
            if op is E.GreaterThan:
                return cs.max > zv
            return cs.max >= zv
        except TypeError:
            return True  # incomparable stored/literal types
    return True  # unsupported shape: never refute


def prune_files(condition: E.Expression, files: Sequence[str],
                sketches: SketchSet
                ) -> Tuple[List[str], List[str], int]:
    """Split `files` into (survivors, pruned, bytes_pruned) under
    `condition`. A file is pruned only when it has a sketch row, its
    live (size, stamp) identity still matches the one captured at
    sketch time (a rewritten file is UNKNOWN — kept), and the sketches
    refute the predicate."""
    from hyperspace_tpu.index.signature import file_stamp

    survivors: List[str] = []
    pruned: List[str] = []
    bytes_pruned = 0
    for f in files:
        fsk = sketches.sketch_for(f)
        if fsk is None:
            survivors.append(f)
            continue
        live = file_stamp(f)
        if live is None or int(live[0]) != fsk.size \
                or str(live[1]) != fsk.stamp:
            survivors.append(f)
            continue
        if predicate_possible(condition, fsk):
            survivors.append(f)
        else:
            pruned.append(f)
            bytes_pruned += fsk.size
    return survivors, pruned, bytes_pruned
