"""FilterIndexRule: redirect filter queries to covering indexes.

Parity: reference `index/rules/FilterIndexRule.scala:41-229`.
- Matches `Project(Filter(Scan))` and bare `Filter(Scan)`.
- Candidate = ACTIVE index whose signature matches the plan AND that covers
  it: the filter must reference the index's FIRST indexed column, and
  project+filter columns must be a subset of indexed+included columns
  (reference `:203-215`).
- Ranking is cost-based — smallest on-disk index (fallback: fewest
  columns), more buckets on ties — exceeding the reference's first-wins
  placeholder (`:222-228`).
- Replacement keeps Project+Filter but swaps the relation for a scan over
  the index data root with NO bucket spec — a plain scan keeps full read
  parallelism (reference `:109-131`).
- Any exception makes the rule a no-op with a warning (reference `:76-80`).
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence

from hyperspace_tpu import telemetry
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import Filter, LogicalPlan, Project, Scan
from hyperspace_tpu.plan.rules.base import Rule, _version_of_root

logger = logging.getLogger(__name__)


def _entry_size_bytes(entry: IndexLogEntry) -> int:
    """On-disk size of the index data, from the stats the build stamped
    into the log entry (`extra.stats.dataSizeBytes`, written by
    `actions/create.stamp_stats`) — ZERO filesystem calls on this path.
    Entries from builds predating the stamp fall back to one directory
    walk (compatibility only; every data-writing action now stamps)."""
    stats = entry.extra.get("stats") if isinstance(entry.extra, dict) else None
    if isinstance(stats, dict):
        try:
            return int(stats.get("dataSizeBytes", 0))
        except (TypeError, ValueError):
            return 0
    from hyperspace_tpu.utils.file_utils import get_directory_size
    try:
        return int(get_directory_size(entry.content.root))
    except OSError:
        return 0


def _eq_columns(condition) -> List[str]:
    """Columns compared for EQUALITY against a literal anywhere in the
    conjunction (lowercased, sorted) — the predicates bucket pruning
    accelerates. Conservative: non-conjunctive shapes report empty."""
    from hyperspace_tpu.plan import expr as E
    out = set()
    try:
        for conjunct in E.split_conjunctive(condition):
            if isinstance(conjunct, E.EqualTo):
                for side, other in ((conjunct.left, conjunct.right),
                                    (conjunct.right, conjunct.left)):
                    if isinstance(side, E.Column) \
                            and isinstance(other, E.Literal):
                        out.add(side.name.lower())
            elif isinstance(conjunct, E.In) \
                    and isinstance(conjunct.child, E.Column):
                out.add(conjunct.child.name.lower())
    except Exception:
        return []
    return sorted(out)


class FilterIndexRule(Rule):
    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        self._sig_cache = {}
        try:
            # TOP-DOWN, mirroring the reference's `transform` (pre-order,
            # `FilterIndexRule.scala:42-56`): a Project(Filter(Scan)) must
            # match BEFORE its inner bare Filter(Scan) — coverage judged
            # on the projected columns admits narrower (cheaper) indexes
            # than the bare match's full-schema requirement.
            return plan.transform_down(self._rewrite)
        except Exception as exc:
            logger.warning("FilterIndexRule failed; skipping: %s", exc)
            return plan

    def _rewrite(self, node: LogicalPlan) -> LogicalPlan:
        # Project(Filter(Scan)) or Filter(Scan)
        if isinstance(node, Project) and isinstance(node.child, Filter) \
                and isinstance(node.child.child, Scan):
            project, filt, scan = node, node.child, node.child.child
        elif isinstance(node, Filter) and isinstance(node.child, Scan):
            project, filt, scan = None, node, node.child
        else:
            return node
        if scan.bucket_spec is not None:
            return node  # already an index scan

        filter_columns = sorted(filt.condition.references())
        # Coverage is judged on the SOURCE columns a projection reads —
        # computed entries (Alias expressions) contribute their references.
        project_columns = (sorted(project.references())
                           if project is not None else scan.schema.names)

        index = self._find_covering_index(filt, scan, project_columns,
                                          filter_columns)
        if index is not None:
            source: LogicalPlan = self.index_scan(index, bucketed=True)
            logger.info("FilterIndexRule: applying index %s", index.name)
            telemetry.event(
                "rule", "FilterIndexRule", action="applied",
                indexes=[{"name": index.name, "root": index.content.root,
                          "num_buckets": index.num_buckets,
                          "side": "filter"}])
        else:
            source = self._hybrid_scan_source(filt, scan, project_columns,
                                              filter_columns)
            if source is None:
                # No covering index applies — consult DATA-SKIPPING
                # sketches: drop source files whose zones/blooms refute
                # the predicate (or serve from a Z-order clustered
                # copy). Bit-identical by construction: only files that
                # cannot contain a matching row are dropped.
                source = self._skipping_source(filt, scan)
            if source is None:
                # The whyNot record carries everything an advisor needs
                # to synthesize a candidate for THIS miss: the relation
                # (scan roots), the predicate columns, which of them are
                # point (equality) comparisons — bucket pruning only
                # helps those — and the full column set a covering index
                # would have to carry.
                telemetry.event(
                    "rule", "FilterIndexRule", action="skipped",
                    reason="no ACTIVE covering index matches the plan "
                           "signature (filter must reference the first "
                           "indexed column; all columns must be covered) "
                           "and no data-skipping sketch prunes the scan",
                    filter_columns=list(filter_columns),
                    eq_columns=_eq_columns(filt.condition),
                    project_columns=sorted(
                        {c.lower() for c in project_columns}),
                    roots=list(scan.root_paths))
                return node

        rewritten: LogicalPlan = Filter(filt.condition, source)
        if project is not None:
            rewritten = Project(project.columns, rewritten)
        else:
            # Bare Filter(Scan): restore the base relation's column order —
            # enabling indexes must not change result shape.
            rewritten = Project(scan.schema.names, rewritten)
        return rewritten

    def _skipping_enabled(self) -> bool:
        conf = getattr(self.session, "conf", None)
        return conf is None or conf.skipping_enabled

    def _emit_skipping(self, entry, scan_roots, files_total: int,
                       pruned, bytes_pruned: int, served: str) -> None:
        """Pruning detail into the index-usage telemetry records (the
        event's `root` is the SOURCE root for in-place pruning so the
        usage join finds the scan that read the survivors) + the
        process/per-query `skipping.{files_pruned,bytes_pruned}`
        counters."""
        reg = telemetry.get_registry()
        reg.counter("skipping.files_pruned").inc(len(pruned))
        reg.counter("skipping.bytes_pruned").inc(bytes_pruned)
        telemetry.add_count("skipping.files_pruned", len(pruned))
        telemetry.add_count("skipping.bytes_pruned", bytes_pruned)
        # The MEASURED prune fraction, per served query: the advisor's
        # what-if scorer assumes the blind constant
        # `advisor.skipping.prune.fraction` — this histogram (and the
        # per-index gauge) is what `Hyperspace.advisor()` reports
        # drift against.
        frac = (len(pruned) / files_total) if files_total else 0.0
        reg.histogram("skipping.measured_prune_fraction").observe(frac)
        reg.gauge(
            f"skipping.{entry.name}.measured_prune_fraction").set(
            round(frac, 6))
        telemetry.event(
            "rule", "FilterIndexRule", action="applied",
            indexes=[{"name": entry.name, "root": scan_roots[0],
                      "index_root": entry.content.root,
                      "num_buckets": 0, "side": "skipping",
                      "served": served,
                      # NOT "files_total": index_usage() overlays the
                      # scan's own files_total (the post-prune listing)
                      # over event keys of the same name.
                      "files_considered": files_total,
                      "files_pruned": len(pruned),
                      "bytes_pruned": bytes_pruned}])

    def _prune_file_list(self, condition, files):
        """Prune `files` (source-data paths) with the best ACTIVE
        non-Z-order skipping sketch available. Returns
        (survivors, pruned, bytes_pruned, entry) — unchanged input and
        entry=None when nothing applies. Sketch-blob problems degrade
        to no pruning, never an error."""
        if not files or not self._skipping_enabled():
            return list(files), [], 0, None
        from hyperspace_tpu.index.sketch import load_sketches
        from hyperspace_tpu.plan.rules.skipping import prune_files
        for entry in self._skipping_indexes():
            if entry.derived_dataset.zorder_by:
                continue  # z-order entries serve whole scans, not lists
            try:
                sketches = load_sketches(entry.content.root)
            except Exception as exc:
                logger.warning("Skipping index %s blob unusable (%s); "
                               "not pruning", entry.name, exc)
                continue
            if not any(f in sketches.files for f in files):
                continue  # sketches cover a different relation
            survivors, pruned, bytes_pruned = prune_files(
                condition, files, sketches)
            if pruned:
                return survivors, pruned, bytes_pruned, entry
        return list(files), [], 0, None

    def _skipping_source(self, filt: Filter, scan: Scan):
        """Data-skipping rewrite when no covering index applies:

        - a Z-ORDER entry whose signature matches the scan serves the
          query from its clustered copy, restricted to the copy files
          the predicate cannot refute (tight zones by construction);
        - otherwise the scan is restricted IN PLACE to the source files
          the sketches cannot refute (explicit file list — plan-time
          pinned by definition).

        Returns a replacement source plan, or None when nothing prunes
        (an unpruned rewrite would be pure churn)."""
        if not self._skipping_enabled():
            return None
        from hyperspace_tpu.index.sketch import load_sketches
        from hyperspace_tpu.plan.rules.skipping import prune_files
        from hyperspace_tpu.plan.schema import Schema

        files = scan.files()
        if not files:
            return None
        for entry in self._skipping_indexes():
            dd = entry.derived_dataset
            if dd.zorder_by:
                # Serving from the copy requires the copy to represent
                # exactly the CURRENT source: signature match, plus a
                # schema covering the scan's.
                if not self.signature_matches(entry, scan):
                    continue
                try:
                    copy_schema = Schema.from_json(entry.schema_json)
                except Exception:
                    continue
                scan_names = {f.name.lower() for f in scan.schema.fields}
                if not scan_names <= {f.name.lower()
                                      for f in copy_schema.fields}:
                    continue
                try:
                    sketches = load_sketches(entry.content.root)
                except Exception as exc:
                    logger.warning("Skipping index %s blob unusable "
                                   "(%s); not serving", entry.name, exc)
                    continue
                copy_files = sorted(sketches.files)
                survivors, pruned, bytes_pruned = prune_files(
                    filt.condition, copy_files, sketches)
                if not pruned:
                    continue  # no win over the source scan
                replacement = Scan(
                    [entry.content.root], scan.schema,
                    files=survivors, index_name=entry.name,
                    pinned_version=_version_of_root(entry.content.root))
                logger.info(
                    "FilterIndexRule: z-order skipping index %s prunes "
                    "%d/%d copy files", entry.name, len(pruned),
                    len(copy_files))
                self._emit_skipping(entry, [entry.content.root],
                                    len(copy_files), pruned, bytes_pruned,
                                    served="zorder-copy")
                return replacement
            try:
                sketches = load_sketches(entry.content.root)
            except Exception as exc:
                logger.warning("Skipping index %s blob unusable (%s); "
                               "not pruning", entry.name, exc)
                continue
            if not any(f in sketches.files for f in files):
                continue
            survivors, pruned, bytes_pruned = prune_files(
                filt.condition, files, sketches)
            if not pruned:
                continue
            logger.info("FilterIndexRule: skipping index %s prunes "
                        "%d/%d source files", entry.name, len(pruned),
                        len(files))
            self._emit_skipping(entry, scan.root_paths, len(files),
                                pruned, bytes_pruned, served="source")
            return Scan(scan.root_paths, scan.schema, files=survivors)
        return None

    def _hybrid_scan_source(self, filt: Filter, scan: Scan,
                            project_columns: Sequence[str],
                            filter_columns: Sequence[str]):
        """Hybrid Scan (extension; reference roadmap): when the index covers
        the columns but the source has CHANGED since build time, serve the
        query from index data anyway — appended files ride along as a
        UNION branch, and (for lineage-enabled indexes) deleted files'
        rows are excluded by a `_hs_file_id NOT IN (...)` filter pushed
        onto the index scan. No refresh required. Gated on
        `spark.hyperspace.index.hybridscan.enabled`."""
        from hyperspace_tpu import constants
        from hyperspace_tpu.plan.nodes import Union

        if self.session.conf.get(constants.HYBRID_SCAN_ENABLED,
                                 "false").lower() != "true":
            return None
        from hyperspace_tpu.index.source_delta import (classify_current,
                                                       restricted_scan,
                                                       split_current)
        needed = ({c for c in filter_columns}
                  | {c for c in project_columns})
        for entry in self._covering_indexes():
            if not self._covers(entry, project_columns, filter_columns):
                continue
            delta = classify_current(entry, scan.files())
            if delta is not None:
                appended, deleted_ids, modified = delta
                # In-place rewrites invalidate the index rows of that file
                # with no way to tell which rows changed — decline.
                if modified or not (appended or deleted_ids):
                    continue
            else:
                # Pre-lineage entry: per-file stamps absent, so deletions
                # are un-servable and untouched-survivor proof falls back
                # to the aggregate signature over the stored file set.
                # (Path-set subset alone misses in-place rewrites.)
                appended, missing, stored = split_current(entry, scan.files())
                deleted_ids = []
                if missing or not appended or not stored:
                    continue
                if not self.signature_matches(entry,
                                              restricted_scan(entry, scan,
                                                              sorted(stored))):
                    continue
            index_source = self.index_scan(entry, bucketed=True)
            if deleted_ids:
                index_source = Filter(self.lineage_exclusion(deleted_ids),
                                      index_source)
            needed_cols = [f.name for f in index_source.schema.fields
                           if f.name.lower() in {c.lower() for c in needed}]
            logger.info("FilterIndexRule: hybrid scan with index %s "
                        "(+%d appended files, -%d deleted files)",
                        entry.name, len(appended), len(deleted_ids))
            telemetry.event(
                "rule", "FilterIndexRule", action="applied",
                indexes=[{"name": entry.name, "root": entry.content.root,
                          "num_buckets": entry.num_buckets,
                          "side": "filter", "hybrid": True,
                          "appended_files": len(appended),
                          "deleted_files": len(deleted_ids)}])
            if not appended:
                return Project(needed_cols, index_source)
            # The covering index's SOURCE-FILE REMAINDER: data-skipping
            # sketches can still thin the appended-files branch of the
            # hybrid union (files indexed by a refreshed skipping index
            # whose zones/blooms refute the predicate).
            appended, rem_pruned, rem_bytes, sk_entry = \
                self._prune_file_list(filt.condition, appended)
            if sk_entry is not None:
                self._emit_skipping(sk_entry, scan.root_paths,
                                    len(appended) + len(rem_pruned),
                                    rem_pruned, rem_bytes,
                                    served="hybrid-remainder")
            if not appended:
                return Project(needed_cols, index_source)
            appended_scan = Scan(scan.root_paths, scan.schema,
                                 files=appended)
            return Union([Project(needed_cols, index_source),
                          Project(needed_cols, appended_scan)])
        return None

    def _find_covering_index(self, filt: Filter, scan: Scan,
                             project_columns: Sequence[str],
                             filter_columns: Sequence[str]) -> Optional[IndexLogEntry]:
        """Reference `FilterIndexRule.scala:146-228`."""
        candidates: List[IndexLogEntry] = []
        for entry in self._covering_indexes():
            if not self._covers(entry, project_columns, filter_columns):
                continue
            if not self.signature_matches(entry, filt):
                continue
            candidates.append(entry)
        if not candidates:
            return None
        if len(candidates) == 1:
            return candidates[0]
        return self._rank(candidates)

    @staticmethod
    def _rank(candidates: List[IndexLogEntry]) -> IndexLogEntry:
        """Cost-based selection — exceeds the reference's first-wins
        placeholder (`FilterIndexRule.scala:222-228`): among covering
        candidates, pick the one that reads the FEWEST BYTES (on-disk
        size of the index data root, the exact cost of the swapped-in
        scan); when any candidate's storage is unstatable, fall back to
        total column count (fewer columns ~ narrower rows ~ fewer
        bytes). Ties break toward MORE buckets (finer point-filter
        bucket pruning: each point value reads 1/num_buckets of the
        files), then name for determinism."""
        sizes = []
        for entry in candidates:
            size = _entry_size_bytes(entry)
            # 0 bytes means missing/unreadable as much as legitimately
            # empty. An index whose data root vanished must never WIN the
            # ranking by looking free: candidates with real bytes beat
            # 0-byte ones outright; with no sized candidate at all, fall
            # back to the column-count proxy. NOTE: stamped stats are
            # trusted as-is (metadata-only ranking, zero FS calls) — a
            # data root deleted out-of-band AFTER a stamped build is not
            # re-detected here and fails loudly at scan time instead;
            # the walk fallback preserves the 0-byte guard only for
            # legacy stampless entries.
            sizes.append(size if size > 0 else None)
        sized = [(s, e) for s, e in zip(sizes, candidates) if s is not None]
        if sized:
            return min(sized,
                       key=lambda p: (p[0], -p[1].num_buckets, p[1].name))[1]
        counts = [len(e.indexed_columns) + len(e.included_columns)
                  for e in candidates]
        return min(zip(counts, candidates),
                   key=lambda p: (p[0], -p[1].num_buckets, p[1].name))[1]

    @staticmethod
    def _covers(entry: IndexLogEntry, project_columns: Sequence[str],
                filter_columns: Sequence[str]) -> bool:
        """Filter columns must include the index's first indexed column and
        all referenced columns must be covered (reference `:203-215`)."""
        first_indexed = entry.indexed_columns[0].lower()
        filter_lower = {c.lower() for c in filter_columns}
        if first_indexed not in filter_lower:
            return False
        covered = {c.lower() for c in
                   (entry.indexed_columns + entry.included_columns)}
        referenced = filter_lower | {c.lower() for c in project_columns}
        return referenced <= covered
