"""Shared rule machinery."""

from __future__ import annotations

import logging
from typing import List, Optional

from hyperspace_tpu.constants import States
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.signature import SignatureProviderFactory
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.plan.schema import Schema

logger = logging.getLogger(__name__)


class Rule:
    """A logical plan rewrite rule (the reference's Catalyst
    `Rule[LogicalPlan]` analog)."""

    def __init__(self, session):
        self.session = session
        # (provider name, plan identity) -> signature, valid within one
        # apply(); avoids re-stat'ing every source file once per candidate
        # index.
        self._sig_cache = {}

    def _active_indexes(self) -> List[IndexLogEntry]:
        """ACTIVE catalog entries via the session context's caching manager
        (reference reads `Hyperspace.getContext(spark).indexCollectionManager
        .getIndexes(ACTIVE)`, `JoinIndexRule.scala:90-93`)."""
        from hyperspace_tpu.facade import Hyperspace
        manager = Hyperspace.get_context(self.session).index_collection_manager
        return manager.get_indexes([States.ACTIVE])

    def signature_matches(self, entry: IndexLogEntry, plan: LogicalPlan) -> bool:
        """Recompute the plan's signature with the provider recorded in the
        index metadata and compare (reference `FilterIndexRule.scala:155-168`).
        Cached per (provider, plan) within one rule application."""
        stored = entry.signature()
        cache_key = (stored.provider, id(plan))
        if cache_key not in self._sig_cache:
            try:
                provider = SignatureProviderFactory.create(stored.provider)
                sig = provider.signature(plan)
            except Exception as exc:  # provider failure -> no match, not a crash
                logger.warning("Signature provider %s failed: %s",
                               stored.provider, exc)
                sig = None
            # Pin the plan object in the cache value: id() keys are only
            # unique while the object is alive, and per-candidate plans
            # built inside one apply() can be GC'd and their id reused.
            self._sig_cache[cache_key] = (plan, sig)
        current = self._sig_cache[cache_key][1]
        return current is not None and current == stored.value

    @staticmethod
    def index_scan(entry: IndexLogEntry, bucketed: bool) -> Scan:
        """Build the replacement relation over the index data. The
        reference's filter rewrite drops the BucketSpec to keep Spark's
        scan parallelism (`FilterIndexRule.scala:112-120`); this engine's
        scan parallelism is unaffected by the spec, so filter rewrites
        KEEP it (bucketed=True) — it is what lets the planner prune the
        read to the literal's hash bucket(s). Join rewrites likewise pass
        bucketed=True so Exchange+Sort are elided (reference
        `JoinIndexRule.scala:124-153`)."""
        from hyperspace_tpu.plan.nodes import BucketSpec

        schema = Schema.from_json(entry.schema_json)
        bucket_spec = None
        if bucketed:
            bucket_spec = BucketSpec(entry.num_buckets,
                                     tuple(entry.indexed_columns),
                                     tuple(entry.indexed_columns))
        return Scan([entry.content.root], schema, bucket_spec=bucket_spec)

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        raise NotImplementedError
