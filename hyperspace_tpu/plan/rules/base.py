"""Shared rule machinery."""

from __future__ import annotations

import logging
from typing import List, Optional

from hyperspace_tpu.constants import States
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.signature import SignatureProviderFactory
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.plan.schema import Schema

logger = logging.getLogger(__name__)


_layout_hash_memo: dict = {}


def _version_of_root(root: str):
    """Committed `v__=N` parsed from an index data root, or None for a
    root that is not a version dir (fabricated/test entries). Entries
    only reach ACTIVE after their version committed (the `_committed`
    marker is the build's last data write), so a parseable version here
    is a committed one by construction — same invariant
    `io/segcache.segment_ref_for_scan` rides."""
    import os
    import re

    from hyperspace_tpu import constants
    m = re.search(re.escape(constants.INDEX_VERSION_DIRECTORY_PREFIX)
                  + r"=(\d+)$", os.path.basename(root.rstrip("/\\")))
    return int(m.group(1)) if m else None


def _layout_hash_current(root: str) -> bool:
    """True when the bucketed layout at `root` was written under the
    CURRENT bucket-hash identity (`io/parquet.BUCKET_HASH_VERSION`).
    Index data dirs (`v__=N`) are immutable, so definitive answers are
    memoized; a TRANSIENT storage error answers False for this query only
    (unbucketed = correct, just unaccelerated) without poisoning the memo.
    Every real build writes the sidecar, so a sidecar carrying an older
    (or no) hashVersion means a stale layout; a MISSING sidecar means a
    fabricated/test entry and trusts the log entry."""
    cached = _layout_hash_memo.get(root)
    if cached is not None:
        return cached
    from hyperspace_tpu.io import parquet
    from hyperspace_tpu.utils import file_utils
    from hyperspace_tpu.utils.storage import join as _join
    try:
        if not file_utils.exists(_join(root, parquet.BUCKET_SPEC_FILE)):
            result = True
        else:
            result = parquet.read_bucket_spec(root) is not None
    except Exception as exc:
        logger.warning("Unreadable bucket spec at %s: %s", root, exc)
        return False  # transient: do not memoize
    if len(_layout_hash_memo) < 4096:
        _layout_hash_memo[root] = result
    return result


_layout_hash_current.cache_clear = _layout_hash_memo.clear  # test seam


class Rule:
    """A logical plan rewrite rule (the reference's Catalyst
    `Rule[LogicalPlan]` analog)."""

    def __init__(self, session):
        self.session = session
        # (provider name, plan identity) -> signature, valid within one
        # apply(); avoids re-stat'ing every source file once per candidate
        # index.
        self._sig_cache = {}

    def _active_indexes(self) -> List[IndexLogEntry]:
        """ACTIVE catalog entries via the session context's caching manager
        (reference reads `Hyperspace.getContext(spark).indexCollectionManager
        .getIndexes(ACTIVE)`, `JoinIndexRule.scala:90-93`)."""
        from hyperspace_tpu.facade import Hyperspace
        manager = Hyperspace.get_context(self.session).index_collection_manager
        return manager.get_indexes([States.ACTIVE])

    def _covering_indexes(self) -> List[IndexLogEntry]:
        """ACTIVE COVERING entries — what the scan-replacement candidate
        loops iterate. With a second index kind in the catalog
        (DataSkippingIndex), a kind filter here keeps covering-specific
        surface (first-indexed-column coverage, bucket specs) off
        entries that have neither."""
        return [e for e in self._active_indexes()
                if e.kind == "CoveringIndex"]

    def _skipping_indexes(self) -> List[IndexLogEntry]:
        """ACTIVE data-skipping entries, Z-order builds first (they can
        both serve AND prune), then by name for determinism."""
        entries = [e for e in self._active_indexes()
                   if e.kind == "DataSkippingIndex"]
        return sorted(entries,
                      key=lambda e: (not e.derived_dataset.zorder_by,
                                     e.name))

    def signature_matches(self, entry: IndexLogEntry, plan: LogicalPlan) -> bool:
        """Recompute the plan's signature with the provider recorded in the
        index metadata and compare (reference `FilterIndexRule.scala:155-168`).
        Cached per (provider, plan) within one rule application."""
        stored = entry.signature()
        cache_key = (stored.provider, id(plan))
        if cache_key not in self._sig_cache:
            try:
                provider = SignatureProviderFactory.create(stored.provider)
                sig = provider.signature(plan)
            except Exception as exc:  # provider failure -> no match, not a crash
                logger.warning("Signature provider %s failed: %s",
                               stored.provider, exc)
                sig = None
            # Pin the plan object in the cache value: id() keys are only
            # unique while the object is alive, and per-candidate plans
            # built inside one apply() can be GC'd and their id reused.
            self._sig_cache[cache_key] = (plan, sig)
        current = self._sig_cache[cache_key][1]
        return current is not None and current == stored.value

    @staticmethod
    def index_scan(entry: IndexLogEntry, bucketed: bool) -> Scan:
        """Build the replacement relation over the index data. The
        reference's filter rewrite drops the BucketSpec to keep Spark's
        scan parallelism (`FilterIndexRule.scala:112-120`); this engine's
        scan parallelism is unaffected by the spec, so filter rewrites
        KEEP it (bucketed=True) — it is what lets the planner prune the
        read to the literal's hash bucket(s). Join rewrites likewise pass
        bucketed=True so Exchange+Sort are elided (reference
        `JoinIndexRule.scala:124-153`)."""
        from hyperspace_tpu.plan.nodes import BucketSpec

        schema = Schema.from_json(entry.schema_json)
        bucket_spec = None
        if bucketed and _layout_hash_current(entry.content.root):
            # The sidecar records which bucket-hash identity wrote the
            # layout; a dir written under an older identity (e.g. before
            # the float -0.0/NaN normalization) must read as unbucketed —
            # correct, just unaccelerated — or point lookups and
            # co-partitioned joins would silently miss rows.
            bucket_spec = BucketSpec(entry.num_buckets,
                                     tuple(entry.indexed_columns),
                                     tuple(entry.indexed_columns))
        # index_name marks the scan as rule-selected index data: if that
        # data is missing/unreadable at execution time the scan raises
        # IndexDataUnavailableError and the query degrades to the source
        # plan instead of failing (graceful degradation).
        scan = Scan([entry.content.root], schema, bucket_spec=bucket_spec,
                    index_name=entry.name,
                    pinned_version=_version_of_root(entry.content.root))
        if scan.pinned_version is not None:
            # Snapshot pin: resolve the committed version's file listing
            # ONCE, at plan time. Execution (including the bucketed read
            # paths) consumes this listing instead of re-listing the
            # directory, so a refresh committing v__=N+1 — or any writer
            # touching the dir — between plan and scan cannot change
            # what this plan reads; the segment cache pins the same
            # version by keying on it. Version dirs are FLAT by
            # construction (every writer emits part files at the top
            # level), so the pin takes one listdir, not the generic
            # recursive glob — this runs on every optimize of every
            # index-served query.
            from hyperspace_tpu.utils import storage
            root = entry.content.root
            try:
                if storage.is_url(root):
                    names = storage.listdir_names(root)
                    join = storage.join
                else:
                    import os as _os
                    names = _os.listdir(root) if _os.path.isdir(root) \
                        else []
                    join = _os.path.join
                suffix = "." + scan.file_format
                scan._files = sorted(join(root, n) for n in names
                                     if n.endswith(suffix))
            except Exception:
                scan.files()  # odd backend: pay the generic listing
        return scan

    @staticmethod
    def lineage_exclusion(deleted_ids):
        """`_hs_file_id NOT IN (deleted...)` predicate excluding the index
        rows of deleted source files (hybrid scan over deletes; lineage-
        enabled builds only)."""
        from hyperspace_tpu import constants
        from hyperspace_tpu.plan import expr as E
        return ~E.Column(constants.LINEAGE_COLUMN).isin(
            *[int(i) for i in deleted_ids])

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        raise NotImplementedError
