"""Projected per-query memory footprint — the admission-control input.

The serving plane (`engine/scheduler.py`) admits each query against a
byte budget; what it needs from the plan layer is a CONSERVATIVE
estimate of how much host+device working memory executing the plan may
pin at once. Exact answers are impossible before execution (selectivity,
join fan-out), so the estimate is deliberately simple and biased high:

- every Scan contributes the total on-disk size of its files times
  `DECODE_EXPANSION` (parquet is column-compressed; decoded Arrow +
  numpy staging + a device copy routinely run 2-4x the file bytes);
- a scan whose files cannot be listed or stat'ed (remote store hiccup,
  empty glob) contributes `DEFAULT_SCAN_BYTES` instead — admission
  control must DEGRADE to a guess, never block on or crash from a
  storage error (the storage plane has its own retry/degradation
  story);
- the whole-plan floor is `MIN_FOOTPRINT_BYTES`, so a zero-byte plan
  still pays a nonzero admission (executor scratch, jit workspace).

Operators above the scans are NOT modeled: sort/join scratch scales
with scan bytes for this engine's operators (masked fusion keeps
intermediates at source row count), and the expansion factor absorbs
it. When real workloads prove the bias wrong, tune the constants —
the scheduler reads only `projected_bytes`.
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

from hyperspace_tpu.plan.nodes import LogicalPlan, Scan

__all__ = ["projected_bytes", "scan_disk_bytes", "file_sizes_total",
           "invalidate_sizes", "DECODE_EXPANSION", "DEFAULT_SCAN_BYTES",
           "MIN_FOOTPRINT_BYTES"]

# Decoded + staged + device-resident expansion over on-disk parquet.
DECODE_EXPANSION = 3.0

# Per-scan stand-in when file sizes are unknowable (listing/stat
# failed): 32 MiB — large enough that a burst of unknown scans still
# queues under a tight budget, small enough not to starve admission.
DEFAULT_SCAN_BYTES = 32 * 1024 * 1024

# Whole-plan floor.
MIN_FOOTPRINT_BYTES = 1 * 1024 * 1024

# Per-file size cache, STAMP-VALIDATED: footprint estimation runs on
# EVERY collect, and serving traffic re-scans the same hot index files
# — but a file rewritten in place (source data appends, a hybrid-scan
# dir, an object-store overwrite) must not keep serving its old size
# to admission control forever. Entries validate against the same
# (size, mtime) stamp the parquet caches use (`io/parquet._file_stamp`)
# — and since the stamp CARRIES the size, a validated hit and a
# revalidation cost the same single stat. The index-FSM invalidation
# hook (`io/segcache.py`) additionally sweeps entries under a
# committed index root (`invalidate_sizes`).
_size_cache: Dict[str, Tuple[object, int]] = {}


def _file_size(path: str) -> int:
    from hyperspace_tpu.io.parquet import _file_stamp
    try:
        stamp = _file_stamp(path)
    except Exception:
        stamp = None
    if stamp is None:
        # Unstampable (directory, no mtime, stat failure): unknowable —
        # never cached, caller substitutes the default.
        _size_cache.pop(path, None)
        return -1
    cached = _size_cache.get(path)
    if cached is not None and cached[0] == stamp:
        return cached[1]
    size = int(stamp[0])
    if len(_size_cache) > 65536:  # bound the cache, arbitrary-large safe
        _size_cache.clear()
    _size_cache[path] = (stamp, size)
    return size


def invalidate_sizes(prefix: str) -> None:
    """Drop cached sizes for every file under `prefix` (the index-FSM
    invalidation hook — a refresh/optimize/vacuum boundary must not
    leave admission control reading pre-commit sizes)."""
    prefix = prefix.rstrip("/\\")
    for path in [p for p in _size_cache
                 if p == prefix or p.startswith(prefix + "/")
                 or p.startswith(prefix + os.sep)]:
        _size_cache.pop(path, None)
    for key in [k for k in _pinned_bytes_cache
                if k[0] == prefix or k[0].startswith(prefix + "/")
                or k[0].startswith(prefix + os.sep)]:
        _pinned_bytes_cache.pop(key, None)


# Per-(root, pinned version) total-bytes memo for VERSION-PINNED index
# scans: a committed `v__=N` dir is immutable, so its total on-disk
# size never changes — the footprint re-projection that runs on every
# optimized plan (scheduler credit) must not re-stat 200 bucket files
# per collect. Swept by `invalidate_sizes` with everything else;
# bounded like the per-file cache.
_pinned_bytes_cache: Dict[Tuple[str, int], int] = {}


def _scan_bytes(scan: Scan) -> int:
    pinned = getattr(scan, "pinned_version", None)
    pin_key = None
    if pinned is not None and not getattr(scan, "_explicit_files", False) \
            and len(scan.root_paths) == 1:
        pin_key = (scan.root_paths[0], int(pinned))
        hit = _pinned_bytes_cache.get(pin_key)
        if hit is not None:
            return hit
    try:
        files = scan.files()
    except Exception:
        return DEFAULT_SCAN_BYTES
    if not files:
        return 0
    total = 0
    unknown = 0
    for f in files:
        size = _file_size(f)
        if size < 0:
            unknown += 1
        else:
            total += size
    if unknown:
        # Extrapolate unknown files from the known mean (or the default
        # when nothing stat'ed) — still biased high via the expansion.
        known = len(files) - unknown
        per = (total // known) if known else DEFAULT_SCAN_BYTES
        total += unknown * per
    elif pin_key is not None:
        if len(_pinned_bytes_cache) > 4096:
            _pinned_bytes_cache.clear()
        _pinned_bytes_cache[pin_key] = total
    return total


def file_sizes_total(files) -> int:
    """Summed on-disk bytes of `files` through the stamp-validated size
    cache (admission control stats the same files every collect, so
    calls on the execute path hit warm cache/dentry entries). Unstatable
    files contribute 0 — this is a telemetry/estimation input, not a
    correctness one."""
    total = 0
    for f in files:
        try:
            size = _file_size(f)
        except Exception:
            size = -1
        if size > 0:
            total += size
    return total


def scan_disk_bytes(plan: LogicalPlan) -> int:
    """Total RAW on-disk bytes of every Scan leaf of `plan` (no decode
    expansion, no floor) — the what-if scorer's before/after unit
    (`hyperspace_tpu/advisor/whatif.py`). Degrades like
    `projected_bytes`: estimation failures return the default, never
    raise."""
    total = 0
    try:
        def visit(node):
            nonlocal total
            if isinstance(node, Scan):
                total += max(0, _scan_bytes(node))
            for c in node.children:
                visit(c)

        visit(plan)
    except Exception:
        return DEFAULT_SCAN_BYTES
    return total


def projected_bytes(plan: LogicalPlan) -> int:
    """Conservative projected working-set bytes of executing `plan`
    (module docstring). Never raises: estimation failures degrade to
    the defaults — admission control is a budget gate, not a second
    failure mode."""
    scans = 0
    disk = 0
    try:
        def visit(node):
            nonlocal scans, disk
            if isinstance(node, Scan):
                scans += 1
                disk += _scan_bytes(node)
            for c in node.children:
                visit(c)

        visit(plan)
    except Exception:
        return max(MIN_FOOTPRINT_BYTES, DEFAULT_SCAN_BYTES)
    est = int(disk * DECODE_EXPANSION)
    if scans and est <= 0:
        est = DEFAULT_SCAN_BYTES
    return max(MIN_FOOTPRINT_BYTES, est)
