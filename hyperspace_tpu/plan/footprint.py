"""Projected per-query memory footprint — the admission-control input.

The serving plane (`engine/scheduler.py`) admits each query against a
byte budget; what it needs from the plan layer is a CONSERVATIVE
estimate of how much host+device working memory executing the plan may
pin at once. Exact answers are impossible before execution (selectivity,
join fan-out), so the estimate is deliberately simple and biased high:

- every Scan contributes the total on-disk size of its files times
  `DECODE_EXPANSION` (parquet is column-compressed; decoded Arrow +
  numpy staging + a device copy routinely run 2-4x the file bytes);
- a scan whose files cannot be listed or stat'ed (remote store hiccup,
  empty glob) contributes `DEFAULT_SCAN_BYTES` instead — admission
  control must DEGRADE to a guess, never block on or crash from a
  storage error (the storage plane has its own retry/degradation
  story);
- the whole-plan floor is `MIN_FOOTPRINT_BYTES`, so a zero-byte plan
  still pays a nonzero admission (executor scratch, jit workspace).

Operators above the scans are NOT modeled: sort/join scratch scales
with scan bytes for this engine's operators (masked fusion keeps
intermediates at source row count), and the expansion factor absorbs
it. When real workloads prove the bias wrong, tune the constants —
the scheduler reads only `projected_bytes`.
"""

from __future__ import annotations

from typing import Dict

from hyperspace_tpu.plan.nodes import LogicalPlan, Scan

__all__ = ["projected_bytes", "DECODE_EXPANSION", "DEFAULT_SCAN_BYTES",
           "MIN_FOOTPRINT_BYTES"]

# Decoded + staged + device-resident expansion over on-disk parquet.
DECODE_EXPANSION = 3.0

# Per-scan stand-in when file sizes are unknowable (listing/stat
# failed): 32 MiB — large enough that a burst of unknown scans still
# queues under a tight budget, small enough not to starve admission.
DEFAULT_SCAN_BYTES = 32 * 1024 * 1024

# Whole-plan floor.
MIN_FOOTPRINT_BYTES = 1 * 1024 * 1024

# Per-file size cache: footprint estimation runs on EVERY collect, and
# serving traffic re-scans the same hot index files; one stat per file
# per process is plenty (a refreshed index writes NEW v__=N paths, so
# stale sizes age out with their files).
_size_cache: Dict[str, int] = {}


def _file_size(path: str) -> int:
    cached = _size_cache.get(path)
    if cached is not None:
        return cached
    from hyperspace_tpu.utils import storage
    try:
        if storage.is_url(path):
            fs, real = storage.get_fs(path)
            size = int(fs.info(real).get("size") or 0)
        else:
            import os
            size = os.path.getsize(path)
    except Exception:
        size = -1  # unknowable: caller substitutes the default
    if len(_size_cache) > 65536:  # bound the cache, arbitrary-large safe
        _size_cache.clear()
    _size_cache[path] = size
    return size


def _scan_bytes(scan: Scan) -> int:
    try:
        files = scan.files()
    except Exception:
        return DEFAULT_SCAN_BYTES
    if not files:
        return 0
    total = 0
    unknown = 0
    for f in files:
        size = _file_size(f)
        if size < 0:
            unknown += 1
        else:
            total += size
    if unknown:
        # Extrapolate unknown files from the known mean (or the default
        # when nothing stat'ed) — still biased high via the expansion.
        known = len(files) - unknown
        per = (total // known) if known else DEFAULT_SCAN_BYTES
        total += unknown * per
    return total


def projected_bytes(plan: LogicalPlan) -> int:
    """Conservative projected working-set bytes of executing `plan`
    (module docstring). Never raises: estimation failures degrade to
    the defaults — admission control is a budget gate, not a second
    failure mode."""
    scans = 0
    disk = 0
    try:
        def visit(node):
            nonlocal scans, disk
            if isinstance(node, Scan):
                scans += 1
                disk += _scan_bytes(node)
            for c in node.children:
                visit(c)

        visit(plan)
    except Exception:
        return max(MIN_FOOTPRINT_BYTES, DEFAULT_SCAN_BYTES)
    est = int(disk * DECODE_EXPANSION)
    if scans and est <= 0:
        est = DEFAULT_SCAN_BYTES
    return max(MIN_FOOTPRINT_BYTES, est)
