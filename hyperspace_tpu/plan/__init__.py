from hyperspace_tpu.plan.schema import Field, Schema
from hyperspace_tpu.plan.expr import (
    Add, And, Column, Div, EqualTo, Expression, GreaterThan, GreaterThanOrEqual,
    In, IsNotNull, IsNull, LessThan, LessThanOrEqual, Literal, Mul, Not,
    NotEqualTo, Or, Sub,
)
from hyperspace_tpu.plan.nodes import (
    Aggregate, AggSpec, BucketSpec, Filter, Join, Limit, LogicalPlan,
    Project, Scan, Sort, Union,
)

__all__ = [
    "Field", "Schema",
    "Add", "And", "Column", "Div", "EqualTo", "Expression", "GreaterThan",
    "GreaterThanOrEqual", "In", "IsNotNull", "IsNull", "LessThan",
    "LessThanOrEqual", "Literal", "Mul", "Not", "NotEqualTo", "Or", "Sub",
    "Aggregate", "AggSpec", "BucketSpec", "Filter", "Join", "Limit",
    "LogicalPlan", "Project", "Scan", "Sort", "Union",
]
