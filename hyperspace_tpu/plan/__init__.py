from hyperspace_tpu.plan.schema import Field, Schema
from hyperspace_tpu.plan.expr import (
    Add, And, Column, Div, EqualTo, Expression, GreaterThan, GreaterThanOrEqual,
    In, IsNotNull, IsNull, LessThan, LessThanOrEqual, Literal, Mul, Not,
    NotEqualTo, Or, Sub,
)
from hyperspace_tpu.plan.nodes import (
    BucketSpec, Filter, Join, LogicalPlan, Project, Scan, Union,
)

__all__ = [
    "Field", "Schema",
    "Add", "And", "Column", "Div", "EqualTo", "Expression", "GreaterThan",
    "GreaterThanOrEqual", "In", "IsNotNull", "IsNull", "LessThan",
    "LessThanOrEqual", "Literal", "Mul", "Not", "NotEqualTo", "Or", "Sub",
    "BucketSpec", "Filter", "Join", "LogicalPlan", "Project", "Scan", "Union",
]
