"""Schema model for the relational IR.

The reference stores a Spark StructType JSON string in the index metadata
(`index/IndexLogEntry.scala:39-47`); this framework owns its schema type with
a stable JSON form, plus mappings to pyarrow and jax/numpy dtypes for the
columnar substrate.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Iterable, List, Optional

from hyperspace_tpu.exceptions import HyperspaceException

# Canonical logical type names.
_TYPES = {
    "bool", "int8", "int16", "int32", "int64", "float32", "float64",
    "string", "date32", "timestamp",
}

_ARROW_TO_LOGICAL = {
    "bool": "bool",
    "int8": "int8", "int16": "int16", "int32": "int32", "int64": "int64",
    "uint8": "int16", "uint16": "int32", "uint32": "int64",
    "float": "float32", "double": "float64",
    "string": "string", "large_string": "string",
    "date32[day]": "date32",
}


@dataclass(frozen=True)
class Field:
    name: str
    dtype: str
    nullable: bool = True

    def __post_init__(self):
        if self.dtype not in _TYPES:
            raise HyperspaceException(f"Unsupported field type: {self.dtype}")

    def to_dict(self) -> dict:
        return {"name": self.name, "type": self.dtype, "nullable": self.nullable}

    @staticmethod
    def from_dict(d: dict) -> "Field":
        return Field(d["name"], d["type"], d.get("nullable", True))


class Schema:
    def __init__(self, fields: Iterable[Field]):
        self.fields: List[Field] = list(fields)
        self._by_lower = {f.name.lower(): f for f in self.fields}

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        f = self._by_lower.get(name.lower())
        if f is None:
            raise HyperspaceException(f"Column not found in schema: {name}")
        return f

    def contains(self, name: str) -> bool:
        return name.lower() in self._by_lower

    def select(self, names: Iterable[str]) -> "Schema":
        return Schema([self.field(n) for n in names])

    def to_json(self) -> str:
        return json.dumps({"type": "struct",
                           "fields": [f.to_dict() for f in self.fields]})

    @staticmethod
    def from_json(text: str) -> "Schema":
        d = json.loads(text)
        return Schema([Field.from_dict(f) for f in d["fields"]])

    def __eq__(self, other) -> bool:
        if not isinstance(other, Schema):
            return NotImplemented
        return self.fields == other.fields

    def __repr__(self) -> str:
        inner = ", ".join(f"{f.name}:{f.dtype}" for f in self.fields)
        return f"Schema({inner})"

    @staticmethod
    def from_arrow(arrow_schema) -> "Schema":
        fields = []
        for f in arrow_schema:
            type_str = str(f.type)
            if type_str.startswith("timestamp"):
                logical = "timestamp"
            elif type_str.startswith("dictionary"):
                logical = "string"
            elif type_str.startswith("decimal"):
                logical = "float64"
            else:
                logical = _ARROW_TO_LOGICAL.get(type_str)
            if logical is None:
                raise HyperspaceException(f"Unsupported arrow type: {type_str}")
            fields.append(Field(f.name, logical, f.nullable))
        return Schema(fields)

    def to_arrow(self):
        import pyarrow as pa
        mapping = {
            "bool": pa.bool_(), "int8": pa.int8(), "int16": pa.int16(),
            "int32": pa.int32(), "int64": pa.int64(),
            "float32": pa.float32(), "float64": pa.float64(),
            "string": pa.string(), "date32": pa.date32(),
            "timestamp": pa.timestamp("us"),
        }
        return pa.schema([pa.field(f.name, mapping[f.dtype], f.nullable)
                          for f in self.fields])
