"""Logical plan nodes for the relational IR.

The reference matches/rewrites Catalyst trees
(`Project(Filter(LogicalRelation))`, `Join(l, r, cond)`); this framework owns
an equivalent minimal node set: Scan (= LogicalRelation over lake files),
Filter, Project, Join. Nodes are immutable, JSON-serializable (see
`plan/serde.py`), and carry enough metadata (root paths, bucket spec) for the
rewrite rules to swap base-table scans for index scans exactly as the
reference's rules do (`index/rules/FilterIndexRule.scala:109-131`,
`index/rules/JoinIndexRule.scala:124-153`).
"""

from __future__ import annotations

import glob
import os
from functools import cached_property
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.expr import Expression
from hyperspace_tpu.plan.schema import Schema


@dataclass(frozen=True)
class BucketSpec:
    """Bucketing metadata: the key enabler of shuffle-free joins.

    Parity: Spark `BucketSpec(numBuckets, bucketedBy, sortedBy)` as used at
    reference `index/DataFrameWriterExtensions.scala:49-66` (write side) and
    `index/rules/JoinIndexRule.scala:124-153` (read side).
    """

    num_buckets: int
    bucket_columns: tuple
    sort_columns: tuple

    def to_dict(self) -> dict:
        return {"numBuckets": self.num_buckets,
                "bucketColumns": list(self.bucket_columns),
                "sortColumns": list(self.sort_columns)}

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["BucketSpec"]:
        if d is None:
            return None
        return BucketSpec(int(d["numBuckets"]), tuple(d["bucketColumns"]),
                          tuple(d["sortColumns"]))


class LogicalPlan:
    """Base plan node."""

    @property
    def children(self) -> List["LogicalPlan"]:
        return []

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    def with_children(self, children: List["LogicalPlan"]) -> "LogicalPlan":
        raise NotImplementedError

    def transform_up(self, fn: Callable[["LogicalPlan"], "LogicalPlan"]) -> "LogicalPlan":
        new_children = [c.transform_up(fn) for c in self.children]
        node = self if new_children == self.children else self.with_children(new_children)
        return fn(node)

    def transform_down(self, fn: Callable[["LogicalPlan"], "LogicalPlan"]) -> "LogicalPlan":
        node = fn(self)
        new_children = [c.transform_down(fn) for c in node.children]
        return node if new_children == node.children else node.with_children(new_children)

    def collect_leaves(self) -> List["LogicalPlan"]:
        if not self.children:
            return [self]
        out: List[LogicalPlan] = []
        for c in self.children:
            out.extend(c.collect_leaves())
        return out

    def is_linear(self) -> bool:
        """True iff every node has at most one child — the join rule's guard
        against signature collisions (reference `JoinIndexRule.scala:210-211`)."""
        if len(self.children) > 1:
            return False
        return all(c.is_linear() for c in self.children)

    def to_dict(self) -> dict:
        raise NotImplementedError

    def simple_string(self) -> str:
        raise NotImplementedError

    def tree_string(self, depth: int = 0) -> str:
        lines = [("  " * depth) + ("+- " if depth else "") + self.simple_string()]
        for c in self.children:
            lines.append(c.tree_string(depth + 1))
        return "\n".join(lines)

    def __eq__(self, other) -> bool:
        if type(self) is not type(other):
            return NotImplemented
        return self.to_dict() == other.to_dict()

    def __hash__(self):
        return hash(self.simple_string())


class Scan(LogicalPlan):
    """Leaf relation over lake files (= reference `LogicalRelation` over
    `HadoopFsRelation`). Carries root paths, schema, format, and an optional
    bucket spec; `files()` resolves the concrete file listing (= the
    reference's `location.allFiles`, `actions/CreateActionBase.scala:89-97`).
    """

    def __init__(self, root_paths: Sequence[str], schema: Schema,
                 file_format: str = "parquet",
                 bucket_spec: Optional[BucketSpec] = None,
                 files: Optional[Sequence[str]] = None,
                 index_name: Optional[str] = None,
                 pinned_version: Optional[int] = None):
        from hyperspace_tpu.utils.storage import canonical
        self.root_paths = [canonical(p) for p in root_paths]
        self._schema = schema
        self.file_format = file_format
        self.bucket_spec = bucket_spec
        # Snapshot pin (set by `Rule.index_scan`): the committed `v__=N`
        # this plan resolved AT PLAN TIME. A pinned scan's file listing
        # is resolved once when the pin is taken and never re-listed at
        # execution, so a maintenance writer racing the query between
        # plan and scan can neither add files to nor swap the version
        # this plan reads (the segment cache keys on the same version).
        # In-process only, like index_name: excluded from to_dict().
        self.pinned_version = pinned_version
        # Set iff a rewrite rule swapped this scan in over INDEX data
        # (`Rule.index_scan`): the execution-time marker the graceful-
        # degradation path keys on — an index scan whose data is missing
        # or unreadable raises IndexDataUnavailableError instead of
        # silently serving empty, and the query falls back to the source
        # plan. In-process only: deliberately excluded from to_dict()
        # (identity/serde), since a serialized plan never carries rule
        # rewrites.
        self.index_name = index_name
        # An EXPLICIT file list (hybrid scan / incremental deltas) restricts
        # the scan and is part of its identity; a lazily-cached glob is not.
        self._explicit_files = files is not None
        self._files = list(files) if files is not None else None

    @property
    def schema(self) -> Schema:
        return self._schema

    def with_children(self, children):
        if children:
            raise HyperspaceException("Scan is a leaf node.")
        return self

    def files(self) -> List[str]:
        """Enumerate data files under the root paths (cached per node)."""
        if self._files is None:
            from hyperspace_tpu.utils import storage
            found: List[str] = []
            for root in self.root_paths:
                if storage.is_url(root):
                    fs, real = storage.get_fs(root)
                    proto = storage.protocol_of(root)
                    if fs.isfile(real):
                        found.append(root)
                    else:
                        found.extend(
                            proto + p for p in fs.find(real)
                            if p.endswith("." + self.file_format))
                    continue
                if os.path.isfile(root):
                    found.append(root)
                else:
                    pattern = os.path.join(root, "**", f"*.{self.file_format}")
                    found.extend(glob.glob(pattern, recursive=True))
            self._files = sorted(found)
        return self._files

    def to_dict(self) -> dict:
        d = {"node": "scan", "rootPaths": list(self.root_paths),
             "format": self.file_format,
             "schema": [f.to_dict() for f in self._schema.fields],
             "bucketSpec": self.bucket_spec.to_dict() if self.bucket_spec else None}
        if self._explicit_files:
            d["files"] = list(self._files)
        return d

    def simple_string(self) -> str:
        bucket = f", buckets={self.bucket_spec.num_buckets}" if self.bucket_spec else ""
        restrict = (f", files={len(self._files)}" if self._explicit_files else "")
        return (f"Scan {self.file_format} [{', '.join(self._schema.names)}] "
                f"roots={self.root_paths}{bucket}{restrict}")


class Filter(LogicalPlan):
    def __init__(self, condition: Expression, child: LogicalPlan):
        self.condition = condition
        self.child = child

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.child]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def with_children(self, children):
        (child,) = children
        return Filter(self.condition, child)

    def to_dict(self) -> dict:
        return {"node": "filter", "condition": self.condition.to_dict(),
                "child": self.child.to_dict()}

    def simple_string(self) -> str:
        return f"Filter ({self.condition!r})"


class Project(LogicalPlan):
    """Projection. Entries are plain column names (pass-through) or
    `Alias(expr, name)` computed columns — the reference rides Catalyst's
    `Project(projectList: Seq[NamedExpression], ...)`; this engine
    evaluates computed entries with the same XLA-fused compiler filters
    use (`engine/compiler.py`)."""

    def __init__(self, columns: Sequence, child: LogicalPlan):
        from hyperspace_tpu.plan.expr import Alias, Expression
        entries = []
        for c in columns:
            if isinstance(c, str) or isinstance(c, Alias):
                entries.append(c)
            elif isinstance(c, Expression):
                raise HyperspaceException(
                    f"Projection expression needs a name: use "
                    f".alias(...) on {c!r}.")
            else:
                raise HyperspaceException(f"Bad projection entry: {c!r}")
        self.columns = entries
        self.child = child

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.child]

    def output_names(self) -> List[str]:
        return [c if isinstance(c, str) else c.name for c in self.columns]

    def references(self) -> set:
        """Source column names this projection reads (plain entries
        reference themselves)."""
        out: set = set()
        for c in self.columns:
            if isinstance(c, str):
                out.add(c)
            else:
                out |= c.references()
        return out

    def is_simple(self) -> bool:
        """True when every entry is a plain column name (the shape the
        rewrite rules and bucketed chains reason about)."""
        return all(isinstance(c, str) for c in self.columns)

    @property
    def schema(self) -> Schema:
        memo = self.__dict__.get("_schema_memo")
        if memo is None:
            from hyperspace_tpu.plan.expr import infer_dtype
            from hyperspace_tpu.plan.schema import Field
            fields = []
            for c in self.columns:
                if isinstance(c, str):
                    fields.append(self.child.schema.field(c))
                else:
                    fields.append(Field(c.name,
                                        infer_dtype(c.child,
                                                    self.child.schema),
                                        True))
            memo = self.__dict__["_schema_memo"] = Schema(fields)
        return memo

    def with_children(self, children):
        (child,) = children
        return Project(self.columns, child)

    def to_dict(self) -> dict:
        return {"node": "project",
                "columns": [c if isinstance(c, str) else c.to_dict()
                            for c in self.columns],
                "child": self.child.to_dict()}

    def simple_string(self) -> str:
        parts = [c if isinstance(c, str) else repr(c) for c in self.columns]
        return f"Project [{', '.join(parts)}]"


_AGG_FUNCS = ("sum", "count", "min", "max", "avg", "stddev",
              "count_distinct")


@dataclass(frozen=True)
class AggSpec:
    """One aggregation: func over an input (a column name, "*" for
    count(*), or a value Expression — e.g. sum(x * y))."""

    func: str
    column: object  # str | Expression
    alias: str

    def __post_init__(self):
        # Window reuses this spec shape with its own function set;
        # Aggregate and Window each validate against theirs.
        if self.func not in _AGG_FUNCS + ("rank", "dense_rank",
                                          "row_number"):
            raise HyperspaceException(f"Unsupported aggregate: {self.func}")

    @property
    def is_expression(self) -> bool:
        from hyperspace_tpu.plan.expr import Expression
        return isinstance(self.column, Expression)

    def references(self) -> set:
        if self.is_expression:
            return self.column.references()
        return set() if self.column == "*" else {self.column}

    def input_dtype(self, child_schema) -> str:
        from hyperspace_tpu.plan.expr import infer_dtype
        if self.is_expression:
            return infer_dtype(self.column, child_schema)
        return child_schema.field(self.column).dtype

    def to_dict(self) -> dict:
        column = (self.column.to_dict() if self.is_expression
                  else self.column)
        return {"func": self.func, "column": column, "alias": self.alias}

    @staticmethod
    def from_dict(d: dict) -> "AggSpec":
        from hyperspace_tpu.plan.expr import Expression
        column = d["column"]
        if isinstance(column, dict):
            column = Expression.from_dict(column)
        return AggSpec(d["func"], column, d["alias"])


class Aggregate(LogicalPlan):
    """Group-by aggregation (sum/count/min/max/avg). The reference delegates
    aggregation to Spark SQL; this framework's engine executes it as
    device segment reductions over sorted groups."""

    def __init__(self, group_columns: Sequence[str],
                 aggregates: Sequence[AggSpec], child: LogicalPlan):
        self.group_columns = list(group_columns)
        self.aggregates = list(aggregates)
        if not self.aggregates and not self.group_columns:
            raise HyperspaceException(
                "Aggregate requires group columns or at least one "
                "aggregation expression.")
        for spec in self.aggregates:
            if spec.func not in _AGG_FUNCS:
                raise HyperspaceException(
                    f"Unsupported aggregate: {spec.func}")
        # Group columns with no aggregates = DISTINCT over those columns.
        self.child = child

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.child]

    @cached_property
    def schema(self) -> Schema:
        from hyperspace_tpu.plan.schema import Field
        fields = [self.child.schema.field(c) for c in self.group_columns]
        for spec in self.aggregates:
            if spec.func in ("count", "count_distinct"):
                dtype = "int64"
            elif spec.func in ("avg", "stddev"):
                dtype = "float64"
            elif spec.func == "sum":
                src = spec.input_dtype(self.child.schema)
                dtype = ("float64" if src in ("float32", "float64")
                         else "int64")
            else:  # min/max keep the input type
                dtype = spec.input_dtype(self.child.schema)
            fields.append(Field(spec.alias, dtype, True))
        return Schema(fields)

    def with_children(self, children):
        (child,) = children
        return Aggregate(self.group_columns, self.aggregates, child)

    def to_dict(self) -> dict:
        return {"node": "aggregate", "groupBy": list(self.group_columns),
                "aggregates": [a.to_dict() for a in self.aggregates],
                "child": self.child.to_dict()}

    def simple_string(self) -> str:
        aggs = ", ".join(f"{a.func}({a.column}) AS {a.alias}"
                         for a in self.aggregates)
        return f"Aggregate [{', '.join(self.group_columns)}] [{aggs}]"


_WINDOW_FUNCS = ("rank", "dense_rank", "row_number", "sum", "avg", "min",
                 "max", "count")


class Window(LogicalPlan):
    """Window functions: appends one column per spec to the child's rows
    (input row order preserved). `partition_by` are plain column names;
    `order_by` uses Sort's spec syntax ("name" asc / "-name" desc) and is
    required by the rank family. The reference delegates windows to Spark
    SQL; this engine executes them as sorted-segment computations
    (`ops/window.py`)."""

    def __init__(self, partition_by: Sequence[str], order_by: Sequence[str],
                 specs: Sequence[AggSpec], child: LogicalPlan):
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        self.specs = list(specs)
        self.child = child
        if not self.specs:
            raise HyperspaceException("Window requires at least one spec.")
        for spec in self.specs:
            if spec.func not in _WINDOW_FUNCS:
                raise HyperspaceException(
                    f"Unsupported window function: {spec.func}")
            if spec.func in ("rank", "dense_rank") and not self.order_by:
                raise HyperspaceException(
                    f"{spec.func} requires an ORDER BY.")
            if spec.is_expression:
                raise HyperspaceException(
                    "Window inputs must be plain columns; project the "
                    "expression first.")
            if (spec.column == "*"
                    and spec.func not in ("rank", "dense_rank",
                                          "row_number", "count")):
                raise HyperspaceException(
                    f"Window {spec.func} requires a column input.")
            if child.schema.contains(spec.alias):
                raise HyperspaceException(
                    f"Window output name collides with an input column: "
                    f"{spec.alias}")

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.child]

    @cached_property
    def schema(self) -> Schema:
        from hyperspace_tpu.plan.schema import Field
        fields = list(self.child.schema.fields)
        for spec in self.specs:
            if spec.func in ("rank", "dense_rank", "row_number", "count"):
                dtype = "int64"
            elif spec.func == "avg":
                dtype = "float64"
            elif spec.func == "sum":
                src = spec.input_dtype(self.child.schema)
                dtype = ("float64" if src in ("float32", "float64")
                         else "int64")
            else:  # min/max keep the input type
                dtype = spec.input_dtype(self.child.schema)
            fields.append(Field(spec.alias, dtype, True))
        return Schema(fields)

    def with_children(self, children):
        (child,) = children
        return Window(self.partition_by, self.order_by, self.specs, child)

    def to_dict(self) -> dict:
        return {"node": "window", "partitionBy": list(self.partition_by),
                "orderBy": list(self.order_by),
                "specs": [s.to_dict() for s in self.specs],
                "child": self.child.to_dict()}

    def simple_string(self) -> str:
        parts = [f"{s.func}({s.column}) AS {s.alias}" for s in self.specs]
        order = f" ORDER BY {', '.join(self.order_by)}" if self.order_by \
            else ""
        return (f"Window [{', '.join(parts)}] PARTITION BY "
                f"[{', '.join(self.partition_by)}]{order}")


def sort_direction(column: str):
    """Parse a sort spec: "name" -> (name, False); "-name" -> (name, True)
    (descending). Descending follows Spark's default null placement:
    ascending is nulls-first, descending is nulls-last."""
    if column.startswith("-"):
        return column[1:], True
    return column, False


class Sort(LogicalPlan):
    """ORDER BY. Plain column names sort ascending (nulls first); a
    leading "-" sorts that column descending (nulls last)."""

    def __init__(self, columns: Sequence[str], child: LogicalPlan):
        self.columns = list(columns)
        self.child = child
        for spec in self.columns:
            name, desc = sort_direction(spec)
            if desc and child.schema.contains(spec):
                # A column literally named "-x" would silently alias
                # column "x" descending; fail loudly instead.
                raise HyperspaceException(
                    f"Ambiguous sort spec {spec!r}: a column with that "
                    "literal name exists; rename it to sort by it.")

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.child]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def with_children(self, children):
        (child,) = children
        return Sort(self.columns, child)

    def to_dict(self) -> dict:
        return {"node": "sort", "columns": list(self.columns),
                "child": self.child.to_dict()}

    def simple_string(self) -> str:
        parts = [f"{name} DESC" if desc else name
                 for name, desc in map(sort_direction, self.columns)]
        return f"Sort [{', '.join(parts)}]"


class Limit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        if n < 0:
            raise HyperspaceException("Limit must be non-negative.")
        self.n = n
        self.child = child

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.child]

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def with_children(self, children):
        (child,) = children
        return Limit(self.n, child)

    def to_dict(self) -> dict:
        return {"node": "limit", "n": self.n, "child": self.child.to_dict()}

    def simple_string(self) -> str:
        return f"Limit {self.n}"


class Union(LogicalPlan):
    """Row-wise union of same-schema children (column names must align).
    Exists for Hybrid Scan: index data UNION appended source files."""

    def __init__(self, children: Sequence[LogicalPlan]):
        if not children:
            raise HyperspaceException("Union requires at least one child.")
        self._children = list(children)
        names0 = [n.lower() for n in self._children[0].schema.names]
        for c in self._children[1:]:
            if [n.lower() for n in c.schema.names] != names0:
                raise HyperspaceException(
                    "Union children must share column names/order.")

    @property
    def children(self) -> List[LogicalPlan]:
        return list(self._children)

    @property
    def schema(self) -> Schema:
        return self._children[0].schema

    def with_children(self, children):
        return Union(children)

    def to_dict(self) -> dict:
        return {"node": "union",
                "children": [c.to_dict() for c in self._children]}

    def simple_string(self) -> str:
        return f"Union ({len(self._children)} children)"


class SetOp(LogicalPlan):
    """SQL set operation with DISTINCT semantics (INTERSECT / EXCEPT):
    output = DISTINCT rows of `left` present in (Intersect) / absent from
    (Except) `right`. Row equality treats NULL as equal to NULL — SQL set
    operations, UNLIKE joins, group nulls together. The reference's serde
    zoo exists to make exactly these queries serializable
    (`index/serde/package.scala:64-167`, IntersectWrapper/ExceptWrapper);
    this IR carries them natively (TPC-DS q8/q14/q38/q87)."""

    kind: str = ""

    def __init__(self, left: LogicalPlan, right: LogicalPlan):
        ln = [n.lower() for n in left.schema.names]
        rn = [n.lower() for n in right.schema.names]
        if ln != rn:
            raise HyperspaceException(
                f"{type(self).__name__} sides must share column "
                f"names/order; got {ln} vs {rn}.")
        self.left = left
        self.right = right

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.left, self.right]

    @property
    def schema(self) -> Schema:
        return self.left.schema

    def with_children(self, children):
        left, right = children
        return type(self)(left, right)

    def to_dict(self) -> dict:
        return {"node": self.kind, "left": self.left.to_dict(),
                "right": self.right.to_dict()}

    def simple_string(self) -> str:
        return type(self).__name__


class Intersect(SetOp):
    kind = "intersect"


class Except(SetOp):
    kind = "except"


_JOIN_TYPES = ("inner", "left_outer", "right_outer", "full_outer",
               "left_semi", "left_anti", "cross")


class Join(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 condition: Optional[Expression], join_type: str = "inner"):
        if join_type not in _JOIN_TYPES:
            raise HyperspaceException(f"Unsupported join type: {join_type}")
        if (condition is None) != (join_type == "cross"):
            raise HyperspaceException(
                "cross joins take no condition; every other join type "
                "requires one.")
        self.left = left
        self.right = right
        self.condition = condition
        self.join_type = join_type

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.left, self.right]

    @cached_property
    def schema(self) -> Schema:
        """Left fields then right fields; duplicate names get a `_r` suffix
        on the right (matching the executor's output); outer joins make the
        nullable side's fields nullable; semi/anti joins output the left
        side only. Memoized — nodes are immutable, and deep query trees
        re-ask for ancestor schemas repeatedly."""
        from hyperspace_tpu.plan.schema import Field as SchemaField
        if self.join_type in ("left_semi", "left_anti"):
            return self.left.schema
        fields = list(self.left.schema.fields)
        left_names = {f.name.lower() for f in fields}
        if self.join_type in ("right_outer", "full_outer"):
            fields = [SchemaField(f.name, f.dtype, True) for f in fields]
        right_nullable = self.join_type in ("left_outer", "full_outer")
        for f in self.right.schema.fields:
            name = (f.name if f.name.lower() not in left_names
                    else f.name + "_r")
            fields.append(SchemaField(name, f.dtype,
                                      f.nullable or right_nullable))
        return Schema(fields)

    def with_children(self, children):
        left, right = children
        return Join(left, right, self.condition, self.join_type)

    def to_dict(self) -> dict:
        return {"node": "join", "type": self.join_type,
                "condition": (self.condition.to_dict()
                              if self.condition is not None else None),
                "left": self.left.to_dict(), "right": self.right.to_dict()}

    def simple_string(self) -> str:
        if self.condition is None:
            return f"Join {self.join_type}"
        return f"Join {self.join_type} ({self.condition!r})"
