"""Expression trees for the relational IR.

The reference leans on Catalyst expressions; this framework owns a small
expression language sufficient for the covering-index workloads (filters and
equi-join conditions over scalar columns): column refs, literals,
comparisons, boolean algebra, arithmetic, IN, NULL tests. Expressions are
JSON-serializable (replacing the reference's Kryo serde of Catalyst trees,
`index/serde/LogicalPlanSerDeUtils.scala:40-67`) and are compiled to jax
ops by the engine (`engine/compiler.py`).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Set

from hyperspace_tpu.exceptions import HyperspaceException


class Expression:
    """Base expression node."""

    @property
    def children(self) -> List["Expression"]:
        return []

    def references(self) -> Set[str]:
        out: Set[str] = set()
        for c in self.children:
            out |= c.references()
        return out

    def to_dict(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "Expression":
        op = d["op"]
        cls = _REGISTRY.get(op)
        if cls is None:
            raise HyperspaceException(f"Unknown expression op: {op}")
        return cls._from_dict(d)

    # Operator sugar so users can write `col("a") == lit(1)` style predicates.
    def __eq__(self, other):  # type: ignore[override]
        return EqualTo(self, _wrap(other))

    def __ne__(self, other):  # type: ignore[override]
        return NotEqualTo(self, _wrap(other))

    def __lt__(self, other):
        return LessThan(self, _wrap(other))

    def __le__(self, other):
        return LessThanOrEqual(self, _wrap(other))

    def __gt__(self, other):
        return GreaterThan(self, _wrap(other))

    def __ge__(self, other):
        return GreaterThanOrEqual(self, _wrap(other))

    def __and__(self, other):
        return And(self, _wrap(other))

    def __or__(self, other):
        return Or(self, _wrap(other))

    def __invert__(self):
        return Not(self)

    def __add__(self, other):
        return Add(self, _wrap(other))

    def __sub__(self, other):
        return Sub(self, _wrap(other))

    def __mul__(self, other):
        return Mul(self, _wrap(other))

    def __truediv__(self, other):
        return Div(self, _wrap(other))

    def __hash__(self):
        return hash(repr(self))

    def isin(self, *values) -> "In":
        return In(self, [(_wrap(v)) for v in values])

    def is_null(self) -> "IsNull":
        return IsNull(self)

    def is_not_null(self) -> "IsNotNull":
        return IsNotNull(self)

    def alias(self, name: str) -> "Alias":
        """Name this expression as a projection output column:
        `df.select(col("a"), (col("x") * col("y")).alias("xy"))`."""
        return Alias(self, name)

    def substr(self, start: int, length: int) -> "Substr":
        """SQL SUBSTR(col, start, length) — 1-based start, on string
        expressions."""
        return Substr(self, start, length)

    def like(self, pattern: str) -> "Like":
        """SQL LIKE: `%` any run, `_` any single char, anchored."""
        return Like(self, pattern)

    def between(self, low, high) -> "Expression":
        """SQL BETWEEN: low <= self <= high (inclusive)."""
        return And(GreaterThanOrEqual(self, _wrap(low)),
                   LessThanOrEqual(self, _wrap(high)))


def _wrap(value) -> "Expression":
    if isinstance(value, Expression):
        return value
    return Literal(value)


class Column(Expression):
    def __init__(self, name: str):
        self.name = name

    def references(self) -> Set[str]:
        return {self.name}

    def to_dict(self) -> dict:
        return {"op": "column", "name": self.name}

    @staticmethod
    def _from_dict(d: dict) -> "Column":
        return Column(d["name"])

    def __repr__(self):
        return f"col({self.name})"


class Literal(Expression):
    def __init__(self, value: Any):
        if value is not None and not isinstance(value, (bool, int, float, str)):
            raise HyperspaceException(f"Unsupported literal: {value!r}")
        self.value = value

    def to_dict(self) -> dict:
        return {"op": "literal", "value": self.value}

    @staticmethod
    def _from_dict(d: dict) -> "Literal":
        return Literal(d["value"])

    def __repr__(self):
        return f"lit({self.value!r})"


class NullLiteral(Expression):
    """A typed SQL NULL (`lit(None)` needs a dtype to carry through the
    engine's static schemas). Exists for the grouping-set/ROLLUP idiom —
    coarser granularities union in with NULL-filled grouping columns —
    and anywhere else a query projects an explicit NULL."""

    op = "null"

    def __init__(self, dtype: str):
        from hyperspace_tpu.plan.schema import Field
        Field("_", dtype)  # validates the dtype name
        self.dtype = dtype

    def to_dict(self) -> dict:
        return {"op": "null", "dtype": self.dtype}

    @staticmethod
    def _from_dict(d: dict) -> "NullLiteral":
        return NullLiteral(d["dtype"])

    def __repr__(self):
        return f"NULL::{self.dtype}"


def null(dtype: str) -> NullLiteral:
    return NullLiteral(dtype)


class _Binary(Expression):
    op: str = ""
    symbol: str = ""

    def __init__(self, left: Expression, right: Expression):
        self.left = left
        self.right = right

    @property
    def children(self) -> List[Expression]:
        return [self.left, self.right]

    def to_dict(self) -> dict:
        return {"op": self.op, "left": self.left.to_dict(),
                "right": self.right.to_dict()}

    @classmethod
    def _from_dict(cls, d: dict):
        return cls(Expression.from_dict(d["left"]), Expression.from_dict(d["right"]))

    def __repr__(self):
        return f"({self.left!r} {self.symbol} {self.right!r})"


class EqualTo(_Binary):
    op, symbol = "eq", "="


class NotEqualTo(_Binary):
    op, symbol = "ne", "!="


class LessThan(_Binary):
    op, symbol = "lt", "<"


class LessThanOrEqual(_Binary):
    op, symbol = "le", "<="


class GreaterThan(_Binary):
    op, symbol = "gt", ">"


class GreaterThanOrEqual(_Binary):
    op, symbol = "ge", ">="


class And(_Binary):
    op, symbol = "and", "AND"


class Or(_Binary):
    op, symbol = "or", "OR"


class Add(_Binary):
    op, symbol = "add", "+"


class Sub(_Binary):
    op, symbol = "sub", "-"


class Mul(_Binary):
    op, symbol = "mul", "*"


class Div(_Binary):
    op, symbol = "div", "/"


class _Unary(Expression):
    op: str = ""

    def __init__(self, child: Expression):
        self.child = child

    @property
    def children(self) -> List[Expression]:
        return [self.child]

    def to_dict(self) -> dict:
        return {"op": self.op, "child": self.child.to_dict()}

    @classmethod
    def _from_dict(cls, d: dict):
        return cls(Expression.from_dict(d["child"]))

    def __repr__(self):
        return f"{self.op}({self.child!r})"


class Not(_Unary):
    op = "not"


class IsNull(_Unary):
    op = "is_null"


class IsNotNull(_Unary):
    op = "is_not_null"


class Alias(Expression):
    """A named projection output (Spark's `Alias`). Only meaningful as a
    top-level entry of a Project/select list."""

    op = "alias"

    def __init__(self, child: Expression, name: str):
        if not isinstance(child, Expression):
            raise HyperspaceException("alias() wraps an Expression.")
        self.child = child
        self.name = name

    @property
    def children(self) -> List[Expression]:
        return [self.child]

    def to_dict(self) -> dict:
        return {"op": "alias", "name": self.name,
                "child": self.child.to_dict()}

    @staticmethod
    def _from_dict(d: dict) -> "Alias":
        return Alias(Expression.from_dict(d["child"]), d["name"])

    def __repr__(self):
        return f"({self.child!r} AS {self.name})"


class Substr(Expression):
    """SUBSTR(string expr, start, length); start is 1-based (SQL)."""

    op = "substr"

    def __init__(self, child: Expression, start: int, length: int):
        if start < 1 or length < 0:
            raise HyperspaceException(
                "SUBSTR start is 1-based and length must be >= 0.")
        self.child = child
        self.start = int(start)
        self.length = int(length)

    @property
    def children(self) -> List[Expression]:
        return [self.child]

    def to_dict(self) -> dict:
        return {"op": "substr", "start": self.start, "length": self.length,
                "child": self.child.to_dict()}

    @staticmethod
    def _from_dict(d: dict) -> "Substr":
        return Substr(Expression.from_dict(d["child"]), d["start"],
                      d["length"])

    def __repr__(self):
        return f"substr({self.child!r}, {self.start}, {self.length})"


class Like(Expression):
    """SQL LIKE over a string expression: `%` matches any run, `_` any
    single character, anchored at both ends. Compiled in DICTIONARY space
    (the pattern runs over the distinct values, O(dictionary) on the
    host; rows pay one code-membership test), so the predicate stays
    XLA-friendly at any row count."""

    op = "like"

    def __init__(self, child: Expression, pattern: str):
        self.child = child
        self.pattern = str(pattern)

    @property
    def children(self) -> List[Expression]:
        return [self.child]

    def regex(self) -> str:
        """Anchored regex equivalent of the SQL pattern. Backslash is the
        escape character (Spark's LIKE default): `\\%` / `\\_` match the
        literal wildcard, `\\\\` a literal backslash."""
        import re
        out = []
        chars = iter(self.pattern)
        for ch in chars:
            if ch == "\\":
                nxt = next(chars, None)
                if nxt is None:
                    out.append(re.escape("\\"))
                else:
                    out.append(re.escape(nxt))
            elif ch == "%":
                out.append(".*")
            elif ch == "_":
                out.append(".")
            else:
                out.append(re.escape(ch))
        return "".join(out)

    def to_dict(self) -> dict:
        return {"op": "like", "pattern": self.pattern,
                "child": self.child.to_dict()}

    @staticmethod
    def _from_dict(d: dict) -> "Like":
        return Like(Expression.from_dict(d["child"]), d["pattern"])

    def __repr__(self):
        return f"{self.child!r} LIKE {self.pattern!r}"


class In(Expression):
    def __init__(self, child: Expression, values: Sequence[Expression]):
        self.child = child
        self.values = list(values)
        for v in self.values:
            if not isinstance(v, Literal):
                raise HyperspaceException("IN list must contain literals only.")

    @property
    def children(self) -> List[Expression]:
        return [self.child, *self.values]

    def to_dict(self) -> dict:
        return {"op": "in", "child": self.child.to_dict(),
                "values": [v.to_dict() for v in self.values]}

    @staticmethod
    def _from_dict(d: dict) -> "In":
        return In(Expression.from_dict(d["child"]),
                  [Expression.from_dict(v) for v in d["values"]])

    def __repr__(self):
        return f"{self.child!r} IN {[v.value for v in self.values]}"


class CaseWhen(Expression):
    """SQL `CASE WHEN cond THEN value [WHEN ...] [ELSE value] END`.
    First matching branch wins; no match and no ELSE yields NULL (the
    conditional-aggregation idiom most TPC-DS pivots use:
    `sum(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price END)` —
    sum/avg skip the NULLs)."""

    op = "case"

    def __init__(self, branches: Sequence[tuple],
                 otherwise: Optional[Expression] = None):
        if not branches:
            raise HyperspaceException("CASE needs at least one WHEN branch.")
        self.branches = [(c, v) for c, v in branches]
        for c, v in self.branches:
            if not isinstance(c, Expression) or not isinstance(v, Expression):
                raise HyperspaceException(
                    "CASE branches must pair (condition, value) expressions.")
        self.otherwise_value = otherwise

    def when(self, condition: "Expression", value) -> "CaseWhen":
        return CaseWhen(self.branches + [(condition, _wrap(value))],
                        self.otherwise_value)

    def otherwise(self, value) -> "CaseWhen":
        return CaseWhen(self.branches, _wrap(value))

    @property
    def children(self) -> List[Expression]:
        out: List[Expression] = []
        for c, v in self.branches:
            out.extend((c, v))
        if self.otherwise_value is not None:
            out.append(self.otherwise_value)
        return out

    def to_dict(self) -> dict:
        return {"op": "case",
                "branches": [[c.to_dict(), v.to_dict()]
                             for c, v in self.branches],
                "otherwise": (self.otherwise_value.to_dict()
                              if self.otherwise_value is not None else None)}

    @staticmethod
    def _from_dict(d: dict) -> "CaseWhen":
        other = d.get("otherwise")
        return CaseWhen(
            [(Expression.from_dict(c), Expression.from_dict(v))
             for c, v in d["branches"]],
            Expression.from_dict(other) if other is not None else None)

    def __repr__(self):
        parts = " ".join(f"WHEN {c!r} THEN {v!r}" for c, v in self.branches)
        tail = (f" ELSE {self.otherwise_value!r}"
                if self.otherwise_value is not None else "")
        return f"CASE {parts}{tail} END"


def when(condition: Expression, value) -> CaseWhen:
    """Start a CASE chain: `when(cond, v).when(cond2, v2).otherwise(v3)`
    (PySpark's `F.when` shape)."""
    return CaseWhen([(condition, _wrap(value))])


class Floor(Expression):
    """FLOOR(x) -> int64 (SQL's `cast(x/50 as int)` bucketing idiom for
    non-negative quotients; true floor semantics for negatives)."""

    op = "floor"

    def __init__(self, child: Expression):
        self.child = child

    @property
    def children(self) -> List["Expression"]:
        return [self.child]

    def to_dict(self) -> dict:
        return {"op": "floor", "child": self.child.to_dict()}

    @staticmethod
    def _from_dict(d: dict) -> "Floor":
        return Floor(Expression.from_dict(d["child"]))

    def __repr__(self):
        return f"floor({self.child!r})"


class ScalarSubquery(Expression):
    """A subquery used as a scalar value inside an expression — TPC-DS's
    `where x > (select 1.3 * avg(...) ...)` idiom. The reference
    serializes Catalyst's ScalarSubquery wrappers for exactly these
    queries (`index/serde/package.scala:64-167`); here the node embeds
    the subplan's own-IR JSON.

    Resolution: `engine/executor.execute_plan` executes the subplan
    (must yield one column; one row -> its value, zero rows -> SQL NULL,
    more -> error) ONCE per plan object and caches the value on the node
    (like `Scan.files()` — per-plan-object staleness semantics). The
    rewrite rules run inside the subplan too (`session.optimize`
    recurses into embedded subqueries)."""

    op = "scalar_subquery"

    def __init__(self, plan):
        self.plan = plan
        # The optimizer's rewritten view of the subplan, refreshed on
        # every session.optimize() — `plan` itself is never mutated, so
        # an expression the user holds stays valid across
        # enable/disable_hyperspace.
        self._opt_plan = None
        self._value = None
        self._resolved = False
        if len(plan.schema.fields) != 1:
            raise HyperspaceException(
                "Scalar subquery must produce exactly one column; got "
                f"{plan.schema.names}.")

    def execution_plan(self):
        return self._opt_plan if self._opt_plan is not None else self.plan

    @property
    def dtype(self) -> str:
        return self.plan.schema.fields[0].dtype

    def references(self) -> Set[str]:
        # No correlated references: the subplan reads its own sources.
        return set()

    def resolve(self, value) -> None:
        self._value = value
        self._resolved = True

    def literal(self) -> "Expression":
        """The resolved value as a Literal (NullLiteral for SQL NULL /
        empty subquery). Compilation reads ONLY this."""
        if not self._resolved:
            raise HyperspaceException(
                "Scalar subquery was not resolved before compilation.")
        if self._value is None:
            return NullLiteral(self.dtype)
        return Literal(self._value)

    def to_dict(self) -> dict:
        d = {"op": "scalar_subquery", "plan": self.plan.to_dict()}
        if self._resolved:
            # The resolved value participates in plan identity (fusion
            # executable keys bake it in as a constant); serde ignores it
            # on load (fresh plans re-resolve).
            d["value"] = self._value
        return d

    @staticmethod
    def _from_dict(d: dict) -> "ScalarSubquery":
        from hyperspace_tpu.plan.serde import plan_from_dict
        return ScalarSubquery(plan_from_dict(d["plan"]))

    def __repr__(self):
        return f"scalar_subquery({self.plan.simple_string()})"


_REGISTRY: Dict[str, Any] = {
    "column": Column, "literal": Literal,
    "eq": EqualTo, "ne": NotEqualTo, "lt": LessThan, "le": LessThanOrEqual,
    "gt": GreaterThan, "ge": GreaterThanOrEqual,
    "and": And, "or": Or, "not": Not,
    "add": Add, "sub": Sub, "mul": Mul, "div": Div,
    "is_null": IsNull, "is_not_null": IsNotNull, "in": In,
    "alias": Alias, "substr": Substr, "case": CaseWhen,
    "null": NullLiteral, "like": Like, "scalar_subquery": ScalarSubquery,
    "floor": Floor,
}


_BOOL_OPS = (EqualTo, NotEqualTo, LessThan, LessThanOrEqual, GreaterThan,
             GreaterThanOrEqual, And, Or, Not, IsNull, IsNotNull, In, Like)


def infer_dtype(expr: Expression, schema) -> str:
    """Logical output dtype of a value expression against a child schema
    (the typing rules the engine's compiler implements: ints accumulate as
    int64, any float operand promotes to float64, Div always yields
    float64)."""
    if isinstance(expr, Alias):
        return infer_dtype(expr.child, schema)
    if isinstance(expr, Column):
        return schema.field(expr.name).dtype
    if isinstance(expr, NullLiteral):
        return expr.dtype
    if isinstance(expr, Literal):
        v = expr.value
        if isinstance(v, bool):
            return "bool"
        if isinstance(v, int):
            return "int64"
        if isinstance(v, float):
            return "float64"
        if isinstance(v, str):
            return "string"
        raise HyperspaceException(f"Untyped literal: {v!r}")
    if isinstance(expr, Substr):
        if infer_dtype(expr.child, schema) != "string":
            raise HyperspaceException("SUBSTR requires a string operand.")
        return "string"
    if isinstance(expr, Div):
        return "float64"
    if isinstance(expr, (Add, Sub, Mul)):
        l = infer_dtype(expr.left, schema)
        r = infer_dtype(expr.right, schema)
        if "string" in (l, r):
            raise HyperspaceException(
                f"Arithmetic over string operands: {expr!r}")
        floats = {"float32", "float64"}
        if l in floats or r in floats:
            return "float64"
        return "int64"
    if isinstance(expr, CaseWhen):
        outs = [infer_dtype(v, schema) for _, v in expr.branches]
        if expr.otherwise_value is not None:
            outs.append(infer_dtype(expr.otherwise_value, schema))
        if all(o == "string" for o in outs):
            return "string"
        if "string" in outs:
            raise HyperspaceException(
                f"CASE branches mix string and numeric values: {expr!r}")
        if all(o == "bool" for o in outs):
            return "bool"
        floats = {"float32", "float64"}
        return "float64" if any(o in floats for o in outs) else "int64"
    if isinstance(expr, ScalarSubquery):
        return expr.dtype
    if isinstance(expr, Floor):
        if infer_dtype(expr.child, schema) == "string":
            raise HyperspaceException("FLOOR over a string operand.")
        return "int64"
    if isinstance(expr, _BOOL_OPS):
        return "bool"
    raise HyperspaceException(f"Cannot infer dtype of: {expr!r}")


def col(name: str) -> Column:
    return Column(name)


def lit(value) -> Literal:
    return Literal(value)


def split_conjunctive(expr: Expression) -> List[Expression]:
    """Flatten an AND tree into its conjuncts (used by the join rule's
    equi-CNF check, reference `index/rules/JoinIndexRule.scala:179-185`)."""
    if isinstance(expr, And):
        return split_conjunctive(expr.left) + split_conjunctive(expr.right)
    return [expr]
