"""Expression trees for the relational IR.

The reference leans on Catalyst expressions; this framework owns a small
expression language sufficient for the covering-index workloads (filters and
equi-join conditions over scalar columns): column refs, literals,
comparisons, boolean algebra, arithmetic, IN, NULL tests. Expressions are
JSON-serializable (replacing the reference's Kryo serde of Catalyst trees,
`index/serde/LogicalPlanSerDeUtils.scala:40-67`) and are compiled to jax
ops by the engine (`engine/compiler.py`).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Set

from hyperspace_tpu.exceptions import HyperspaceException


class Expression:
    """Base expression node."""

    @property
    def children(self) -> List["Expression"]:
        return []

    def references(self) -> Set[str]:
        out: Set[str] = set()
        for c in self.children:
            out |= c.references()
        return out

    def to_dict(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "Expression":
        op = d["op"]
        cls = _REGISTRY.get(op)
        if cls is None:
            raise HyperspaceException(f"Unknown expression op: {op}")
        return cls._from_dict(d)

    # Operator sugar so users can write `col("a") == lit(1)` style predicates.
    def __eq__(self, other):  # type: ignore[override]
        return EqualTo(self, _wrap(other))

    def __ne__(self, other):  # type: ignore[override]
        return NotEqualTo(self, _wrap(other))

    def __lt__(self, other):
        return LessThan(self, _wrap(other))

    def __le__(self, other):
        return LessThanOrEqual(self, _wrap(other))

    def __gt__(self, other):
        return GreaterThan(self, _wrap(other))

    def __ge__(self, other):
        return GreaterThanOrEqual(self, _wrap(other))

    def __and__(self, other):
        return And(self, _wrap(other))

    def __or__(self, other):
        return Or(self, _wrap(other))

    def __invert__(self):
        return Not(self)

    def __add__(self, other):
        return Add(self, _wrap(other))

    def __sub__(self, other):
        return Sub(self, _wrap(other))

    def __mul__(self, other):
        return Mul(self, _wrap(other))

    def __truediv__(self, other):
        return Div(self, _wrap(other))

    def __hash__(self):
        return hash(repr(self))

    def isin(self, *values) -> "In":
        return In(self, [(_wrap(v)) for v in values])

    def is_null(self) -> "IsNull":
        return IsNull(self)

    def is_not_null(self) -> "IsNotNull":
        return IsNotNull(self)


def _wrap(value) -> "Expression":
    if isinstance(value, Expression):
        return value
    return Literal(value)


class Column(Expression):
    def __init__(self, name: str):
        self.name = name

    def references(self) -> Set[str]:
        return {self.name}

    def to_dict(self) -> dict:
        return {"op": "column", "name": self.name}

    @staticmethod
    def _from_dict(d: dict) -> "Column":
        return Column(d["name"])

    def __repr__(self):
        return f"col({self.name})"


class Literal(Expression):
    def __init__(self, value: Any):
        if value is not None and not isinstance(value, (bool, int, float, str)):
            raise HyperspaceException(f"Unsupported literal: {value!r}")
        self.value = value

    def to_dict(self) -> dict:
        return {"op": "literal", "value": self.value}

    @staticmethod
    def _from_dict(d: dict) -> "Literal":
        return Literal(d["value"])

    def __repr__(self):
        return f"lit({self.value!r})"


class _Binary(Expression):
    op: str = ""
    symbol: str = ""

    def __init__(self, left: Expression, right: Expression):
        self.left = left
        self.right = right

    @property
    def children(self) -> List[Expression]:
        return [self.left, self.right]

    def to_dict(self) -> dict:
        return {"op": self.op, "left": self.left.to_dict(),
                "right": self.right.to_dict()}

    @classmethod
    def _from_dict(cls, d: dict):
        return cls(Expression.from_dict(d["left"]), Expression.from_dict(d["right"]))

    def __repr__(self):
        return f"({self.left!r} {self.symbol} {self.right!r})"


class EqualTo(_Binary):
    op, symbol = "eq", "="


class NotEqualTo(_Binary):
    op, symbol = "ne", "!="


class LessThan(_Binary):
    op, symbol = "lt", "<"


class LessThanOrEqual(_Binary):
    op, symbol = "le", "<="


class GreaterThan(_Binary):
    op, symbol = "gt", ">"


class GreaterThanOrEqual(_Binary):
    op, symbol = "ge", ">="


class And(_Binary):
    op, symbol = "and", "AND"


class Or(_Binary):
    op, symbol = "or", "OR"


class Add(_Binary):
    op, symbol = "add", "+"


class Sub(_Binary):
    op, symbol = "sub", "-"


class Mul(_Binary):
    op, symbol = "mul", "*"


class Div(_Binary):
    op, symbol = "div", "/"


class _Unary(Expression):
    op: str = ""

    def __init__(self, child: Expression):
        self.child = child

    @property
    def children(self) -> List[Expression]:
        return [self.child]

    def to_dict(self) -> dict:
        return {"op": self.op, "child": self.child.to_dict()}

    @classmethod
    def _from_dict(cls, d: dict):
        return cls(Expression.from_dict(d["child"]))

    def __repr__(self):
        return f"{self.op}({self.child!r})"


class Not(_Unary):
    op = "not"


class IsNull(_Unary):
    op = "is_null"


class IsNotNull(_Unary):
    op = "is_not_null"


class In(Expression):
    def __init__(self, child: Expression, values: Sequence[Expression]):
        self.child = child
        self.values = list(values)
        for v in self.values:
            if not isinstance(v, Literal):
                raise HyperspaceException("IN list must contain literals only.")

    @property
    def children(self) -> List[Expression]:
        return [self.child, *self.values]

    def to_dict(self) -> dict:
        return {"op": "in", "child": self.child.to_dict(),
                "values": [v.to_dict() for v in self.values]}

    @staticmethod
    def _from_dict(d: dict) -> "In":
        return In(Expression.from_dict(d["child"]),
                  [Expression.from_dict(v) for v in d["values"]])

    def __repr__(self):
        return f"{self.child!r} IN {[v.value for v in self.values]}"


_REGISTRY: Dict[str, Any] = {
    "column": Column, "literal": Literal,
    "eq": EqualTo, "ne": NotEqualTo, "lt": LessThan, "le": LessThanOrEqual,
    "gt": GreaterThan, "ge": GreaterThanOrEqual,
    "and": And, "or": Or, "not": Not,
    "add": Add, "sub": Sub, "mul": Mul, "div": Div,
    "is_null": IsNull, "is_not_null": IsNotNull, "in": In,
}


def col(name: str) -> Column:
    return Column(name)


def lit(value) -> Literal:
    return Literal(value)


def split_conjunctive(expr: Expression) -> List[Expression]:
    """Flatten an AND tree into its conjuncts (used by the join rule's
    equi-CNF check, reference `index/rules/JoinIndexRule.scala:179-185`)."""
    if isinstance(expr, And):
        return split_conjunctive(expr.left) + split_conjunctive(expr.right)
    return [expr]
