"""Workload miner: distill recurring index opportunities from the
flight ring.

The ring holds finished `QueryMetrics` — and since PR 11 each carries
its SOURCE logical plan and a monotonic `flight_seq`. The miner polls
incrementally (`FlightRecorder.snapshot(since_seq)`) and reads three
signal families out of each new query:

- the rewrite rules' structured whyNot events: `FilterIndexRule
  skipped` carries the scan roots, predicate columns (and which of them
  are point equalities — bucket pruning only helps those) and the
  projected column set; `JoinIndexRule skipped ("no usable/compatible
  index pair")` carries per-side roots, join keys, and referenced
  columns. A query that a rule already SERVED contributes no miss — an
  existing index is doing its job.
- per-scan telemetry: `bytes_scanned` / `files_scanned` on the Scan
  operator records, attributed to their roots — the cost the candidate
  would amortize.
- repeat counts: misses aggregate into `WorkloadSignature`s keyed by
  (kind, relation root(s), filter/join columns, projected columns); a
  signature below `spark.hyperspace.advisor.min.repeats` observations
  is noise, not workload.

Everything here is read-only over already-recorded data: no IO, no
plan execution, no lock held beyond the ring's snapshot copy.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["WorkloadMiner", "WorkloadSignature"]


class WorkloadSignature:
    """One recurring workload shape the advisor can act on.

    kind="filter": `roots` is the scanned relation, `filter_columns` /
    `eq_columns` / `project_columns` describe the recurring predicate
    shape. kind="join": `roots`/`join_columns`/`referenced_columns` and
    the `right_*` twins describe the two sides. `plan` is the most
    recently recorded source logical plan exhibiting the shape — the
    what-if scorer's replay input."""

    __slots__ = ("kind", "key", "roots", "right_roots", "filter_columns",
                 "eq_columns", "project_columns", "join_columns",
                 "right_join_columns", "referenced_columns",
                 "right_referenced_columns", "count", "total_scan_bytes",
                 "last_seq", "plan", "tenant")

    def __init__(self, kind: str, key: tuple):
        self.kind = kind
        self.key = key
        # The tenant whose queries exhibit the shape: signatures are
        # KEYED by tenant, so two tenants' identical misses stay
        # separate candidates — the executor budgets each against its
        # own `advisor.tenant.<id>.budget.bytes`.
        self.tenant: str = "default"
        self.roots: Tuple[str, ...] = ()
        self.right_roots: Tuple[str, ...] = ()
        self.filter_columns: Tuple[str, ...] = ()
        self.eq_columns: Tuple[str, ...] = ()
        self.project_columns: Tuple[str, ...] = ()
        self.join_columns: Tuple[str, ...] = ()
        self.right_join_columns: Tuple[str, ...] = ()
        self.referenced_columns: Tuple[str, ...] = ()
        self.right_referenced_columns: Tuple[str, ...] = ()
        self.count = 0
        self.total_scan_bytes = 0
        self.last_seq = 0
        self.plan = None

    @property
    def mean_scan_bytes(self) -> int:
        return self.total_scan_bytes // self.count if self.count else 0

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "roots": list(self.roots),
            "right_roots": list(self.right_roots) or None,
            "filter_columns": list(self.filter_columns) or None,
            "eq_columns": list(self.eq_columns) or None,
            "project_columns": list(self.project_columns) or None,
            "join_columns": list(self.join_columns) or None,
            "count": self.count,
            "total_scan_bytes": self.total_scan_bytes,
            "last_seq": self.last_seq,
            "tenant": self.tenant,
        }


def _scan_bytes_by_root(metrics) -> Dict[str, int]:
    """{root: summed bytes_scanned} over the query's Scan operator
    records (first root wins attribution for multi-root scans — good
    enough for amortization)."""
    out: Dict[str, int] = {}
    for op in getattr(metrics, "operators", ()):
        if op.name != "Scan":
            continue
        roots = op.detail.get("roots") or ()
        nbytes = op.detail.get("bytes_scanned")
        if not roots or not isinstance(nbytes, (int, float)):
            continue
        root = roots[0]
        out[root] = out.get(root, 0) + int(nbytes)
    return out


class WorkloadMiner:
    """Incremental aggregation of workload signatures from the process
    flight ring. Single-consumer cursor (`last_seq`); thread safety is
    the caller's (the `IndexAdvisor` serializes polls under its lock)."""

    def __init__(self, min_repeats: int = 2):
        self.min_repeats = max(1, int(min_repeats))
        self.last_seq = 0
        self._signatures: Dict[tuple, WorkloadSignature] = {}
        self.queries_seen = 0
        self.queries_served = 0

    # -- polling -----------------------------------------------------------

    def poll(self, recorder=None) -> int:
        """Fold every ring entry newer than the cursor into the
        signature table. Returns how many queries were mined."""
        if recorder is None:
            from hyperspace_tpu import telemetry
            recorder = telemetry.get_recorder()
        fresh, self.last_seq = recorder.snapshot(self.last_seq)
        for metrics in fresh:
            try:
                self._mine_one(metrics)
            except Exception:
                # One malformed recorder (test fakes, partial records)
                # must not stall the miner's cursor.
                continue
        self.queries_seen += len(fresh)
        return len(fresh)

    def _mine_one(self, metrics) -> None:
        events = [e for e in getattr(metrics, "events", ())
                  if e.get("category") == "rule"]
        if any(e.get("action") == "applied" for e in events):
            # An index already serves this query; nothing to advise.
            self.queries_served += 1
            return
        seq = getattr(metrics, "flight_seq", 0)
        plan = getattr(metrics, "logical_plan", None)
        tenant = getattr(metrics, "tenant", None) or "default"
        bytes_by_root = _scan_bytes_by_root(metrics)
        # One observation per (relation, predicate) per QUERY: the
        # filter rule declines both the outer Project(Filter(Scan))
        # match and the inner bare Filter(Scan) on the same walk,
        # emitting two whyNot records for one miss. Keep the one with
        # the NARROWEST projected set (the outer match — the columns
        # the query actually needs; the bare match reports the full
        # relation schema).
        filters: Dict[tuple, dict] = {}
        for e in events:
            if e.get("action") != "skipped":
                continue
            if e.get("name") == "FilterIndexRule" and e.get("roots"):
                k = (tuple(e["roots"]),
                     self._cols(e, "filter_columns"))
                best = filters.get(k)
                if best is None or len(e.get("project_columns") or ()) \
                        < len(best.get("project_columns") or ()):
                    filters[k] = e
            elif e.get("name") == "JoinIndexRule" \
                    and e.get("left_roots") and e.get("right_roots"):
                self._fold_join(e, seq, plan, bytes_by_root, tenant)
        for e in filters.values():
            self._fold_filter(e, seq, plan, bytes_by_root, tenant)

    @staticmethod
    def _cols(e, key) -> Tuple[str, ...]:
        return tuple(sorted({str(c).lower() for c in (e.get(key) or ())}))

    def _fold_filter(self, e, seq, plan, bytes_by_root,
                     tenant: str = "default") -> None:
        roots = tuple(e["roots"])
        filter_cols = self._cols(e, "filter_columns")
        if not filter_cols:
            return
        project_cols = self._cols(e, "project_columns")
        key = ("filter", tenant, roots, filter_cols, project_cols)
        sig = self._signatures.get(key)
        if sig is None:
            sig = self._signatures[key] = WorkloadSignature("filter", key)
            sig.tenant = tenant
            sig.roots = roots
            sig.filter_columns = filter_cols
            sig.project_columns = project_cols
        sig.eq_columns = tuple(sorted(set(sig.eq_columns)
                                      | set(self._cols(e, "eq_columns"))))
        self._observe(sig, seq, plan,
                      sum(bytes_by_root.get(r, 0) for r in roots))

    def _fold_join(self, e, seq, plan, bytes_by_root,
                   tenant: str = "default") -> None:
        left_roots = tuple(e["left_roots"])
        right_roots = tuple(e["right_roots"])
        left_cols = tuple(str(c).lower()
                          for c in (e.get("left_join_columns") or ()))
        right_cols = tuple(str(c).lower()
                           for c in (e.get("right_join_columns") or ()))
        if not left_cols or len(left_cols) != len(right_cols):
            return
        key = ("join", tenant, left_roots, right_roots, left_cols,
               right_cols)
        sig = self._signatures.get(key)
        if sig is None:
            sig = self._signatures[key] = WorkloadSignature("join", key)
            sig.tenant = tenant
            sig.roots = left_roots
            sig.right_roots = right_roots
            sig.join_columns = left_cols
            sig.right_join_columns = right_cols
        sig.referenced_columns = tuple(sorted(
            set(sig.referenced_columns)
            | set(self._cols(e, "left_referenced"))))
        sig.right_referenced_columns = tuple(sorted(
            set(sig.right_referenced_columns)
            | set(self._cols(e, "right_referenced"))))
        nbytes = (sum(bytes_by_root.get(r, 0) for r in left_roots)
                  + sum(bytes_by_root.get(r, 0) for r in right_roots))
        self._observe(sig, seq, plan, nbytes)

    @staticmethod
    def _observe(sig: WorkloadSignature, seq: int, plan,
                 nbytes: int) -> None:
        sig.count += 1
        sig.total_scan_bytes += max(0, int(nbytes))
        if seq >= sig.last_seq:
            sig.last_seq = seq
            if plan is not None:
                sig.plan = plan

    # -- results -----------------------------------------------------------

    def signatures(self) -> List[WorkloadSignature]:
        """Every signature seen so far, deterministically ordered
        (most-observed first, then key)."""
        return sorted(self._signatures.values(),
                      key=lambda s: (-s.count, s.key))

    def recurring(self) -> List[WorkloadSignature]:
        """Signatures at or past the repeat threshold, with a replayable
        plan — the scorer's input."""
        return [s for s in self.signatures()
                if s.count >= self.min_repeats and s.plan is not None]
