"""Self-driving index advisor: close the loop from observed workload to
recommended index to background build.

The source paper's plan-analysis layer stops at explain / what-if over
hypothetical indexes (PAPER.md §"whatIf"); this engine has something
Hyperspace never shipped — an always-on flight recorder holding every
query's operator tree, rule decisions (including the structured whyNot
records both rewrite rules emit on every decline), and pruning stats.
The advisor closes the loop in three stages, one module each:

- **miner** (`advisor/miner.py`): polls the flight ring INCREMENTALLY
  (`FlightRecorder.snapshot(since_seq)` — one lock acquire per poll,
  nothing re-read) and distills recurring (relation, filter-cols,
  join-cols) workload signatures from the whyNot events, with observed
  repeat counts and per-relation scan bytes.
- **what-if scorer** (`advisor/whatif.py`): synthesizes hypothetical
  covering (and data-skipping) index candidates per signature, REPLAYS
  the recorded logical plans through the real rewrite rules against a
  hypothetical catalog (no data touched — the same rule code that will
  serve the real index decides whether the candidate would fire), and
  scores candidates by estimated bytes avoided amortized over the
  observed frequency.
- **executor** (`advisor/executor.py`): auto-builds the top-scoring
  candidates through the NORMAL index-creation path (the collection
  manager's Create actions — maintenance lease, OCC one-winner races,
  action reports all apply; `scripts/check_metrics_coverage.py` bans
  Action construction anywhere in advisor/ outside the executor),
  gated by serving pressure (never starve admission), a per-warehouse
  build budget, and a per-run build cap; every recommendation,
  decision, and build lands in `advisor.*` counters and the persisted
  `_advisor_state.json`.

Surface: `Hyperspace.advisor()` returns the session's `IndexAdvisor`;
`run_once()` is one mine→score→build cycle, `start(interval_s)` runs
it on a background daemon thread. `spark.hyperspace.advisor.*` knobs
(docs/advisor.md) size the budgets; `advisor.enabled=false` makes the
executor a no-op while mining keeps measuring.
"""

from __future__ import annotations

import json
import threading
import time
from typing import List, Optional

from hyperspace_tpu.advisor.executor import AdvisorExecutor
from hyperspace_tpu.advisor.miner import WorkloadMiner, WorkloadSignature
from hyperspace_tpu.advisor.whatif import Candidate, score_signatures

__all__ = ["IndexAdvisor", "WorkloadMiner", "WorkloadSignature",
           "Candidate", "score_signatures", "AdvisorExecutor",
           "STATE_FILE"]

STATE_FILE = "_advisor_state.json"


class IndexAdvisor:
    """One session's advisor: a miner cursor over the process flight
    ring, the what-if scorer, and the build executor. `run_once()` is
    deterministic over a fixed recorded workload (the determinism test
    pins this): same ring contents → same ranked recommendations."""

    def __init__(self, session):
        self.session = session
        self.conf = session.conf
        self.miner = WorkloadMiner(min_repeats=self.conf.advisor_min_repeats)
        self.executor = AdvisorExecutor(session)
        self._lock = threading.Lock()
        self._recommendations: List[Candidate] = []
        self._decisions: List[dict] = []
        self._daemon: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- the mine -> score -> build cycle ---------------------------------

    def observe(self) -> int:
        """Incremental mine of the flight ring; returns how many new
        queries were folded in."""
        from hyperspace_tpu import telemetry
        mined = self.miner.poll()
        if mined:
            telemetry.get_registry().counter(
                "advisor.queries_mined").inc(mined)
        return mined

    def recommendations(self) -> List[Candidate]:
        """Ranked candidates of the latest scoring pass (best first)."""
        with self._lock:
            return list(self._recommendations)

    def decisions(self) -> List[dict]:
        with self._lock:
            return list(self._decisions)

    def run_once(self) -> dict:
        """One full advisor cycle: poll the ring, what-if score the
        recurring signatures, build what wins (unless disabled or
        deferred), persist `_advisor_state.json`. Returns a summary
        dict (also the shape persisted per run)."""
        from hyperspace_tpu import telemetry

        reg = telemetry.get_registry()
        reg.counter("advisor.runs").inc()
        with self._lock:
            mined = self.miner.poll()
            if mined:
                reg.counter("advisor.queries_mined").inc(mined)
            signatures = self.miner.recurring()
            reg.gauge("advisor.signatures").set(len(signatures))
            candidates = score_signatures(self.session, signatures,
                                          self.conf)
            reg.counter("advisor.candidates").inc(len(candidates))
            recommended = [c for c in candidates if c.score > 0
                           and c.score
                           >= self.conf.advisor_min_benefit_bytes]
            reg.gauge("advisor.recommended").set(len(recommended))
            self._recommendations = recommended
            if self.conf.advisor_enabled:
                decisions = self.executor.execute(recommended)
            else:
                decisions = [{"name": c.name, "action": "disabled",
                              "reason": "spark.hyperspace.advisor."
                                        "enabled=false"}
                             for c in recommended]
            self._decisions.extend(decisions)
            summary = {
                "ran_at": round(time.time(), 3),
                "queries_mined": mined,
                "last_seq": self.miner.last_seq,
                "signatures": [s.to_dict() for s in signatures],
                "recommendations": [c.to_dict() for c in recommended],
                "decisions": decisions,
                "skipping_drift": self.skipping_drift(),
            }
            self._persist(summary)
        telemetry.event("advisor", "run",
                        signatures=len(signatures),
                        recommended=len(recommended),
                        built=sum(1 for d in decisions
                                  if d.get("action") == "built"))
        return summary

    def skipping_drift(self) -> dict:
        """How far reality drifted from the what-if scorer's blind
        constant: the scorer assumes every skipping index prunes
        `spark.hyperspace.advisor.skipping.prune.fraction` of a scan,
        while `FilterIndexRule` records the MEASURED fraction of every
        served query (`skipping.measured_prune_fraction` histogram +
        per-index gauges). The loop is CLOSED: `whatif.py` now scores
        skipping candidates with the measured fraction (per-index
        gauge first, then the global mean) and falls back to the
        assumption only before anything has been measured —
        `scoring_source` here says which one the next scoring pass
        will use, and each candidate's
        `detail["prune_fraction_source"]` records which one it DID
        use."""
        from hyperspace_tpu import telemetry

        assumed = self.conf.advisor_skipping_prune_fraction
        out: dict = {"assumed_fraction": assumed,
                     "measured_mean_fraction": None,
                     "queries_measured": 0, "drift": None,
                     "scoring_source": "assumed",
                     "per_index": {}}
        snap = telemetry.get_registry().series_snapshot()
        hist = snap.get("histograms", {}).get(
            "skipping.measured_prune_fraction")
        if hist and hist.get("count"):
            mean = hist["sum"] / hist["count"]
            out["measured_mean_fraction"] = round(mean, 4)
            out["queries_measured"] = hist["count"]
            out["drift"] = round(mean - assumed, 4)
            out["scoring_source"] = "measured"
        for name, value in snap.get("gauges", {}).items():
            if name.startswith("skipping.") and \
                    name.endswith(".measured_prune_fraction"):
                index = name[len("skipping."):
                             -len(".measured_prune_fraction")]
                out["per_index"][index] = round(value, 4)
        return out

    def report(self) -> dict:
        """One human-facing advisor report: the latest ranked
        recommendations and decisions, the skipping-drift story, and
        the per-index usage rows (`Hyperspace.index_usage`) with their
        `unused` drop candidates — each section error-isolated, so a
        mid-teardown subsystem degrades to an `{"error": ...}` stub
        instead of failing the whole read. Report-only: nothing is
        built or vacuumed by asking."""
        doc: dict = {"generated_at": round(time.time(), 3)}

        def section(name, fn):
            try:
                doc[name] = fn()
            except Exception as exc:
                doc[name] = {"error": repr(exc)}

        def _usage():
            from hyperspace_tpu.facade import Hyperspace
            rows = Hyperspace(self.session).index_usage()
            return {"indexes": rows,
                    "unused": [r["index"] for r in rows if r["unused"]]}

        section("recommendations",
                lambda: [c.to_dict() for c in self.recommendations()])
        section("decisions", self.decisions)
        section("skipping_drift", self.skipping_drift)
        section("index_usage", _usage)
        return doc

    # -- persisted state ---------------------------------------------------

    def _state_path(self) -> str:
        from hyperspace_tpu.utils import storage
        return storage.join(self.conf.system_path, STATE_FILE)

    def _persist(self, summary: dict) -> None:
        """Atomic single-file state: the latest run summary plus the
        decision history — what a fresh process (or an operator asking
        "why did you build that?") reads back. A persistence failure
        never fails the run (counted `advisor.state_errors`)."""
        from hyperspace_tpu import telemetry
        from hyperspace_tpu.utils import file_utils
        doc = {
            "kind": "hyperspace-advisor-state",
            "version": 1,
            "updated_at": summary["ran_at"],
            "last_seq": summary["last_seq"],
            "last_run": summary,
            "decision_history": self._decisions[-200:],
        }
        try:
            file_utils.create_directory(self.conf.system_path)
            file_utils.atomic_publish(self._state_path(),
                                      json.dumps(doc, default=str,
                                                 indent=1))
        except Exception:
            telemetry.get_registry().counter(
                "advisor.state_errors").inc()

    def state(self) -> Optional[dict]:
        """Reload the persisted advisor state, or None."""
        from hyperspace_tpu.utils import file_utils
        try:
            raw = file_utils.load_byte_array(self._state_path())
        except Exception:
            return None
        try:
            return json.loads(raw)
        except Exception:
            return None

    # -- background mode ---------------------------------------------------

    def start(self, interval_s: float = 60.0) -> None:
        """Run `run_once` on a background daemon thread every
        `interval_s` seconds until `stop()`. Idempotent. The thread
        lives in advisor/, not engine/ — it issues no queries, only
        maintenance builds, which the serving-pressure gate makes yield
        to live traffic."""
        if self._daemon is not None and self._daemon.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.run_once()
                except Exception:
                    from hyperspace_tpu import telemetry
                    telemetry.get_registry().counter(
                        "advisor.run_errors").inc()

        self._daemon = threading.Thread(target=loop, name="hs-advisor",
                                        daemon=True)
        self._daemon.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        daemon, self._daemon = self._daemon, None
        if daemon is not None:
            daemon.join(timeout=timeout_s)
