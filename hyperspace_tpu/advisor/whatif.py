"""What-if scoring: replay recorded plans against hypothetical indexes.

The Hyperspace paper's `whatIf` answers "would this index be used, and
what would it save?" without building anything. This module does the
same with the engine's REAL machinery instead of a cost-model clone:

- for each recurring workload signature (`advisor/miner.py`), it
  synthesizes a hypothetical ACTIVE `IndexLogEntry` — fingerprinted
  with the same `FileBasedSignatureProvider` a real build would use, so
  signature matching behaves identically — whose `extra.stats` carries
  the ESTIMATED on-disk size (the rules' cost-based ranking reads
  stamped stats, never the filesystem, so a nonexistent data root is
  fine);
- it REPLAYS the recorded source plan through the real rewrite rules
  (`JoinIndexRule` + `FilterIndexRule` via a throwaway session whose
  catalog is the real ACTIVE entries plus the hypotheticals — candidate
  selection, coverage, ranking all run the production code path) and
  keeps a candidate only if the rules actually select it;
- it scores each kept candidate by estimated bytes avoided per
  occurrence, amortized over the signature's observed repeat count.

No data is touched: the only IO is the signature provider's file
stats. The byte model (documented in docs/advisor.md): a covering
index over columns C of a relation with schema S costs
`src_bytes * width(C)/width(S)` to read; a point (equality) predicate
on the leading indexed column additionally prunes to 1/num_buckets of
it. A hypothetical DATA-SKIPPING index cannot be replayed (the rules
consult sketch blobs that do not exist yet), so it scores with the
conservative `spark.hyperspace.advisor.skipping.prune.fraction`
constant and is marked estimate-only.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from hyperspace_tpu.utils.hashing import md5_hex

__all__ = ["Candidate", "score_signatures", "hypothetical_entry",
           "replay_plan", "measured_prune_fraction"]

# Approximate decoded bytes per value per logical dtype — only RATIOS
# matter (index width over relation width).
_DTYPE_WIDTH = {
    "bool": 1, "int8": 1, "int16": 2, "int32": 4, "int64": 8,
    "float32": 4, "float64": 8, "date32": 4, "timestamp": 8,
    # int32 codes + an amortized share of dictionary + hashes.
    "string": 12,
}


def _width(schema, columns: Optional[Sequence[str]] = None) -> int:
    names = ({c.lower() for c in columns} if columns is not None
             else None)
    total = 0
    for f in schema.fields:
        if names is None or f.name.lower() in names:
            total += _DTYPE_WIDTH.get(f.dtype, 8)
    return max(total, 1)


class Candidate:
    """One scored recommendation: the config(s) to build, the relation
    scan(s) to build them over, and the what-if verdict."""

    __slots__ = ("kind", "name", "configs", "scans", "signature",
                 "est_index_bytes", "est_bytes_avoided_per_query",
                 "score", "replayed", "replay_applied", "detail")

    def __init__(self, kind: str, name: str, configs, scans, signature,
                 est_index_bytes: int, est_avoided: int,
                 replayed: bool, replay_applied: Optional[bool],
                 detail: Optional[dict] = None):
        self.kind = kind
        self.name = name
        self.configs = list(configs)
        self.scans = list(scans)
        self.signature = signature
        self.est_index_bytes = int(est_index_bytes)
        self.est_bytes_avoided_per_query = int(est_avoided)
        self.score = int(est_avoided) * signature.count
        self.replayed = replayed
        self.replay_applied = replay_applied
        self.detail = detail or {}

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "name": self.name,
            "indexes": [getattr(c, "index_name", None)
                        for c in self.configs],
            "signature": self.signature.to_dict(),
            "est_index_bytes": self.est_index_bytes,
            "est_bytes_avoided_per_query":
                self.est_bytes_avoided_per_query,
            "score": self.score,
            "replayed": self.replayed,
            "replay_applied": self.replay_applied,
            "detail": dict(self.detail),
        }


def _candidate_name(kind: str, root: str, indexed, included) -> str:
    """Deterministic, collision-resistant advisor index name — the same
    signature always proposes the same name, so re-runs recognize their
    own builds in the catalog instead of proposing duplicates."""
    digest = md5_hex("|".join((kind, root, ",".join(indexed),
                               ",".join(included))))[:10]
    return f"adv_{kind}_{digest}"


def measured_prune_fraction(conf, index_name: Optional[str] = None):
    """The skipping prune fraction the scorer should assume, as
    `(fraction, source)` — closing the advisor's blind-constant loop:
    prefer the MEASURED per-index gauge for `index_name` (candidate
    names are deterministic, so a signature re-proposing an index the
    advisor already built reads that index's own recorded reality),
    then the global measured mean (`skipping.measured_prune_fraction`
    histogram over every served skipping query), and only then the
    `advisor.skipping.prune.fraction` conf assumption. `source` is one
    of "measured:index" / "measured:global" / "assumed" — candidates
    carry it in `detail["prune_fraction_source"]` and the drift report
    says when measurement overrode the assumption."""
    from hyperspace_tpu import telemetry

    def clamp(v):
        return min(max(float(v), 0.0), 1.0)

    snap = telemetry.get_registry().series_snapshot()
    if index_name is not None:
        v = snap.get("gauges", {}).get(
            f"skipping.{index_name}.measured_prune_fraction")
        if v is not None:
            return clamp(v), "measured:index"
    hist = snap.get("histograms", {}).get(
        "skipping.measured_prune_fraction")
    count = (hist or {}).get("count") or 0
    if count:
        return clamp(hist["sum"] / count), "measured:global"
    return clamp(conf.advisor_skipping_prune_fraction), "assumed"


def _single_scan(plan, roots) -> Optional[object]:
    """The plan's Scan leaf over exactly `roots`, or None."""
    from hyperspace_tpu.plan.nodes import Scan
    for leaf in plan.collect_leaves():
        if isinstance(leaf, Scan) and tuple(leaf.root_paths) == roots:
            return leaf
    return None


def hypothetical_entry(name: str, scan, indexed: Sequence[str],
                       included: Sequence[str], num_buckets: int,
                       system_path: str, est_bytes: int):
    """An ACTIVE `IndexLogEntry` for an index that does not exist:
    fingerprinted over the live source files exactly as
    `CreateActionBase.get_index_log_entry` would, data root pointed at
    the path a real build WOULD use, estimated size stamped into
    `extra.stats` (what the rules' ranking reads). Returns None when
    the source cannot be fingerprinted (files vanished since
    recording)."""
    from hyperspace_tpu.constants import States
    from hyperspace_tpu.index.log_entry import (Content, CoveringIndex,
                                                Directory, Hdfs,
                                                IndexLogEntry,
                                                LogicalPlanFingerprint,
                                                NoOpFingerprint,
                                                PlanSource, Signature,
                                                Source)
    from hyperspace_tpu.index.signature import FileBasedSignatureProvider
    from hyperspace_tpu.plan.serde import plan_to_json

    provider = FileBasedSignatureProvider()
    try:
        sig_value = provider.signature(scan)
    except Exception:
        sig_value = None
    if sig_value is None:
        return None
    schema = scan.schema.select(list(indexed) + list(included))
    files = scan.files()
    entry = IndexLogEntry(
        name=name,
        derived_dataset=CoveringIndex(
            indexed_columns=list(indexed),
            included_columns=list(included),
            schema_json=schema.to_json(),
            num_buckets=num_buckets),
        content=Content(root=os.path.join(system_path, name, "v__=0"),
                        directories=[]),
        source=Source(
            plan=PlanSource(
                raw_plan=plan_to_json(scan),
                fingerprint=LogicalPlanFingerprint(
                    [Signature(provider.name(), sig_value)])),
            data=[Hdfs(Content(root="", directories=[
                Directory(path="", files=files,
                          fingerprint=NoOpFingerprint())]))]),
        extra={"stats": {"dataSizeBytes": int(est_bytes),
                         "rowCount": 0},
               "hypothetical": True})
    entry.state = States.ACTIVE
    return entry


class _WhatIfManager:
    """Catalog stand-in the replay session's rules read: the REAL
    active entries plus the hypotheticals under test."""

    def __init__(self, entries):
        self._entries = list(entries)

    def get_indexes(self, states=None):
        return [e for e in self._entries
                if states is None or e.state in states]


def replay_plan(session, plan, hypothetical_entries):
    """Run the production rewrite rules over (a serde clone of) `plan`
    with the hypothetical entries visible, returning the set of index
    names the rules actually SELECTED. The clone keeps replay-side plan
    mutation (snapshot pins, explicit file lists) off the recorded
    object."""
    from hyperspace_tpu.constants import States
    from hyperspace_tpu.engine.session import HyperspaceSession
    from hyperspace_tpu.facade import Hyperspace, HyperspaceContext
    from hyperspace_tpu.plan.nodes import Scan
    from hyperspace_tpu.plan.serde import plan_from_json, plan_to_json

    real = []
    try:
        manager = Hyperspace.get_context(session).index_collection_manager
        real = manager.get_indexes([States.ACTIVE])
    except Exception:
        pass
    shadow = HyperspaceSession(session.conf)
    shadow.enable_hyperspace()
    ctx = HyperspaceContext.__new__(HyperspaceContext)
    ctx.index_collection_manager = _WhatIfManager(
        real + list(hypothetical_entries))
    with Hyperspace._lock:
        Hyperspace._contexts[shadow] = ctx
    try:
        clone = plan_from_json(plan_to_json(plan))
        optimized = shadow.optimize(clone)
    except Exception:
        return set()
    selected = set()

    def visit(node):
        if isinstance(node, Scan) and node.index_name:
            selected.add(node.index_name)
        for c in node.children:
            visit(c)

    visit(optimized)
    return selected


def _filter_candidates(session, sig, conf, system_path) -> List[Candidate]:
    """Covering + data-skipping candidates for one recurring filter
    signature."""
    from hyperspace_tpu.index.index_config import (DataSkippingIndexConfig,
                                                   IndexConfig)

    if len(sig.roots) != 1 or sig.plan is None:
        return []
    scan = _single_scan(sig.plan, sig.roots)
    if scan is None:
        return []
    root = sig.roots[0]
    src_bytes = max(sig.mean_scan_bytes, 0)
    if src_bytes <= 0:
        from hyperspace_tpu.plan import footprint
        src_bytes = footprint.scan_disk_bytes(scan)
    out: List[Candidate] = []

    # Covering candidate: eq columns lead (bucket pruning serves point
    # predicates), then the remaining filter columns; included = every
    # other column the query shape reads.
    eq = [c for c in sig.filter_columns if c in set(sig.eq_columns)]
    non_eq = [c for c in sig.filter_columns if c not in set(eq)]
    indexed = list(eq) + list(non_eq)
    needed = set(sig.project_columns) | set(sig.filter_columns)
    included = sorted(needed - set(indexed))
    covered_all = {f.name.lower() for f in scan.schema.fields} <= \
        (set(indexed) | set(included))
    num_buckets = conf.num_buckets
    width_frac = _width(scan.schema, indexed + included) \
        / _width(scan.schema)
    est_idx_bytes = max(1, int(src_bytes * min(width_frac, 1.0)))
    read_frac = (1.0 / max(num_buckets, 1)
                 if indexed and indexed[0] in set(eq) else 1.0)
    avoided = max(0, src_bytes - int(est_idx_bytes * read_frac))
    if avoided > 0:
        name = _candidate_name("cov", root, indexed, included)
        entry = hypothetical_entry(name, scan, indexed, included,
                                   num_buckets, system_path,
                                   est_idx_bytes)
        if entry is not None:
            applied = name in replay_plan(session, sig.plan, [entry])
            if applied:
                cfg = IndexConfig(name, indexed, included)
                out.append(Candidate(
                    "covering", name, [cfg], [scan], sig,
                    est_idx_bytes, avoided, replayed=True,
                    replay_applied=True,
                    detail={"root": root, "indexed": indexed,
                            "included": included,
                            "read_fraction": round(read_frac, 6),
                            "covers_full_schema": covered_all}))

    # Data-skipping candidate: cheap to build and store (per-file
    # sketches), prunes whole files instead of narrowing rows. The
    # rules cannot replay sketches that do not exist — estimate-only,
    # scored with the MEASURED prune fraction when the rules have
    # recorded one (per-index first, then the global mean), and only
    # the conf assumption when nothing has been measured yet.
    sk_name = _candidate_name("skip", root, list(sig.filter_columns), [])
    prune_frac, prune_src = measured_prune_fraction(conf, sk_name)
    sk_avoided = int(src_bytes * prune_frac)
    if sk_avoided > 0 and sig.filter_columns:
        sk_cfg = DataSkippingIndexConfig(sk_name,
                                         list(sig.filter_columns))
        out.append(Candidate(
            "skipping", sk_name, [sk_cfg], [scan], sig,
            # Sketch blobs are ~per-file metadata: budget them at 1% of
            # the source, floored at 64 KiB.
            max(64 * 1024, src_bytes // 100), sk_avoided,
            replayed=False, replay_applied=None,
            detail={"root": root,
                    "skip_by": list(sig.filter_columns),
                    "prune_fraction": prune_frac,
                    "prune_fraction_source": prune_src}))
    return out


def _join_candidates(session, sig, conf, system_path) -> List[Candidate]:
    """A compatible covering-index PAIR for one recurring join
    signature (both sides must exist for the join rule to fire — the
    candidate is the pair, built together)."""
    from hyperspace_tpu.index.index_config import IndexConfig

    if len(sig.roots) != 1 or len(sig.right_roots) != 1 \
            or sig.plan is None:
        return []
    left_scan = _single_scan(sig.plan, sig.roots)
    right_scan = _single_scan(sig.plan, sig.right_roots)
    if left_scan is None or right_scan is None:
        return []
    from hyperspace_tpu.plan import footprint

    sides = []
    total_avoided = 0
    total_idx_bytes = 0
    entries = []
    configs = []
    names = []
    for scan, join_cols, referenced in (
            (left_scan, sig.join_columns, sig.referenced_columns),
            (right_scan, sig.right_join_columns,
             sig.right_referenced_columns)):
        src = footprint.scan_disk_bytes(scan)
        indexed = list(join_cols)
        needed = set(referenced) or \
            {f.name.lower() for f in scan.schema.fields}
        included = sorted(needed - set(indexed))
        width_frac = _width(scan.schema, indexed + included) \
            / _width(scan.schema)
        est_idx = max(1, int(src * min(width_frac, 1.0)))
        name = _candidate_name("cov", scan.root_paths[0], indexed,
                               included)
        entry = hypothetical_entry(name, scan, indexed, included,
                                   conf.num_buckets, system_path,
                                   est_idx)
        if entry is None:
            return []
        entries.append(entry)
        configs.append(IndexConfig(name, indexed, included))
        names.append(name)
        sides.append(scan)
        total_avoided += max(0, src - est_idx)
        total_idx_bytes += est_idx
    # The pair also elides the join's Exchange+Sort (the bucketed
    # layout IS the sort) — count the join keys' width once more as a
    # stand-in for that saved pass, so an equal-width pair still
    # scores.
    total_avoided += _width(left_scan.schema, sig.join_columns) \
        * max(1, sig.count)
    if total_avoided <= 0:
        return []
    selected = replay_plan(session, sig.plan, entries)
    if not set(names) <= selected:
        return []
    return [Candidate(
        "join", "+".join(names), configs, sides, sig,
        total_idx_bytes, total_avoided, replayed=True,
        replay_applied=True,
        detail={"left_root": sig.roots[0],
                "right_root": sig.right_roots[0],
                "join_columns": list(sig.join_columns)})]


def _already_built(session, candidate: Candidate) -> bool:
    """True when every index of the candidate already exists in the
    catalog in any non-DOESNOTEXIST state (built by a previous advisor
    run — deterministic names make this an exact check — or by hand)."""
    from hyperspace_tpu.constants import States
    from hyperspace_tpu.facade import Hyperspace
    try:
        manager = Hyperspace.get_context(session).index_collection_manager
        existing = {e.name for e in manager.get_indexes()
                    if e.state != States.DOESNOTEXIST}
    except Exception:
        return False
    return all(getattr(c, "index_name", None) in existing
               for c in candidate.configs)


def score_signatures(session, signatures, conf) -> List[Candidate]:
    """Candidates for every recurring signature, what-if verified where
    replayable, deduplicated against the live catalog, ranked by score
    (desc) then name — deterministic over fixed inputs."""
    system_path = conf.system_path
    out: List[Candidate] = []
    for sig in signatures:
        try:
            if sig.kind == "filter":
                cands = _filter_candidates(session, sig, conf,
                                           system_path)
            elif sig.kind == "join":
                cands = _join_candidates(session, sig, conf,
                                         system_path)
            else:
                cands = []
        except Exception:
            continue  # one unscorable signature never stalls the rest
        for c in cands:
            if not _already_built(session, c):
                out.append(c)
    seen = set()
    deduped = []
    for c in sorted(out, key=lambda c: (-c.score, c.name)):
        if c.name not in seen:
            seen.add(c.name)
            deduped.append(c)
    return deduped
