"""Advisor build executor — the ONE place advisor code turns a
recommendation into an index.

Builds go through the session's `CachingIndexCollectionManager.create`,
i.e. the exact transactional path a user-issued `hs.create_index`
takes: lease-based stale-writer recovery in `validate()`, optimistic
one-winner concurrency on the op-log slot in `begin()`, action reports,
and the commit-marker protocol. `scripts/check_metrics_coverage.py`
bans Action construction anywhere else under advisor/ — an advisor
build that bypassed the lease path could corrupt an index the moment a
manual maintenance verb raced it.

Gates, in order, per run:

1. **serving pressure** — the whole run defers (`advisor.deferred`)
   while queries wait in the scheduler queue, or while admitted bytes
   exceed `spark.hyperspace.advisor.serve.headroom` of the serving HBM
   budget. Background index builds must NEVER starve admission; a
   deferred run simply retries on the next cycle.
2. **build budget** — summed ESTIMATED index bytes per run stay under
   `spark.hyperspace.advisor.build.budget.bytes`
   (`advisor.rejected_budget` past it) and at most
   `spark.hyperspace.advisor.max.builds` builds start. Signatures are
   keyed by tenant (`advisor/miner.py`), and each tenant's share of the
   run additionally stays under its own
   `spark.hyperspace.advisor.tenant.<id>.budget.bytes` when set —
   one chatty tenant cannot monopolize the build pool.
3. **the lease path** — a lost OCC race or an index that appeared
   since scoring is a clean `conflict` decision (`advisor.
   build_conflicts`), not an error: somebody else built it, the
   workload is served either way.
"""

from __future__ import annotations

import time
from typing import List, Optional

__all__ = ["AdvisorExecutor"]


class AdvisorExecutor:
    def __init__(self, session):
        self.session = session
        self.conf = session.conf

    # -- gates -------------------------------------------------------------

    def serving_pressure(self) -> Optional[str]:
        """A human-readable reason to defer every build this run, or
        None when serving is quiet enough."""
        from hyperspace_tpu.engine.scheduler import get_scheduler
        try:
            p = get_scheduler().pressure()
        except Exception:
            return None
        if p.get("queue_depth", 0) > 0:
            return (f"{p['queue_depth']} queries waiting for admission")
        budget = self.conf.serve_hbm_budget_bytes
        if budget and budget > 0:
            headroom = max(0.0, min(self.conf.advisor_serve_headroom,
                                    1.0))
            if p.get("admitted_bytes", 0) > budget * headroom:
                return (f"admitted {p['admitted_bytes']} B exceeds "
                        f"{headroom:.0%} of the {budget} B serving "
                        "budget")
        return None

    # -- the build ---------------------------------------------------------

    def _exists(self, index_name: str) -> bool:
        from hyperspace_tpu.constants import States
        from hyperspace_tpu.facade import Hyperspace
        try:
            manager = Hyperspace.get_context(
                self.session).index_collection_manager
            return any(e.name == index_name for e in manager.get_indexes()
                       if e.state != States.DOESNOTEXIST)
        except Exception:
            return False

    def _build_one(self, config, scan) -> None:
        """One index build through the lease path (module docstring).
        Raises whatever the action raises — the caller classifies."""
        from hyperspace_tpu.engine.dataframe import DataFrame
        from hyperspace_tpu.facade import Hyperspace
        from hyperspace_tpu.plan.nodes import Scan

        manager = Hyperspace.get_context(
            self.session).index_collection_manager
        # A fresh Scan clone: create() fingerprints and lists the
        # CURRENT source state, never the recorded plan object (whose
        # listing may be stale or pinned).
        df = DataFrame(Scan(list(scan.root_paths), scan.schema),
                       self.session)
        manager.create(df, config)

    def execute(self, candidates: List) -> List[dict]:
        """Act on ranked candidates; returns one decision dict per
        candidate (and one 'deferred' marker for the whole run when the
        serving gate trips)."""
        from hyperspace_tpu import telemetry
        from hyperspace_tpu.exceptions import HyperspaceException

        reg = telemetry.get_registry()
        decisions: List[dict] = []
        if not candidates:
            return decisions
        pressure = self.serving_pressure()
        if pressure is not None:
            reg.counter("advisor.deferred").inc()
            return [{"name": c.name, "action": "deferred",
                     "reason": pressure, "score": c.score}
                    for c in candidates]

        budget = self.conf.advisor_build_budget_bytes
        max_builds = max(0, self.conf.advisor_max_builds)
        spent = 0
        tenant_spent: dict = {}
        builds = 0
        for cand in candidates:
            tenant = getattr(cand.signature, "tenant", None) or "default"
            decision = {"name": cand.name, "kind": cand.kind,
                        "score": cand.score, "tenant": tenant,
                        "est_index_bytes": cand.est_index_bytes,
                        "decided_at": round(time.time(), 3)}
            if builds + len(cand.configs) > max_builds:
                decision.update(action="skipped",
                                reason=f"max.builds={max_builds} "
                                       "reached this run")
                decisions.append(decision)
                continue
            if budget > 0 and spent + cand.est_index_bytes > budget:
                reg.counter("advisor.rejected_budget").inc()
                decision.update(
                    action="rejected_budget",
                    reason=f"estimated {cand.est_index_bytes} B would "
                           f"exceed the {budget} B build budget "
                           f"({spent} B already committed this run)")
                decisions.append(decision)
                continue
            # Per-tenant build budget: the miner keys signatures by
            # tenant, so each candidate bills exactly one tenant;
            # `advisor.tenant.<id>.budget.bytes` caps what one tenant's
            # workload can spend per run without starving the others
            # out of the shared `build.budget.bytes` pool (0 = no
            # per-tenant cap; the global budget still applies).
            t_budget = self.conf.advisor_tenant_budget_bytes(tenant)
            t_spent = tenant_spent.get(tenant, 0)
            if t_budget > 0 and t_spent + cand.est_index_bytes > t_budget:
                reg.counter("advisor.rejected_budget").inc()
                reg.counter(
                    f"advisor.tenant.{tenant}.rejected_budget").inc()
                decision.update(
                    action="rejected_budget",
                    reason=f"estimated {cand.est_index_bytes} B would "
                           f"exceed tenant '{tenant}'s {t_budget} B "
                           f"build budget ({t_spent} B already "
                           "committed this run)")
                decisions.append(decision)
                continue
            try:
                built_names = []
                for config, scan in zip(cand.configs, cand.scans):
                    if self._exists(config.index_name):
                        # Half-built pair from an interrupted prior run,
                        # or a manual build: finish the missing side(s)
                        # instead of refusing the whole candidate.
                        continue
                    self._build_one(config, scan)
                    builds += 1
                    built_names.append(config.index_name)
                spent += cand.est_index_bytes
                tenant_spent[tenant] = t_spent + cand.est_index_bytes
                if built_names:
                    reg.counter("advisor.builds").inc(len(built_names))
                    decision.update(action="built", indexes=built_names)
                else:
                    decision.update(action="exists",
                                    reason="every index of the "
                                           "candidate already exists")
            except HyperspaceException as exc:
                # Lost the op-log slot / index appeared since scoring:
                # the lease path kept the catalog consistent; somebody
                # else owns the build. Clean concede.
                reg.counter("advisor.build_conflicts").inc()
                decision.update(action="conflict", reason=str(exc))
            except Exception as exc:  # noqa: BLE001 — classified below
                reg.counter("advisor.build_failures").inc()
                decision.update(action="failed", reason=repr(exc))
            decisions.append(decision)
            telemetry.event("advisor", "decision",
                            candidate=decision.get("name"),
                            action=decision.get("action"),
                            score=decision.get("score"))
        return decisions
