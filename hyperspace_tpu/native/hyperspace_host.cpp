// Native host glue for hyperspace_tpu.
//
// The reference delegates host-side heavy lifting to Spark's JVM engine;
// this framework's host path is Python + pyarrow, with the per-value
// dictionary hashing (the one O(values * bytes) pure-Python loop) done
// here. Exposed via a plain C ABI and loaded with ctypes — no pybind11
// dependency.
//
// Functions operate on Arrow string-array layout: a contiguous UTF-8 data
// buffer plus (n+1) int offsets.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Per-bucket sorted merge join over int64 keys laid out bucket-major
// (both sides sorted within each bucket — the covering-index layout).
// Classic run-merge: for each run of equal left keys, bracket the equal
// right run once; inner emits the cross product, left_outer emits one
// (i, -1) row per unmatched left row.

struct JoinInputs {
    const int64_t* lk;
    const int64_t* rk;
    const int64_t* lb;  // B+1 cumulative left bucket bounds
    const int64_t* rb;  // B+1 cumulative right bucket bounds
    int left_outer;
};

void count_range(const JoinInputs& in, int64_t b0, int64_t b1,
                 int64_t* counts) {
    for (int64_t b = b0; b < b1; ++b) {
        int64_t i = in.lb[b], le = in.lb[b + 1];
        int64_t j = in.rb[b], re = in.rb[b + 1];
        int64_t cnt = 0;
        while (i < le) {
            const int64_t k = in.lk[i];
            while (j < re && in.rk[j] < k) ++j;
            int64_t j2 = j;
            while (j2 < re && in.rk[j2] == k) ++j2;
            int64_t i2 = i;
            while (i2 < le && in.lk[i2] == k) ++i2;
            const int64_t m = j2 - j;
            cnt += m ? m * (i2 - i) : (in.left_outer ? (i2 - i) : 0);
            i = i2;
            j = j2;
        }
        counts[b] = cnt;
    }
}

void fill_range(const JoinInputs& in, int64_t b0, int64_t b1,
                const int64_t* offsets, int32_t* li, int32_t* ri) {
    for (int64_t b = b0; b < b1; ++b) {
        int64_t i = in.lb[b], le = in.lb[b + 1];
        int64_t j = in.rb[b], re = in.rb[b + 1];
        int64_t o = offsets[b];
        while (i < le) {
            const int64_t k = in.lk[i];
            while (j < re && in.rk[j] < k) ++j;
            int64_t j2 = j;
            while (j2 < re && in.rk[j2] == k) ++j2;
            int64_t i2 = i;
            while (i2 < le && in.lk[i2] == k) ++i2;
            if (j2 > j) {
                for (int64_t a = i; a < i2; ++a) {
                    for (int64_t c = j; c < j2; ++c) {
                        li[o] = static_cast<int32_t>(a);
                        ri[o] = static_cast<int32_t>(c);
                        ++o;
                    }
                }
            } else if (in.left_outer) {
                for (int64_t a = i; a < i2; ++a) {
                    li[o] = static_cast<int32_t>(a);
                    ri[o] = -1;
                    ++o;
                }
            }
            i = i2;
            j = j2;
        }
    }
}

// Contiguous bucket ranges balanced by left-row mass.
std::vector<int64_t> split_buckets(const int64_t* lb, int64_t B,
                                   int n_threads) {
    std::vector<int64_t> cuts;
    cuts.push_back(0);
    const int64_t total = lb[B];
    for (int t = 1; t < n_threads; ++t) {
        const int64_t want = total * t / n_threads;
        int64_t b = cuts.back();
        while (b < B && lb[b] < want) ++b;
        cuts.push_back(b);
    }
    cuts.push_back(B);
    return cuts;
}

template <typename Fn>
void run_threaded(const int64_t* lb, int64_t B, int n_threads, Fn fn) {
    if (n_threads <= 1 || B <= 1) {
        fn(0, B);
        return;
    }
    auto cuts = split_buckets(lb, B, n_threads);
    std::vector<std::thread> workers;
    for (size_t t = 0; t + 1 < cuts.size(); ++t) {
        if (cuts[t + 1] > cuts[t]) {
            workers.emplace_back(fn, cuts[t], cuts[t + 1]);
        }
    }
    for (auto& w : workers) w.join();
}

}  // namespace

extern "C" {

void bucketed_merge_join_count_i64(const int64_t* lk, const int64_t* rk,
                                   const int64_t* lb, const int64_t* rb,
                                   int64_t B, int left_outer,
                                   int n_threads, int64_t* counts) {
    JoinInputs in{lk, rk, lb, rb, left_outer};
    run_threaded(lb, B, n_threads, [&](int64_t b0, int64_t b1) {
        count_range(in, b0, b1, counts);
    });
}

void bucketed_merge_join_fill_i64(const int64_t* lk, const int64_t* rk,
                                  const int64_t* lb, const int64_t* rb,
                                  int64_t B, int left_outer, int n_threads,
                                  const int64_t* offsets, int32_t* li,
                                  int32_t* ri) {
    JoinInputs in{lk, rk, lb, rb, left_outer};
    run_threaded(lb, B, n_threads, [&](int64_t b0, int64_t b1) {
        fill_range(in, b0, b1, offsets, li, ri);
    });
}

}  // extern "C"

namespace {

// Stable LSD radix scatter of the current permutation by one 16-bit
// digit of `w` (values gathered through the permutation). `hist` is the
// digit histogram, already computed over the full array; `offs` is a
// caller-provided 65536-slot scratch — like the histogram it lives on
// the heap, not this frame: a 512 KB stack array would overflow
// small-stack worker threads (musl/pthread defaults).
void radix_pass_u64(const uint64_t* w, int shift, const int64_t* hist,
                    const int32_t* cur, int32_t* nxt, int64_t n,
                    int64_t* offs) {
    int64_t run = 0;
    for (int d = 0; d < 65536; ++d) {
        offs[d] = run;
        run += hist[d];
    }
    for (int64_t i = 0; i < n; ++i) {
        const int32_t r = cur[i];
        nxt[offs[(w[r] >> shift) & 0xFFFF]++] = r;
    }
}

// Stable ascending LSD radix over the packed uint64 sort words
// (words[0] most significant), starting from the identity permutation
// in `a` with scratch `b`. Returns whichever buffer holds the final
// order. Shared by the bucketed and plain entry points.
int32_t* radix_words_lsd(const uint64_t* const* words, int32_t n_words,
                         int64_t n, int32_t* a, int32_t* b) {
    std::vector<int64_t> hist(4 * 65536);
    std::vector<int64_t> offs(65536);
    for (int32_t w = n_words - 1; w >= 0; --w) {
        const uint64_t* W = words[w];
        std::fill(hist.begin(), hist.end(), 0);
        int64_t* h0 = hist.data();
        int64_t* h1 = h0 + 65536;
        int64_t* h2 = h1 + 65536;
        int64_t* h3 = h2 + 65536;
        for (int64_t i = 0; i < n; ++i) {
            const uint64_t v = W[i];
            ++h0[v & 0xFFFF];
            ++h1[(v >> 16) & 0xFFFF];
            ++h2[(v >> 32) & 0xFFFF];
            ++h3[v >> 48];
        }
        const int64_t* hs[4] = {h0, h1, h2, h3};
        for (int p = 0; p < 4; ++p) {
            // A digit with a single occupied bin permutes nothing.
            // Constant iff the first non-empty bin holds all n rows.
            const int64_t* h = hs[p];
            bool constant = false;
            for (int d = 0; d < 65536; ++d) {
                if (h[d] == n) { constant = true; break; }
                if (h[d] != 0) break;
            }
            if (!constant) {
                radix_pass_u64(W, 16 * p, h, a, b, n, offs.data());
                std::swap(a, b);
            }
        }
    }
    return a;
}

}  // namespace

extern "C" {

// Stable (bucket, key-words) sort permutation — the index build's host
// lane. `words` are big-endian-significant packed uint64 sort lanes
// (words[0] most significant); rows sort ascending by
// (bucket, words[0], ..., words[n_words-1]), ties keeping input order.
// LSD: radix each word least-significant-first (16-bit digits, constant
// digits skipped via the histogram), then one stable counting pass by
// bucket. Outputs the int32 permutation plus per-bucket [start, end)
// bounds. No device link traffic — this replaces a ~perm-sized D2H
// transfer plus a host lexsort (the round-4 review's rung-1 residual).
void bucket_key_sort_perm(const int32_t* bucket_ids, int64_t n,
                          int64_t num_buckets,
                          const uint64_t* const* words, int32_t n_words,
                          int32_t* perm, int64_t* starts, int64_t* ends) {
    if (n <= 0) {
        for (int64_t d = 0; d < num_buckets; ++d) starts[d] = ends[d] = 0;
        return;
    }
    std::vector<int32_t> cur(n), tmp(n);
    for (int64_t i = 0; i < n; ++i) cur[i] = static_cast<int32_t>(i);
    int32_t* a = radix_words_lsd(words, n_words, n, cur.data(), tmp.data());
    // Final stable counting pass by bucket id; writes land directly in
    // `perm` when the parity works out, else through tmp.
    std::vector<int64_t> boffs(num_buckets, 0);
    for (int64_t i = 0; i < n; ++i) ++boffs[bucket_ids[i]];
    int64_t run = 0;
    for (int64_t d = 0; d < num_buckets; ++d) {
        starts[d] = run;
        run += boffs[d];
        ends[d] = run;
        boffs[d] = starts[d];
    }
    for (int64_t i = 0; i < n; ++i) {
        const int32_t r = a[i];
        perm[boffs[bucket_ids[r]]++] = r;
    }
}

// Plain (no-bucket) stable key-words sort permutation — the entry the
// host ORDER BY and group-encode lanes use. Skips the bucket counting
// pass entirely (a memcpy of the final buffer replaces it), and lets
// the Python side skip allocating an O(n) all-zeros bucket-id array.
void key_sort_perm_u64(int64_t n, const uint64_t* const* words,
                       int32_t n_words, int32_t* perm) {
    if (n <= 0) return;
    std::vector<int32_t> cur(n), tmp(n);
    for (int64_t i = 0; i < n; ++i) cur[i] = static_cast<int32_t>(i);
    int32_t* a = radix_words_lsd(words, n_words, n, cur.data(), tmp.data());
    std::memcpy(perm, a, static_cast<size_t>(n) * sizeof(int32_t));
}

}  // extern "C"

extern "C" {

// FNV-1a 64-bit over each of n strings; identical to the Python
// implementation in io/columnar.py (_string_hash64) — the device bucket
// layout depends on this exact hash.
void fnv1a64_batch_i32(const uint8_t* data, const int32_t* offsets,
                       int64_t n, uint64_t* out) {
    const uint64_t kOffset = 0xCBF29CE484222325ULL;
    const uint64_t kPrime = 0x100000001B3ULL;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h = kOffset;
        for (int32_t j = offsets[i]; j < offsets[i + 1]; ++j) {
            h = (h ^ data[j]) * kPrime;
        }
        out[i] = h;
    }
}

void fnv1a64_batch_i64(const uint8_t* data, const int64_t* offsets,
                       int64_t n, uint64_t* out) {
    const uint64_t kOffset = 0xCBF29CE484222325ULL;
    const uint64_t kPrime = 0x100000001B3ULL;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h = kOffset;
        for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
            h = (h ^ data[j]) * kPrime;
        }
        out[i] = h;
    }
}

}  // extern "C"
