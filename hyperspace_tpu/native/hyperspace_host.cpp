// Native host glue for hyperspace_tpu.
//
// The reference delegates host-side heavy lifting to Spark's JVM engine;
// this framework's host path is Python + pyarrow, with the per-value
// dictionary hashing (the one O(values * bytes) pure-Python loop) done
// here. Exposed via a plain C ABI and loaded with ctypes — no pybind11
// dependency.
//
// Functions operate on Arrow string-array layout: a contiguous UTF-8 data
// buffer plus (n+1) int offsets.

#include <cstdint>
#include <cstring>

extern "C" {

// FNV-1a 64-bit over each of n strings; identical to the Python
// implementation in io/columnar.py (_string_hash64) — the device bucket
// layout depends on this exact hash.
void fnv1a64_batch_i32(const uint8_t* data, const int32_t* offsets,
                       int64_t n, uint64_t* out) {
    const uint64_t kOffset = 0xCBF29CE484222325ULL;
    const uint64_t kPrime = 0x100000001B3ULL;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h = kOffset;
        for (int32_t j = offsets[i]; j < offsets[i + 1]; ++j) {
            h = (h ^ data[j]) * kPrime;
        }
        out[i] = h;
    }
}

void fnv1a64_batch_i64(const uint8_t* data, const int64_t* offsets,
                       int64_t n, uint64_t* out) {
    const uint64_t kOffset = 0xCBF29CE484222325ULL;
    const uint64_t kPrime = 0x100000001B3ULL;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h = kOffset;
        for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
            h = (h ^ data[j]) * kPrime;
        }
        out[i] = h;
    }
}

}  // extern "C"
