// Native host glue for hyperspace_tpu.
//
// The reference delegates host-side heavy lifting to Spark's JVM engine;
// this framework's host path is Python + pyarrow, with the per-value
// dictionary hashing (the one O(values * bytes) pure-Python loop) done
// here. Exposed via a plain C ABI and loaded with ctypes — no pybind11
// dependency.
//
// Functions operate on Arrow string-array layout: a contiguous UTF-8 data
// buffer plus (n+1) int offsets.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Per-bucket sorted merge join over int64 keys laid out bucket-major
// (both sides sorted within each bucket — the covering-index layout).
// Classic run-merge: for each run of equal left keys, bracket the equal
// right run once; inner emits the cross product, left_outer emits one
// (i, -1) row per unmatched left row.

struct JoinInputs {
    const int64_t* lk;
    const int64_t* rk;
    const int64_t* lb;  // B+1 cumulative left bucket bounds
    const int64_t* rb;  // B+1 cumulative right bucket bounds
    int left_outer;
};

void count_range(const JoinInputs& in, int64_t b0, int64_t b1,
                 int64_t* counts) {
    for (int64_t b = b0; b < b1; ++b) {
        int64_t i = in.lb[b], le = in.lb[b + 1];
        int64_t j = in.rb[b], re = in.rb[b + 1];
        int64_t cnt = 0;
        while (i < le) {
            const int64_t k = in.lk[i];
            while (j < re && in.rk[j] < k) ++j;
            int64_t j2 = j;
            while (j2 < re && in.rk[j2] == k) ++j2;
            int64_t i2 = i;
            while (i2 < le && in.lk[i2] == k) ++i2;
            const int64_t m = j2 - j;
            cnt += m ? m * (i2 - i) : (in.left_outer ? (i2 - i) : 0);
            i = i2;
            j = j2;
        }
        counts[b] = cnt;
    }
}

void fill_range(const JoinInputs& in, int64_t b0, int64_t b1,
                const int64_t* offsets, int32_t* li, int32_t* ri) {
    for (int64_t b = b0; b < b1; ++b) {
        int64_t i = in.lb[b], le = in.lb[b + 1];
        int64_t j = in.rb[b], re = in.rb[b + 1];
        int64_t o = offsets[b];
        while (i < le) {
            const int64_t k = in.lk[i];
            while (j < re && in.rk[j] < k) ++j;
            int64_t j2 = j;
            while (j2 < re && in.rk[j2] == k) ++j2;
            int64_t i2 = i;
            while (i2 < le && in.lk[i2] == k) ++i2;
            if (j2 > j) {
                for (int64_t a = i; a < i2; ++a) {
                    for (int64_t c = j; c < j2; ++c) {
                        li[o] = static_cast<int32_t>(a);
                        ri[o] = static_cast<int32_t>(c);
                        ++o;
                    }
                }
            } else if (in.left_outer) {
                for (int64_t a = i; a < i2; ++a) {
                    li[o] = static_cast<int32_t>(a);
                    ri[o] = -1;
                    ++o;
                }
            }
            i = i2;
            j = j2;
        }
    }
}

// Contiguous bucket ranges balanced by left-row mass.
std::vector<int64_t> split_buckets(const int64_t* lb, int64_t B,
                                   int n_threads) {
    std::vector<int64_t> cuts;
    cuts.push_back(0);
    const int64_t total = lb[B];
    for (int t = 1; t < n_threads; ++t) {
        const int64_t want = total * t / n_threads;
        int64_t b = cuts.back();
        while (b < B && lb[b] < want) ++b;
        cuts.push_back(b);
    }
    cuts.push_back(B);
    return cuts;
}

template <typename Fn>
void run_threaded(const int64_t* lb, int64_t B, int n_threads, Fn fn) {
    if (n_threads <= 1 || B <= 1) {
        fn(0, B);
        return;
    }
    auto cuts = split_buckets(lb, B, n_threads);
    std::vector<std::thread> workers;
    for (size_t t = 0; t + 1 < cuts.size(); ++t) {
        if (cuts[t + 1] > cuts[t]) {
            workers.emplace_back(fn, cuts[t], cuts[t + 1]);
        }
    }
    for (auto& w : workers) w.join();
}

}  // namespace

extern "C" {

void bucketed_merge_join_count_i64(const int64_t* lk, const int64_t* rk,
                                   const int64_t* lb, const int64_t* rb,
                                   int64_t B, int left_outer,
                                   int n_threads, int64_t* counts) {
    JoinInputs in{lk, rk, lb, rb, left_outer};
    run_threaded(lb, B, n_threads, [&](int64_t b0, int64_t b1) {
        count_range(in, b0, b1, counts);
    });
}

void bucketed_merge_join_fill_i64(const int64_t* lk, const int64_t* rk,
                                  const int64_t* lb, const int64_t* rb,
                                  int64_t B, int left_outer, int n_threads,
                                  const int64_t* offsets, int32_t* li,
                                  int32_t* ri) {
    JoinInputs in{lk, rk, lb, rb, left_outer};
    run_threaded(lb, B, n_threads, [&](int64_t b0, int64_t b1) {
        fill_range(in, b0, b1, offsets, li, ri);
    });
}

}  // extern "C"

extern "C" {

// FNV-1a 64-bit over each of n strings; identical to the Python
// implementation in io/columnar.py (_string_hash64) — the device bucket
// layout depends on this exact hash.
void fnv1a64_batch_i32(const uint8_t* data, const int32_t* offsets,
                       int64_t n, uint64_t* out) {
    const uint64_t kOffset = 0xCBF29CE484222325ULL;
    const uint64_t kPrime = 0x100000001B3ULL;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h = kOffset;
        for (int32_t j = offsets[i]; j < offsets[i + 1]; ++j) {
            h = (h ^ data[j]) * kPrime;
        }
        out[i] = h;
    }
}

void fnv1a64_batch_i64(const uint8_t* data, const int64_t* offsets,
                       int64_t n, uint64_t* out) {
    const uint64_t kOffset = 0xCBF29CE484222325ULL;
    const uint64_t kPrime = 0x100000001B3ULL;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h = kOffset;
        for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
            h = (h ^ data[j]) * kPrime;
        }
        out[i] = h;
    }
}

}  // extern "C"
