"""Loader for the native host library (ctypes, no pybind11).

The shared library is built from `hyperspace_host.cpp` on first use (g++ is
part of the toolchain); every native entry point has a pure-Python fallback,
so a missing compiler only costs performance, never correctness.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
# ABI version in the filename: a .so built from older sources simply
# never matches the load path (no in-place overwrite of a possibly
# mmapped stale library, no dlopen returning the cached stale handle).
_ABI_VERSION = 4
_SO_PATH = os.path.join(_HERE, f"libhyperspace_host_v{_ABI_VERSION}.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build() -> bool:
    src = os.path.join(_HERE, "hyperspace_host.cpp")
    try:
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
             "-o", _SO_PATH, src],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception as exc:
        logger.warning("Native host library build failed (falling back to "
                       "Python): %s", exc)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if not os.path.exists(_SO_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
            for suffix, off_t in (("i32", ctypes.c_int32),
                                  ("i64", ctypes.c_int64)):
                fn = getattr(lib, f"fnv1a64_batch_{suffix}")
                fn.restype = None
                fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_int64, ctypes.c_void_p]
            lib.bucketed_merge_join_count_i64.restype = None
            lib.bucketed_merge_join_count_i64.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
                ctypes.c_int, ctypes.c_void_p]
            lib.bucketed_merge_join_fill_i64.restype = None
            lib.bucketed_merge_join_fill_i64.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
                ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p]
            lib.bucket_key_sort_perm.restype = None
            lib.bucket_key_sort_perm.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p]
            lib.key_sort_perm_u64.restype = None
            lib.key_sort_perm_u64.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32,
                ctypes.c_void_p]
            _lib = lib
        except (OSError, AttributeError) as exc:
            # AttributeError = missing symbol (a hand-built .so from other
            # sources at the versioned path): fall back to numpy.
            logger.warning("Native host library load failed: %s", exc)
        return _lib


def arrow_string_hash64(arr) -> Optional["numpy.ndarray"]:
    """FNV-1a 64 over each element of an Arrow string array, operating
    directly on its packed offset/data buffers (zero per-value Python).
    Returns None if the library is unavailable or the array has nulls."""
    import numpy as np
    import pyarrow as pa

    lib = get_lib()
    if lib is None:
        return None
    if hasattr(arr, "combine_chunks"):
        arr = arr.combine_chunks()
    if arr.null_count:
        return None
    large = pa.types.is_large_string(arr.type)
    buffers = arr.buffers()  # [validity, offsets, data]
    offsets_buf, data_buf = buffers[1], buffers[2]
    off_dtype = np.int64 if large else np.int32
    # Offset values index the shared data buffer absolutely, so a sliced
    # array only shifts where we START reading the offsets buffer.
    offsets = np.frombuffer(offsets_buf, dtype=off_dtype, count=len(arr) + 1,
                            offset=arr.offset * np.dtype(off_dtype).itemsize)
    out = np.empty(len(arr), dtype=np.uint64)
    data_ptr = data_buf.address if data_buf is not None else 0
    fn = lib.fnv1a64_batch_i64 if large else lib.fnv1a64_batch_i32
    fn(ctypes.c_void_p(data_ptr),
       offsets.ctypes.data_as(ctypes.c_void_p),
       ctypes.c_int64(len(arr)),
       out.ctypes.data_as(ctypes.c_void_p))
    return out


def string_hash64(values) -> Optional["numpy.ndarray"]:
    """FNV-1a 64 over a numpy array of strings (U-dtype fast path avoids
    per-value Python objects). None when the native library is missing."""
    import numpy as np
    import pyarrow as pa

    if get_lib() is None:
        return None
    values = np.asarray(values)
    if values.dtype.kind != "U":
        values = values.astype(object)
    return arrow_string_hash64(pa.array(values, type=pa.string()))


def pack_sort_words(lanes):
    """Pack order-preserving uint32 sort lanes (most significant first)
    into uint64 words for `bucket_key_sort_perm`. Accepts the lane dtypes
    `ops/keys.host_column_sort_lanes` produces: bool validity (False =
    null sorts first), signed int32 (biased to order-equivalent uint32),
    and uint32. Returns a list of C-contiguous uint64 arrays, or None when
    a lane's dtype can't be mapped (caller falls back to np.lexsort)."""
    import numpy as np

    u32 = []
    for lane in lanes:
        lane = np.asarray(lane)
        if lane.dtype == np.bool_:
            u32.append(lane.astype(np.uint32))
        elif lane.dtype == np.int32:
            u32.append(lane.view(np.uint32) ^ np.uint32(0x80000000))
        elif lane.dtype == np.uint32:
            u32.append(lane)
        elif lane.dtype in (np.int8, np.int16):
            u32.append(lane.astype(np.int32).view(np.uint32)
                       ^ np.uint32(0x80000000))
        else:
            return None
    if len(u32) % 2:
        u32.insert(0, None)  # zero-pad the most significant word's hi lane
    words = []
    for hi, lo in zip(u32[0::2], u32[1::2]):
        w = lo.astype(np.uint64)
        if hi is not None:
            w |= hi.astype(np.uint64) << np.uint64(32)
        words.append(np.ascontiguousarray(w))
    return words


def key_sort_perm(n: int, lanes):
    """Stable ascending sort permutation over `lanes` alone (no bucket
    grouping) via the native radix — the plain-sort entry the host sort
    and group-encode lanes share. Calls the dedicated no-bucket kernel:
    no O(n) dummy bucket-id allocation, no final counting pass. Returns
    an int32 permutation or None (library unavailable, unsupported lane
    dtype, or n >= 2^31)."""
    import numpy as np

    lib = get_lib()
    if lib is None:
        return None
    if n >= 1 << 31:
        return None  # int32 permutation indices would wrap
    words = pack_sort_words(lanes)
    if words is None:
        return None
    perm = np.empty(n, dtype=np.int32)
    word_ptrs = (ctypes.c_void_p * len(words))(
        *[w.ctypes.data_as(ctypes.c_void_p).value for w in words])
    lib.key_sort_perm_u64(ctypes.c_int64(n), word_ptrs,
                          ctypes.c_int32(len(words)),
                          perm.ctypes.data_as(ctypes.c_void_p))
    return perm


def bucket_key_sort_perm(bucket_ids, num_buckets: int, lanes):
    """Stable (bucket, *lanes) ascending sort permutation + per-bucket
    bounds via the native radix sort — the index build's host lane.
    Returns (perm int32, starts int64, ends int64) or None when the
    library is unavailable or a lane dtype is unsupported."""
    import numpy as np

    lib = get_lib()
    if lib is None:
        return None
    bucket_ids = np.ascontiguousarray(bucket_ids, dtype=np.int32)
    n = len(bucket_ids)
    if n >= 1 << 31:
        # int32 permutation indices would wrap; callers fall back to the
        # lexsort/device lanes, which carry int64 permutations.
        return None
    words = pack_sort_words(lanes)
    if words is None:
        return None
    perm = np.empty(n, dtype=np.int32)
    starts = np.empty(num_buckets, dtype=np.int64)
    ends = np.empty(num_buckets, dtype=np.int64)
    word_ptrs = (ctypes.c_void_p * len(words))(
        *[w.ctypes.data_as(ctypes.c_void_p).value for w in words])
    lib.bucket_key_sort_perm(
        bucket_ids.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(n),
        ctypes.c_int64(num_buckets), word_ptrs, ctypes.c_int32(len(words)),
        perm.ctypes.data_as(ctypes.c_void_p),
        starts.ctypes.data_as(ctypes.c_void_p),
        ends.ctypes.data_as(ctypes.c_void_p))
    return perm, starts, ends


def bucketed_merge_join_i64(lkey, rkey, lbounds, rbounds,
                            left_outer: bool = False):
    """Multithreaded per-bucket sorted merge join over int64 keys in the
    bucket-major index layout. `lbounds`/`rbounds` are the B+1 cumulative
    bucket boundaries; both sides must be sorted within each bucket.
    Returns (li, ri) int32 row-index pairs (ri -1 for unmatched left rows
    under left_outer), or None when the native library is unavailable —
    callers fall back to the numpy path (`ops/join.py`)."""
    import numpy as np

    lib = get_lib()
    if lib is None:
        return None
    lkey = np.ascontiguousarray(lkey, dtype=np.int64)
    rkey = np.ascontiguousarray(rkey, dtype=np.int64)
    lbounds = np.ascontiguousarray(lbounds, dtype=np.int64)
    rbounds = np.ascontiguousarray(rbounds, dtype=np.int64)
    B = len(lbounds) - 1
    n_threads = min(os.cpu_count() or 1, 16)
    counts = np.zeros(B, dtype=np.int64)

    def p(a):
        return a.ctypes.data_as(ctypes.c_void_p)

    lib.bucketed_merge_join_count_i64(
        p(lkey), p(rkey), p(lbounds), p(rbounds), ctypes.c_int64(B),
        ctypes.c_int(1 if left_outer else 0), ctypes.c_int(n_threads),
        p(counts))
    offsets = np.zeros(B, dtype=np.int64)
    if B > 1:
        np.cumsum(counts[:-1], out=offsets[1:])
    total = int(counts.sum())
    li = np.empty(total, dtype=np.int32)
    ri = np.empty(total, dtype=np.int32)
    if total:
        lib.bucketed_merge_join_fill_i64(
            p(lkey), p(rkey), p(lbounds), p(rbounds), ctypes.c_int64(B),
            ctypes.c_int(1 if left_outer else 0), ctypes.c_int(n_threads),
            p(offsets), p(li), p(ri))
    return li, ri
