"""hyperspace_tpu — a TPU-native covering-index subsystem for lake data.

A ground-up rebuild of the capabilities of Microsoft Hyperspace (reference
surveyed in SURVEY.md): users create covering indexes — bucketed, sorted,
columnar copies of selected columns — over Parquet files, with all index data
and metadata stored on the lake behind an optimistic-concurrency operation
log, and a rewrite layer that transparently redirects filter and equi-join
queries to the indexes. The control plane is Python; the data plane is
jax/XLA/Pallas over a TPU device mesh.
"""

__version__ = "0.1.0"

from hyperspace_tpu.exceptions import (HyperspaceException,
                                       IndexDataUnavailableError)
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.index.index_config import (DataSkippingIndexConfig,
                                               IndexConfig)

_LAZY = {
    "Hyperspace": ("hyperspace_tpu.facade", "Hyperspace"),
    "HyperspaceSession": ("hyperspace_tpu.engine.session", "HyperspaceSession"),
    "DataFrame": ("hyperspace_tpu.engine.dataframe", "DataFrame"),
    "col": ("hyperspace_tpu.plan.expr", "col"),
    "lit": ("hyperspace_tpu.plan.expr", "lit"),
    # the observability surface: `hs.telemetry.enable_tracing()`,
    # `hs.telemetry.export_trace(path)`, `hs.telemetry.get_registry()`
    "telemetry": ("hyperspace_tpu.telemetry", None),
}


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module 'hyperspace_tpu' has no attribute {name!r}")
    import importlib
    module = importlib.import_module(target[0])
    value = getattr(module, target[1]) if target[1] is not None else module
    globals()[name] = value
    return value


__all__ = ["HyperspaceException", "IndexDataUnavailableError",
           "HyperspaceConf", "IndexConfig", "DataSkippingIndexConfig",
           "Hyperspace", "HyperspaceSession", "DataFrame", "col", "lit",
           "telemetry", "__version__"]
