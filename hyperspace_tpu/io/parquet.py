"""Parquet IO, including the bucketed index-data layout.

Layout parity with the reference's bucketed write
(`index/DataFrameWriterExtensions.scala:49-78`): one parquet file (set) per
bucket, hash-partitioned by the indexed columns and sorted within buckets.
Bucket id is encoded in the file name (`part-<bucket 5 digits>.parquet`) —
the read side maps file -> bucket from the name, like Spark's bucketed
tables — and a `_bucket_spec.json` sidecar makes index data dirs
self-describing.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Sequence

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.utils import storage
from hyperspace_tpu.plan.nodes import BucketSpec
from hyperspace_tpu.plan.schema import Schema

BUCKET_FILE_RE = re.compile(r"part-(\d{5})(?:-[A-Za-z0-9]+)?\.parquet$")
BUCKET_SPEC_FILE = "_bucket_spec.json"

# Version of THE bucket hash identity (`ops/hash_partition` + float-lane
# normalization in `ops/keys.py`). Bumped whenever the row -> bucket map
# of existing layouts would change (v2: -0.0/NaN float normalization). A
# data dir written under a different version reports no bucket spec, so
# readers treat it as unbucketed (correct, just unaccelerated) instead of
# silently mis-bucketing point lookups and co-partitioned joins.
BUCKET_HASH_VERSION = 2


def bucket_file_name(bucket: int, suffix: Optional[str] = None) -> str:
    tag = f"-{suffix}" if suffix else ""
    return f"part-{bucket:05d}{tag}.parquet"


def bucket_of_file(path: str) -> Optional[int]:
    m = BUCKET_FILE_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def _read_one(path: str, cols):
    import pyarrow.parquet as pq

    from hyperspace_tpu.utils import faults, retry

    # partitioning=None: the index layout's `v__=N` version directories
    # LOOK like hive partitions, and newer pyarrow infers a synthetic
    # `v__` dictionary column from the path (even for single-file
    # reads) — which is not data, collides with files that were written
    # while such inference was active, and must never enter a batch.
    def read():
        faults.fire("parquet.read", path)
        if storage.is_url(path):
            fs, real = storage.get_fs(path)
            return pq.read_table(real, columns=cols, filesystem=fs,
                                 partitioning=None)
        return pq.read_table(path, columns=cols, partitioning=None)

    # Transient storage failures (connection resets, 5xx from object
    # stores) retry per the io.retry policy; a corrupt file or missing
    # path is permanent and raises through (index scans convert it into
    # graceful degradation upstream).
    return retry.call(read, operation=f"parquet.read:{path}")


# Decoded-read cache: query trees that reference the same relation more
# than once (q64 joins a year-over-year aggregate to itself, so every
# underlying index is read twice) would otherwise re-decode identical
# parquet bytes. Entries are keyed on (files, columns) and VALIDATED by
# each file's (size, mtime) captured at read time — a refreshed or
# rewritten file misses. LRU-bounded by decoded bytes.
READ_CACHE_BYTES = int(os.environ.get(
    "HYPERSPACE_READ_CACHE_BYTES", 256 * 1024 * 1024))
import threading  # noqa: E402
from collections import OrderedDict as _OrderedDict  # noqa: E402
_read_cache: "_OrderedDict" = _OrderedDict()
# The bucketed join reads its two sides concurrently; all cache map
# mutations (touch, insert, evict) take this lock. File reads and decode
# run outside it.
_read_cache_lock = threading.Lock()

# ONE shared IO executor for concurrent per-file reads and footer
# fetches (lazily created): the previous per-call
# ThreadPoolExecutor(8) spun up and tore down 8 threads on EVERY
# multi-file read — per-query thread churn on the hot scan path.
# Tasks never submit sub-tasks, so sharing cannot deadlock.
_io_pool = None
_io_pool_lock = threading.Lock()


def io_executor():
    global _io_pool
    if _io_pool is None:
        with _io_pool_lock:
            if _io_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                _io_pool = ThreadPoolExecutor(max_workers=8,
                                              thread_name_prefix="hs-io")
    return _io_pool


def shutdown_io_executor(wait: bool = True) -> None:
    """Tear the shared IO pool down (idempotent; lazily re-created by
    the next `io_executor()` call, so tests survive a mid-run
    shutdown). Registered atexit: before this, interpreter teardown
    left 8 idle `hs-io` threads to be reaped by the futures module's
    own exit hook with any queued work's ordering unobserved — now the
    pool drains deterministically."""
    global _io_pool
    with _io_pool_lock:
        pool, _io_pool = _io_pool, None
    if pool is not None:
        pool.shutdown(wait=wait)


import atexit as _atexit  # noqa: E402

_atexit.register(shutdown_io_executor)


def _file_stamp(path: str):
    """(size, mtime) of a FILE, or None when the path is a directory or
    the backend exposes no modification time — both must disable caching
    (a directory's own stamp does not change when a member file is
    rewritten in place; without mtime a same-size rewrite would collide)."""
    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        info = fs.info(real)
        if (info.get("type") == "directory") or fs.isdir(real):
            return None
        mtime = (info.get("mtime") or info.get("updated")
                 or info.get("last_modified") or info.get("LastModified")
                 or info.get("created"))
        if not mtime:
            return None
        return (info.get("size", 0) or 0, str(mtime))
    st = os.stat(path)
    import stat as _stat
    if _stat.S_ISDIR(st.st_mode):
        return None
    return (st.st_size, st.st_mtime_ns)


def _stamps(paths: Sequence[str]):
    """Tuple of per-file stamps, or None when any file is unstampable
    (directory, no mtime, stat failure) — which disables caching."""
    try:
        stamps = tuple(_file_stamp(p) for p in paths)
    except OSError:
        return None
    return None if any(st is None for st in stamps) else stamps


def clear_read_cache() -> None:
    with _read_cache_lock:
        _read_cache.clear()
    _count_cache.clear()
    clear_batch_cache()
    clear_device_cache()


def invalidate_paths(prefix: str) -> None:
    """Drop every host-cache entry (read / decoded-batch / footer-count)
    whose key touches a path under `prefix` — the index-FSM
    invalidation hook (`io/segcache.py`). Stamp validation alone cannot
    close the mid-commit window: a racing query can stat, validate, and
    serve bytes the committing action is replacing; an explicit sweep
    at the commit boundary can."""
    prefix = prefix.rstrip("/\\")

    def under(path: str) -> bool:
        return path == prefix or path.startswith(prefix + "/") \
            or path.startswith(prefix + os.sep)

    with _read_cache_lock:
        for key in [k for k in _read_cache if any(under(p)
                                                  for p in k[0])]:
            del _read_cache[key]
    with _batch_cache_lock:
        for key in [k for k in _batch_cache if any(under(p)
                                                   for p in k[0])]:
            del _batch_cache[key]
    for path in [p for p in _count_cache if under(p)]:
        _count_cache.pop(path, None)


def read_table(paths: Sequence[str], columns: Optional[Sequence[str]] = None):
    """Read one or more parquet files/dirs into a single Arrow table, in
    path order. Files are read concurrently (pyarrow releases the GIL);
    order is preserved by the map. `scheme://` paths read through their
    fsspec filesystem. Results are served from the stamped read cache
    when every file is unchanged."""
    import pyarrow as pa

    from hyperspace_tpu.telemetry import memory as _mem

    if not paths:
        raise HyperspaceException("No parquet inputs to read.")
    cols = list(columns) if columns else None
    key = (tuple(paths), tuple(cols) if cols else None)
    stamps = _stamps(paths)
    if stamps is not None and READ_CACHE_BYTES > 0:
        with _read_cache_lock:
            hit = _read_cache.get(key)
            if hit is not None and hit[0] == stamps:
                _read_cache.move_to_end(key)  # LRU touch
                _mem.cache_hit("parquet_read")
                return hit[1]
    _mem.cache_miss("parquet_read")

    if len(paths) == 1:
        table = _read_one(paths[0], cols)
    else:
        tables = list(io_executor().map(lambda p: _read_one(p, cols),
                                        paths))
        table = pa.concat_tables(tables, promote_options="default")

    if stamps is not None and READ_CACHE_BYTES > 0:
        # Re-stat after the read: a file rewritten DURING the read would
        # otherwise cache new (or torn, for multi-file concat) bytes under
        # the old stamp, and the stale entry would keep validating until
        # the file changed again. Insert only when nothing moved.
        if _stamps(paths) != stamps:
            return table
        with _read_cache_lock:
            _read_cache[key] = (stamps, table)
            total = sum(t.nbytes for _, t in _read_cache.values())
            evictions = 0
            while total > READ_CACHE_BYTES and len(_read_cache) > 1:
                _, (_, evicted) = _read_cache.popitem(last=False)
                total -= evicted.nbytes
                evictions += 1
            entries = len(_read_cache)
        _mem.cache_eviction("parquet_read", evictions)
        _mem.cache_stats("parquet_read", total, entries)
    return table


_count_cache: dict = {}


def file_row_counts(paths: Sequence[str]) -> List[int]:
    """Per-file row counts from parquet footers (no data read); stamped
    per-file cache (index data files are immutable, and the bucketed read
    path asks for the same footers on every warm query)."""
    import pyarrow.parquet as pq

    def meta_rows(p):
        try:
            stamp = _file_stamp(p)
        except OSError:
            stamp = None
        if stamp is not None:
            hit = _count_cache.get(p)
            if hit is not None and hit[0] == stamp:
                return hit[1]
        if storage.is_url(p):
            fs, real = storage.get_fs(p)
            with fs.open(real, "rb") as f:
                rows = pq.read_metadata(f).num_rows
        else:
            rows = pq.read_metadata(p).num_rows
        if stamp is not None:
            if len(_count_cache) > 65536:
                _count_cache.clear()
            _count_cache[p] = (stamp, rows)
        return rows

    if len(paths) <= 1:
        return [meta_rows(p) for p in paths]
    return list(io_executor().map(meta_rows, paths))


# Decoded host-batch cache: the read cache (above) keeps Arrow bytes, but
# a warm query still re-derives numpy-backed ColumnBatches from them every
# execution (~50 ms at 4M rows). Batches are immutable downstream (every
# operator gathers into new arrays), and the numpy columns mostly alias
# the cached Arrow buffers, so caching the decoded form costs little extra
# memory. Same stamp validation as the read cache.
_batch_cache: "_OrderedDict" = _OrderedDict()
_batch_cache_lock = threading.Lock()


def clear_batch_cache() -> None:
    with _batch_cache_lock:
        _batch_cache.clear()


def _stamped_batch_read(paths: Sequence[str],
                        columns: Optional[Sequence[str]], schema,
                        cache: "_OrderedDict", lock, budget: int):
    """Stamped-LRU read for the HOST decoded-batch cache: get with
    stamp validation, decode on miss, insert with re-stat (a file
    rewritten during the read must not cache under the old stamp),
    evict LRU entries until within budget. Hit/miss/eviction/bytes-held
    series land as `cache.host_batch.*`. (The DEVICE lane lives in
    `io/segcache.py` — version-keyed HBM residency, single-flight
    fills, index-FSM invalidation.)"""
    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.telemetry import memory as _mem

    name = "host_batch"
    key = (tuple(paths), tuple(columns) if columns is not None else None,
           schema.to_json() if schema is not None else None)
    # Enforce the effective budget on ENTRY, not only on insert: a budget
    # lowered mid-session (the documented OOM remedy — conf
    # `cache.device.bytes`) must actually release already-resident
    # batches, and budget 0 must empty the cache, or the memory being
    # tuned away stays pinned.
    with lock:
        evictions = 0
        if budget <= 0:
            evictions = len(cache)
            cache.clear()
            total = 0
        else:
            total = sum(b for _, _, b in cache.values())
            while total > budget and cache:
                _, (_, _, evicted) = cache.popitem(last=False)
                total -= evicted
                evictions += 1
        entries = len(cache)
    _mem.cache_eviction(name, evictions)
    _mem.cache_stats(name, total, entries)
    stamps = _stamps(paths)
    if stamps is not None and budget > 0:
        with lock:
            hit = cache.get(key)
            if hit is not None and hit[0] == stamps:
                cache.move_to_end(key)
                _mem.cache_hit(name)
                return hit[1]
            if hit is not None:
                del cache[key]
    _mem.cache_miss(name)
    table = read_table(paths, columns=columns)
    batch = columnar.from_arrow(table, schema, device=False)
    if stamps is not None and budget > 0:
        if _stamps(paths) != stamps:
            return batch
        nbytes = _batch_nbytes(batch)
        if nbytes <= budget:
            with lock:
                cache[key] = (stamps, batch, nbytes)
                total = sum(b for _, _, b in cache.values())
                evictions = 0
                while total > budget and len(cache) > 1:
                    _, (_, _, evicted) = cache.popitem(last=False)
                    total -= evicted
                    evictions += 1
                entries = len(cache)
            _mem.cache_eviction(name, evictions)
            _mem.cache_stats(name, total, entries)
    return batch


def read_host_batch(paths: Sequence[str],
                    columns: Optional[Sequence[str]], schema,
                    budget: Optional[int] = None):
    """Read parquet files into a HOST-lane ColumnBatch through the stamped
    decoded-batch cache. `budget` (session conf) overrides the env-default
    cache bound."""
    return _stamped_batch_read(paths, columns, schema, _batch_cache,
                               _batch_cache_lock,
                               READ_CACHE_BYTES if budget is None else budget)


def clear_device_cache() -> None:
    """Empty the HBM segment cache (`io/segcache.py` owns the device
    lane now; this name survives for the cold-cache callers —
    `clear_read_cache`, bench drivers, tests)."""
    from hyperspace_tpu.io import segcache
    segcache.clear()


def _batch_nbytes(batch) -> int:
    """Approximate resident bytes of a host batch (column payloads +
    validity; dictionaries are shared and small)."""
    total = 0
    for col in batch.columns.values():
        total += getattr(col.data, "nbytes", 0)
        if col.validity is not None:
            total += getattr(col.validity, "nbytes", 0)
    return total


def write_table(table, path: str) -> None:
    """Write an index data file. Numeric columns skip parquet's
    dictionary-encoding attempt, and statistics are disabled for ALL
    columns: index rows are pre-sorted runs, the bucket layout (not page
    stats) prunes reads, and dropping both measured ~3x faster encodes
    with smaller files and ~25% faster reads. String columns keep
    dictionary encoding — they compress well and decode to the same Arrow
    dictionaries the device encoding consumes."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.utils import faults, retry

    string_cols = [f.name for f in table.schema
                   if pa.types.is_string(f.type) or pa.types.is_large_string(f.type)
                   or pa.types.is_dictionary(f.type)]
    kwargs = dict(use_dictionary=string_cols or False,
                  write_statistics=False, compression="snappy")

    def write():
        faults.fire("parquet.write", path)
        if storage.is_url(path):
            fs, real = storage.get_fs(path)
            fs.makedirs(os.path.dirname(real), exist_ok=True)
            pq.write_table(table, real, filesystem=fs, **kwargs)
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        pq.write_table(table, path, **kwargs)

    # A retried attempt rewrites the whole file — safe: version dirs are
    # private to their writing action until the commit marker lands.
    retry.call(write, operation=f"parquet.write:{path}")


def write_bucket_spec(directory: str, spec: BucketSpec, schema: Schema) -> None:
    from hyperspace_tpu.utils import file_utils
    payload = json.dumps({"bucketSpec": spec.to_dict(),
                          "hashVersion": BUCKET_HASH_VERSION,
                          "schema": [fld.to_dict() for fld in schema.fields]},
                         indent=2)
    file_utils.create_file(storage.join(directory, BUCKET_SPEC_FILE), payload)


def read_bucket_spec(directory: str) -> Optional[BucketSpec]:
    from hyperspace_tpu.utils import file_utils
    path = storage.join(directory, BUCKET_SPEC_FILE)
    if not file_utils.exists(path):
        return None
    payload = json.loads(file_utils.read_contents(path))
    if payload.get("hashVersion", 1) != BUCKET_HASH_VERSION:
        # Layout written under a different hash identity: expose it as
        # unbucketed so reads stay correct (no pruning/co-partitioning).
        return None
    return BucketSpec.from_dict(payload["bucketSpec"])


def bucket_map(files: Sequence[str]) -> Dict[int, List[str]]:
    """Group an EXPLICIT file listing by bucket id (files not carrying
    the bucket naming pattern are dropped). The snapshot-pinned scan
    path (`engine/physical.ScanExec._per_bucket_files`) derives bucket
    maps from its plan-time-frozen listing through this instead of
    re-listing the directory at execution."""
    out: Dict[int, List[str]] = {}
    for path in sorted(files, key=os.path.basename):
        bucket = bucket_of_file(path)
        if bucket is not None:
            out.setdefault(bucket, []).append(path)
    return out


def bucket_files(directory: str) -> Dict[int, List[str]]:
    """Map bucket id -> parquet files in a bucketed data dir (empty buckets
    have no files)."""
    out: Dict[int, List[str]] = {}
    from hyperspace_tpu.utils import file_utils
    if not file_utils.is_dir(directory):
        return out
    for name in sorted(storage.listdir_names(directory)):
        bucket = bucket_of_file(name)
        if bucket is not None:
            out.setdefault(bucket, []).append(storage.join(directory, name))
    return out
