"""HBM-resident index segment cache — THE device-residency seam.

BENCH_r05 put the device path's problem in one line: ~0.11s of device
compute against ~1.95s of H2D/D2H. Under serving traffic every query
re-paid parquet decode + H2D for the same hot index shards; the paper's
premise is that a covering index is a *reusable* derived dataset
(PAPER.md §3 — read many times per build), and on a TPU the analog of
Spark's distributed page cache is HBM residency. This module promotes
the stamped device-batch LRU that used to live inside `io/parquet.py`
into a first-class, process-wide, byte-budgeted segment cache that owns
device residency end to end (`scripts/check_metrics_coverage.py` bans
the old `_device_cache`/`read_device_batch` access anywhere else):

- **keying**: a committed index segment is keyed by
  `(index root, v__=N, bucket selector, columns, schema)` — content
  identity, NO per-read stat/stamp validation. Index version dirs are
  immutable once their `_committed` marker lands (PR 4), and the rules
  only ever select committed versions, so a key can never alias two
  byte-states. Version keying is also what gives reads pinned-version
  stability: a refresh committing `v__=N+1` mid-query cannot perturb a
  scan already reading (and caching under) `v__=N`. Non-index device
  scans (source data, hybrid-scan appended files) have no version to
  key on and fall back to the PR-3 `(paths, size+mtime stamp)`
  validation.
- **fills**: misses decode through the stamped host read cache and
  cross the link through the PR-5 `TransferEngine` (chunked, staged,
  budget-shared with live queries' transfers) tagged as the `fill`
  lane, with per-key SINGLE-FLIGHT: N concurrent queries over the same
  cold bucket trigger exactly one decode+H2D — the PR-7 scheduler
  queue is the coalescing point; queued queries whose footprint
  overlaps an in-flight fill wait on the fill (deadline-checkpointed),
  not the link. A fill's projected bytes are RESERVED against the
  budget before the transfer starts (concurrent fills cannot
  collectively blow past it) and released on every exit path —
  cancellation mid-fill included.
- **eviction**: byte-budgeted LRU (the PR-3 machinery), with the PR-3
  accountant's live HBM gauges as a CEILING: when a serving budget
  (`spark.hyperspace.serve.hbm.budget.bytes`) is set, the cache's
  effective budget shrinks by non-cache device residency so the cache
  and the admission controller share one truth about device memory.
  Indexes listed in `spark.hyperspace.cache.segments.pin.indexes` are
  pinned: their segments survive byte pressure (but not invalidation).
- **invalidation**: hooks off the index log FSM, not ad-hoc clears —
  `IndexDataManagerImpl.commit/delete` and the log manager's stable-log
  publish call `on_version_committed` / `on_version_deleted` /
  `on_index_dropped`, which also drop the stamped host caches and the
  footprint size cache for the affected paths (the old mid-commit
  stamp-validation race).

- **host tier (tiered cache)**: with
  `spark.hyperspace.cache.segments.host.bytes` > 0, a `ColumnBatch`
  evicted from the device tier by byte pressure DEMOTES into a
  host-RAM copy (decoded columns fetched D2H once,
  `io/columnar.batch_to_host`) instead of dropping. A later read of
  the demoted key re-promotes through the TransferEngine FILL lane
  (`host_batch_to_device(tag="fill")`) — the H2D cost is paid again,
  the parquet decode is NOT. The host tier is its own byte-budgeted
  LRU; invalidation sweeps both tiers. This is what lets the index
  advisor keep more auto-built indexes warm-ish than HBM alone allows.
- **bucket-scoped invalidation**: an incremental refresh names the
  buckets it actually touched (`on_version_committed(...,
  touched_buckets=, carried_from=)`); entries of the carried-from
  version whose bucket selector provably avoids every touched bucket
  are REKEYED to the new version (the new version hard-links those
  buckets' files byte-for-byte, so content identity holds) instead of
  dropped — the warm set survives an append that only landed in other
  buckets. Selectors whose bucket coverage is unknowable ("all",
  explicit file lists, SPMD range keys) drop conservatively.

Telemetry: `cache.segments.{hits,misses,fills,evictions,bytes_held,
entries,pins}` and `cache.segments.host.{hits,demotions,evictions,
bytes_held,entries}` plus `cache.segments.rekeyed` through the PR-3
helpers (per-query mirrors feed the regression differ's `cache`
bucket), `segcache.fill` spans, and `transfer.fill.*` counters on the
fill lane. Budget knobs: `spark.hyperspace.cache.segments.bytes`
(falls back to the legacy `cache.device.bytes` key, then the
HYPERSPACE_SEGMENT_CACHE_BYTES / HYPERSPACE_DEVICE_CACHE_BYTES env
defaults) and `spark.hyperspace.cache.segments.host.bytes` (0 = host
tier off).
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from hyperspace_tpu import constants

__all__ = ["SegmentCache", "SegmentRef", "get_cache", "set_cache",
           "reset_cache", "clear", "segment_ref_for_scan",
           "on_version_committed", "on_version_deleted",
           "on_index_dropped", "invalidate_source_paths", "read_segment",
           "stats_snapshot"]

# Process-wide default budget (bytes); session conf overrides. The new
# env var wins; the legacy device-cache env keeps old deployments'
# sizing working.
SEGMENT_CACHE_BYTES = int(os.environ.get(
    "HYPERSPACE_SEGMENT_CACHE_BYTES",
    os.environ.get("HYPERSPACE_DEVICE_CACHE_BYTES", 4 * 1024 ** 3)))

# Host-tier default (bytes); 0 = tier off. Session conf
# (`cache.segments.host.bytes`) overrides.
SEGMENT_CACHE_HOST_BYTES = int(os.environ.get(
    "HYPERSPACE_SEGMENT_CACHE_HOST_BYTES", 0))

# Wait quantum for single-flight waiters: short enough that a
# cancelled waiter notices its deadline promptly, long enough not to
# spin (same discipline as the scheduler's queue wait).
_FILL_WAIT_QUANTUM_S = 0.05

_VERSION_DIR_RE = re.compile(
    re.escape(constants.INDEX_VERSION_DIRECTORY_PREFIX) + r"=(\d+)$")


@dataclass(frozen=True)
class SegmentRef:
    """Identity of one cacheable index segment: WHICH committed bytes a
    read covers, independent of how the filesystem is asked for them.
    `bucket` is the bucket selector the read applied — a single bucket
    id, a sorted tuple of pruned bucket ids, or "all"."""

    index_name: str
    index_root: str   # parent of the v__=N dir (warehouse-unique)
    version: int
    bucket: object

    @property
    def key(self) -> tuple:
        return ("seg", self.index_root, self.version, self.bucket)


def segment_ref_for_scan(scan, bucket=None, allowed_buckets=None,
                         bucketed: bool = False) -> Optional[SegmentRef]:
    """SegmentRef for a rule-selected index scan, or None when the read
    is not version-addressable (source-data scans, multi-root scans, a
    root that is not a `v__=N` dir). Only the rules put `index_name` on
    a Scan, and they only ever resolve COMMITTED versions
    (`IndexDataManager.get_latest_version_id`), so a parseable version
    here is a committed one by construction."""
    if not getattr(scan, "index_name", None):
        return None
    roots = list(scan.root_paths)
    if len(roots) != 1:
        return None
    root = roots[0].rstrip("/\\")
    m = _VERSION_DIR_RE.search(os.path.basename(root))
    if m is None:
        return None
    if bucket is not None:
        selector: object = int(bucket)
    elif allowed_buckets is not None:
        selector = ("pruned", tuple(sorted(allowed_buckets)))
    else:
        selector = "all"
    if getattr(scan, "_explicit_files", False):
        # An explicit file list (sketch-pruned reads) restricts WHICH of
        # the version's bytes the read covers — two different survivor
        # sets under one version must not alias one cache entry.
        selector = ("files", selector,
                    tuple(os.path.basename(f) for f in scan.files()))
    if bucketed:
        # The bucket-ordered whole-index read (`execute_bucketed`) and
        # the plain read can concatenate the same files in different
        # orders — distinct layouts, distinct keys.
        selector = ("bucketed", selector)
    return SegmentRef(index_name=scan.index_name,
                      index_root=os.path.dirname(root),
                      version=int(m.group(1)),
                      bucket=selector)


class _Entry:
    __slots__ = ("batch", "nbytes", "ref", "pinned", "stamps")

    def __init__(self, batch, nbytes: int, ref: Optional[SegmentRef],
                 pinned: bool, stamps=None):
        self.batch = batch
        self.nbytes = nbytes
        self.ref = ref
        self.pinned = pinned
        # Per-file (size, mtime) stamps for UNVERSIONED entries; hits
        # revalidate against the live stamps (version-keyed entries are
        # immutable by construction and carry None).
        self.stamps = stamps


class _HostEntry:
    """One host-tier (demoted) segment: the fully-decoded host copy of
    a device batch (`columnar.batch_to_host`), plus the identity it was
    cached under so invalidation reaches it."""

    __slots__ = ("batch", "nbytes", "ref", "stamps")

    def __init__(self, batch, nbytes: int, ref: Optional[SegmentRef],
                 stamps=None):
        self.batch = batch
        self.nbytes = nbytes
        self.ref = ref
        self.stamps = stamps


def _selector_buckets(selector) -> Optional[frozenset]:
    """The exact bucket-id set a cache-key selector covers, or None
    when it is unknowable ("all", explicit file lists, foreign key
    shapes) — the bucket-scoped invalidation's safety question: an
    entry may only survive a touched-bucket commit when its coverage
    PROVABLY avoids every touched bucket."""
    if isinstance(selector, int):
        return frozenset((selector,))
    if isinstance(selector, tuple) and selector:
        if selector[0] == "pruned" and len(selector) == 2:
            try:
                return frozenset(int(b) for b in selector[1])
            except (TypeError, ValueError):
                return None
        if selector[0] == "bucketed" and len(selector) == 2:
            return _selector_buckets(selector[1])
    return None


class _Fill:
    """One in-flight single-flight fill. `event` flips when the filler
    finishes (success or not); waiters read `batch`/`error` after it.
    `doomed` marks a fill whose index was invalidated mid-flight — its
    result is still returned to its waiters (their query pinned that
    version) but never inserted."""

    __slots__ = ("event", "batch", "error", "doomed", "reserved",
                 "index_root")

    def __init__(self, index_root: Optional[str]):
        self.event = threading.Event()
        self.batch = None
        self.error: Optional[BaseException] = None
        self.doomed = False
        self.reserved = 0
        self.index_root = index_root


def _batch_nbytes(batch) -> int:
    """Resident bytes of a ColumnBatch (payload + validity + the device
    halves of string dictionary hashes)."""
    total = 0
    for col in batch.columns.values():
        total += int(getattr(col.data, "nbytes", 0))
        if col.validity is not None:
            total += int(getattr(col.validity, "nbytes", 0))
        if col.dict_hashes is not None:
            for h in col.dict_hashes:
                total += int(getattr(h, "nbytes", 0))
    return total


def _pinned_indexes(conf) -> frozenset:
    if conf is None:
        return frozenset()
    raw = conf.get(constants.SEGMENT_CACHE_PIN_INDEXES) or ""
    return frozenset(n.strip() for n in raw.split(",") if n.strip())


class SegmentCache:
    """Process-wide HBM segment cache (module docstring). All blocking
    happens on caller threads; the cache spawns none of its own."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 host_budget_bytes: Optional[int] = None):
        self._cv = threading.Condition()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._fills: Dict[tuple, _Fill] = {}
        self._bytes_held = 0
        self._reserved = 0
        self._default_budget = (SEGMENT_CACHE_BYTES if budget_bytes is None
                                else int(budget_bytes))
        # Host (demotion) tier: LRU of _HostEntry under its own byte
        # budget. Guarded by the same cv as the device tier — demotion
        # moves an entry between tiers atomically.
        self._host: "OrderedDict[tuple, _HostEntry]" = OrderedDict()
        self._host_bytes = 0
        self._default_host_budget = (
            SEGMENT_CACHE_HOST_BYTES if host_budget_bytes is None
            else int(host_budget_bytes))

    # -- budget math ------------------------------------------------------

    def _configured_budget(self, conf, override: Optional[int]) -> int:
        if override is not None:
            return int(override)
        if conf is not None:
            value = conf.segment_cache_bytes
            if value is not None:
                return int(value)
        return self._default_budget

    def _effective_budget(self, conf, override: Optional[int]) -> int:
        """The configured budget, CAPPED by what the serving budget
        leaves after non-cache device residency — the accountant's live
        gauges are the shared truth between this cache and the
        admission controller (`engine/scheduler.py` derives headroom
        from the same numbers)."""
        budget = self._configured_budget(conf, override)
        serve = conf.serve_hbm_budget_bytes if conf is not None else 0
        if serve and serve > 0:
            try:
                from hyperspace_tpu import telemetry
                live = sum(telemetry.get_accountant().live.values())
            except Exception:
                live = 0
            non_cache = max(0, live - self._bytes_held - self._reserved)
            budget = min(budget, max(0, serve - non_cache))
        return budget

    # -- residency accounting --------------------------------------------

    def _publish_stats(self) -> None:
        # Caller holds the cv lock.
        from hyperspace_tpu.telemetry import memory as _mem
        _mem.cache_stats("segments", self._bytes_held, len(self._entries))
        _mem.cache_stats("segments.host", self._host_bytes,
                         len(self._host))
        from hyperspace_tpu import telemetry
        telemetry.get_registry().gauge("cache.segments.pins").set(
            sum(1 for e in self._entries.values() if e.pinned))

    def _host_budget(self, conf) -> int:
        if conf is not None:
            try:
                return int(conf.segment_cache_host_bytes)
            except Exception:
                pass  # conf-shaped fakes without the property
        return self._default_host_budget

    def _host_insert(self, key: tuple, hent: _HostEntry,
                     host_budget: int) -> int:
        """Insert one demoted entry into the host LRU, evicting host LRU
        victims past the budget. Caller holds the cv lock. Returns host
        evictions."""
        evictions = 0
        if key in self._host:
            self._host_bytes -= self._host.pop(key).nbytes
        while self._host and self._host_bytes + hent.nbytes > host_budget:
            _k, victim = self._host.popitem(last=False)
            self._host_bytes -= victim.nbytes
            evictions += 1
        if hent.nbytes <= host_budget:
            self._host[key] = hent
            self._host_bytes += hent.nbytes
        else:
            evictions += 0  # larger than the whole tier: dropped
        return evictions

    def _demote(self, key: tuple, ent: _Entry, conf) -> bool:
        """Try to move an evicted device entry into the host tier.
        Caller holds the cv lock. Only decoded `ColumnBatch` payloads
        demote (the generic `get_or_fill` payloads — SPMD shard tuples —
        have no host form the promote path could rebuild); anything
        else, and any demotion failure, falls back to the plain drop.
        The D2H fetch runs under the lock — demotion is an eviction-path
        event, not a hot-path one, and on the CPU/virtual backends the
        fetch is a view."""
        from hyperspace_tpu import telemetry
        host_budget = self._host_budget(conf)
        if host_budget <= 0:
            return False
        from hyperspace_tpu.io import columnar
        if not isinstance(ent.batch, columnar.ColumnBatch):
            return False
        try:
            hbatch = columnar.batch_to_host(ent.batch)
        except Exception:
            return False  # a failed demotion is just an eviction
        nbytes = _batch_nbytes(hbatch)
        hent = _HostEntry(hbatch, nbytes, ent.ref, stamps=ent.stamps)
        host_evictions = self._host_insert(key, hent, host_budget)
        reg = telemetry.get_registry()
        reg.counter("cache.segments.host.demotions").inc()
        if host_evictions:
            from hyperspace_tpu.telemetry import memory as _mem
            _mem.cache_eviction("segments.host", host_evictions)
        return key in self._host

    def _host_take(self, key: tuple):
        """Pop the host-tier entry for `key` (promotion consumes it),
        or None. Caller holds the cv lock."""
        hent = self._host.pop(key, None)
        if hent is not None:
            self._host_bytes -= hent.nbytes
        return hent

    def _evict_until(self, need: int, budget: int, conf=None) -> int:
        """Evict unpinned LRU entries until `need` extra bytes fit under
        `budget`, demoting each victim into the host tier when one is
        configured. Caller holds the cv lock. Returns evictions."""
        evictions = 0
        while self._bytes_held + self._reserved + need > budget:
            victim_key = None
            for key, ent in self._entries.items():  # LRU order
                if not ent.pinned:
                    victim_key = key
                    break
            if victim_key is None:
                break  # only pinned residency left
            ent = self._entries.pop(victim_key)
            self._bytes_held -= ent.nbytes
            self._demote(victim_key, ent, conf)
            evictions += 1
        return evictions

    def bytes_held(self) -> int:
        with self._cv:
            return self._bytes_held

    def resident_bytes_for_plan(self, plan) -> int:
        """Bytes already HBM-resident for `plan`'s index scans — the
        admission-control footprint credit (`QueryScheduler` shrinks an
        admitted query's charged bytes by this, so K queries over the
        same hot index do not serially occupy budget as if each
        re-staged the data)."""
        from hyperspace_tpu.plan.nodes import Scan

        roots: set = set()

        def visit(node):
            if isinstance(node, Scan) and getattr(node, "index_name",
                                                  None):
                for r in node.root_paths:
                    root = r.rstrip("/\\")
                    if _VERSION_DIR_RE.search(os.path.basename(root)):
                        roots.add(os.path.dirname(root))
            for c in node.children:
                visit(c)

        try:
            visit(plan)
        except Exception:
            return 0
        if not roots:
            return 0
        with self._cv:
            return sum(e.nbytes for e in self._entries.values()
                       if e.ref is not None and e.ref.index_root in roots)

    # -- the read path ----------------------------------------------------

    def read(self, paths: Sequence[str],
             columns: Optional[Sequence[str]], schema,
             ref: Optional[SegmentRef] = None,
             conf=None, budget: Optional[int] = None):
        """Read parquet `paths` into a DEVICE-resident ColumnBatch
        through the segment cache: a hit skips the parquet decode AND
        the host->device transfer; a miss fills once per key no matter
        how many threads ask (single-flight)."""
        from hyperspace_tpu import telemetry
        from hyperspace_tpu.telemetry import memory as _mem

        cols = tuple(columns) if columns is not None else None
        schema_json = schema.to_json() if schema is not None else None
        stamps = None
        if ref is not None:
            key = ref.key + (cols, schema_json)
        else:
            # Unversioned read: PR-3 stamp validation (size+mtime per
            # file). Unstampable paths are uncacheable.
            from hyperspace_tpu.io import parquet
            stamps = parquet._stamps(paths)
            if stamps is None:
                _mem.cache_miss("segments")
                return self._decode(paths, cols, schema)
            key = ("path", tuple(paths), cols, schema_json)

        while True:
            fill = None
            with self._cv:
                ent = self._entries.get(key)
                if ent is not None:
                    if ent.stamps is not None and ent.stamps != stamps:
                        # Rewritten since caching: stale — drop and
                        # fall through to a fresh fill.
                        self._bytes_held -= ent.nbytes
                        del self._entries[key]
                        self._publish_stats()
                    else:
                        self._entries.move_to_end(key)
                        _mem.cache_hit("segments")
                        return ent.batch
                fill = self._fills.get(key)
                if fill is None:
                    fill = _Fill(ref.index_root if ref is not None
                                 else None)
                    self._fills[key] = fill
                    break
            # Another thread owns the fill: wait on IT, not the link —
            # deadline-checkpointed so a cancelled waiter leaves the
            # queue promptly (the filler keeps going for its own query).
            # The wait is a critical-path source: wall blocked on
            # someone else's fill classifies `cache_fill_wait`.
            t_wait0 = time.perf_counter()
            try:
                while not fill.event.is_set():
                    telemetry.check_deadline("cache.fill")
                    fill.event.wait(_FILL_WAIT_QUANTUM_S)
            finally:
                telemetry.add_seconds("cache.fill_wait_s",
                                      time.perf_counter() - t_wait0)
            if fill.error is None and fill.batch is not None:
                # Coalesced: one decode+H2D served K waiters the SAME
                # batch object (bit-identical by construction).
                _mem.cache_hit("segments")
                telemetry.add_count("cache.segments.coalesced")
                return fill.batch
            # The filler died (fault, cancellation): retry with our own
            # fill — its failure was its query's, not necessarily ours.

        # This thread is the filler.
        _mem.cache_miss("segments")
        reg = telemetry.get_registry()
        try:
            with telemetry.span("segcache.fill", "cache",
                                index=(ref.index_name if ref else None),
                                files=len(paths)):
                reg.counter("cache.segments.fills").inc()
                # Tenant chargeback: the filler's tenant pays for the
                # fill (coalesced waiters ride it free — same contract
                # as the batch lane's leader-pays cohort accounting).
                telemetry.charge_tenant("cache.segments.fills")
                batch, nbytes = self._fill(key, fill, paths, cols,
                                           schema, stamps, ref, conf,
                                           budget)
            fill.batch = batch
            return batch
        except BaseException as exc:
            fill.error = exc
            raise
        finally:
            with self._cv:
                if self._fills.get(key) is fill:
                    del self._fills[key]
                if fill.reserved:
                    self._reserved -= fill.reserved
                    fill.reserved = 0
                self._cv.notify_all()
            fill.event.set()

    def get_or_fill(self, key: tuple, fill_fn, ref: Optional[SegmentRef]
                    = None, conf=None, budget: Optional[int] = None):
        """Generic cached fill under the cache's single-flight + byte-
        budget + LRU + index-FSM-invalidation machinery, for payloads
        the cache does not itself know how to decode — the per-device
        BUCKET-RANGE fills of the born-sharded read path
        (`parallel/spmd.read_sharded`): one committed index version on
        an n-device mesh caches n entries, each holding exactly one
        device's padded bucket-range shard, so each device's HBM holds
        only its range and warm multi-chip reads are link-free per
        device. `fill_fn` runs outside the lock and returns
        (payload, resident_bytes); `ref` ties the entry to the index
        log FSM's invalidation hooks."""
        from hyperspace_tpu import telemetry
        from hyperspace_tpu.telemetry import memory as _mem

        while True:
            fill = None
            with self._cv:
                ent = self._entries.get(key)
                if ent is not None:
                    self._entries.move_to_end(key)
                    _mem.cache_hit("segments")
                    return ent.batch
                fill = self._fills.get(key)
                if fill is None:
                    fill = _Fill(ref.index_root if ref is not None
                                 else None)
                    self._fills[key] = fill
                    break
            t_wait0 = time.perf_counter()
            try:
                while not fill.event.is_set():
                    telemetry.check_deadline("cache.fill")
                    fill.event.wait(_FILL_WAIT_QUANTUM_S)
            finally:
                telemetry.add_seconds("cache.fill_wait_s",
                                      time.perf_counter() - t_wait0)
            if fill.error is None and fill.batch is not None:
                _mem.cache_hit("segments")
                telemetry.add_count("cache.segments.coalesced")
                return fill.batch
            # The filler died; retry with our own fill.

        _mem.cache_miss("segments")
        reg = telemetry.get_registry()
        try:
            with telemetry.span("segcache.fill", "cache",
                                index=(ref.index_name if ref else None)):
                reg.counter("cache.segments.fills").inc()
                telemetry.charge_tenant("cache.segments.fills")
                payload, nbytes = fill_fn()
                budget_eff = self._effective_budget(conf, budget)
                if budget_eff > 0 and nbytes <= budget_eff:
                    with self._cv:
                        if not fill.doomed:
                            evictions = self._evict_until(nbytes,
                                                          budget_eff,
                                                          conf)
                            self._entries[key] = _Entry(
                                payload, nbytes, ref,
                                pinned=(ref is not None
                                        and ref.index_name
                                        in _pinned_indexes(conf)))
                            self._bytes_held += nbytes
                            self._publish_stats()
                            self._cv.notify_all()
                            _mem.cache_eviction("segments", evictions)
            fill.batch = payload
            return payload
        except BaseException as exc:
            fill.error = exc
            raise
        finally:
            with self._cv:
                if self._fills.get(key) is fill:
                    del self._fills[key]
                self._cv.notify_all()
            fill.event.set()

    def _decode(self, paths, cols, schema):
        """Uncached decode+transfer (fill lane, no insert)."""
        from hyperspace_tpu.io import columnar, parquet
        table = parquet.read_table(paths, columns=list(cols) if cols
                                   else None)
        return columnar.from_arrow(table, schema, device=True,
                                   transfer_tag="fill")

    def _promote(self, key, paths, stamps, conf, budget_override):
        """Host-tier promotion: when the missed key has a demoted host
        copy, rebuild the device batch from it through the transfer
        engine's FILL lane — H2D paid, parquet decode skipped. Returns
        (batch, nbytes) or None (no/stale host entry; fall through to
        the real fill). Runs on the filler thread, inside its
        single-flight slot, so concurrent waiters coalesce onto one
        promotion exactly as they would onto one decode."""
        from hyperspace_tpu.io import columnar, parquet
        from hyperspace_tpu.telemetry import memory as _mem

        with self._cv:
            hent = self._host.get(key)
        if hent is None:
            return None
        if hent.stamps is not None and parquet._stamps(paths) != hent.stamps:
            # Unversioned entry demoted before a rewrite: stale.
            with self._cv:
                if self._host.get(key) is hent:
                    self._host_take(key)
                    self._publish_stats()
            return None
        with self._cv:
            if self._host_take(key) is not hent:
                return None  # raced an invalidation sweep
            self._publish_stats()
        batch = columnar.host_batch_to_device(hent.batch,
                                              transfer_tag="fill")
        nbytes = _batch_nbytes(batch)
        # cache_hit mirrors onto the active per-query recorder too —
        # the regression differ's cache bucket sees host-tier promotes
        # per query, like every other cache series.
        _mem.cache_hit("segments.host")
        return batch, nbytes

    def _fill(self, key, fill: _Fill, paths, cols, schema, stamps, ref,
              conf, budget_override) -> Tuple[object, int]:
        """One fill: host decode, byte reservation (evicting LRU for
        headroom), H2D through the transfer engine's fill lane, insert.
        Runs OUTSIDE the cache lock except for the bookkeeping. A key
        with a demoted host-tier copy promotes instead of decoding."""
        from hyperspace_tpu.io import columnar, parquet
        from hyperspace_tpu.telemetry import memory as _mem

        promoted = self._promote(key, paths, stamps, conf,
                                 budget_override)
        if promoted is not None:
            batch, nbytes = promoted
            with self._cv:
                budget = self._effective_budget(conf, budget_override)
                if not fill.doomed and 0 < nbytes <= budget:
                    evictions = self._evict_until(nbytes, budget, conf)
                    self._entries[key] = _Entry(
                        batch, nbytes, ref,
                        pinned=(ref is not None and ref.index_name
                                in _pinned_indexes(conf)),
                        stamps=stamps)
                    self._bytes_held += nbytes
                    _mem.cache_eviction("segments", evictions)
                self._publish_stats()
                self._cv.notify_all()
            return batch, nbytes

        table = parquet.read_table(paths, columns=list(cols) if cols
                                   else None)
        budget = self._effective_budget(conf, budget_override)
        # Reserve the projected device bytes BEFORE the transfer: the
        # Arrow nbytes is a close proxy for the decoded device batch
        # (validated against the real size after placement). Without a
        # reservation, K concurrent fills each individually under
        # budget could collectively blow past it.
        projected = int(table.nbytes)
        cacheable = budget > 0 and projected <= budget
        if cacheable:
            with self._cv:
                evictions = self._evict_until(projected, budget, conf)
                self._reserved += projected
                fill.reserved = projected
                self._publish_stats()
            _mem.cache_eviction("segments", evictions)
        # The transfer itself: chunked + staged + deadline-checkpointed
        # by the engine; a cancellation raising out of here releases
        # the reservation in read()'s finally.
        batch = columnar.from_arrow(table, schema, device=True,
                                    transfer_tag="fill")
        nbytes = _batch_nbytes(batch)
        if not cacheable:
            return batch, nbytes
        if stamps is not None and parquet._stamps(paths) != stamps:
            # Unversioned read raced a rewrite: serve, never cache.
            return batch, nbytes
        with self._cv:
            self._reserved -= fill.reserved
            fill.reserved = 0
            budget = self._effective_budget(conf, budget_override)
            if fill.doomed or nbytes > budget:
                self._publish_stats()
                self._cv.notify_all()
                return batch, nbytes
            evictions = self._evict_until(nbytes, budget, conf)
            self._entries[key] = _Entry(
                batch, nbytes, ref,
                pinned=(ref is not None
                        and ref.index_name in _pinned_indexes(conf)),
                stamps=stamps)
            self._bytes_held += nbytes
            self._publish_stats()
            self._cv.notify_all()
        _mem.cache_eviction("segments", evictions)
        from hyperspace_tpu import telemetry
        telemetry.memory.maybe_sample()
        return batch, nbytes

    # -- invalidation (the index log FSM hooks) ---------------------------

    def _drop(self, predicate) -> int:
        from hyperspace_tpu.telemetry import memory as _mem
        with self._cv:
            victims = [k for k, e in self._entries.items()
                       if e.ref is not None and predicate(e.ref)]
            for k in victims:
                self._bytes_held -= self._entries.pop(k).nbytes
            host_victims = [k for k, e in self._host.items()
                            if e.ref is not None and predicate(e.ref)]
            for k in host_victims:
                self._host_bytes -= self._host.pop(k).nbytes
            for f in self._fills.values():
                if f.index_root is not None and predicate(
                        SegmentRef("", f.index_root, -1, "all")):
                    f.doomed = True
            self._publish_stats()
            self._cv.notify_all()
        _mem.cache_eviction("segments", len(victims))
        if host_victims:
            _mem.cache_eviction("segments.host", len(host_victims))
        return len(victims)

    def rekey_carried(self, index_root: str, new_version: int,
                      carried_from: int, touched) -> int:
        """Bucket-scoped commit handling for an INCREMENTAL refresh:
        `v__=<new_version>` carried `v__=<carried_from>`'s bucket runs
        forward (hard-linked, byte-identical) except for the buckets in
        `touched` (delta runs appended / deletion-filtered rewrites).
        Entries of the carried-from version whose bucket selector
        provably avoids every touched bucket are REKEYED under the new
        version — content identity holds, so the warm set survives the
        commit — while touched-bucket, unknowable-selector, and
        other-version entries drop as before. Both tiers. Returns how
        many entries were rekeyed (`cache.segments.rekeyed`)."""
        from dataclasses import replace as _replace

        from hyperspace_tpu import telemetry
        from hyperspace_tpu.telemetry import memory as _mem

        root = index_root.rstrip("/\\")
        touched = frozenset(int(b) for b in touched)
        rekeyed = 0
        dropped = 0
        host_dropped = 0
        with self._cv:
            for tier in (self._entries, self._host):
                for key in list(tier.keys()):
                    ent = tier[key]
                    ref = ent.ref
                    if ref is None or ref.index_root != root \
                            or ref.version == new_version:
                        continue
                    coverage = (_selector_buckets(ref.bucket)
                                if ref.version == carried_from else None)
                    # Key shape: ("seg", root, version, bucket, ...) —
                    # rekey = same tuple with the version swapped. Any
                    # other shape (generic get_or_fill keys) is
                    # unknowable and drops.
                    new_key = None
                    if coverage is not None and not (coverage & touched) \
                            and isinstance(key, tuple) and len(key) >= 4 \
                            and key[0] == "seg":
                        new_key = key[:2] + (new_version,) + key[3:]
                    if new_key is not None and new_key not in tier:
                        ent.ref = _replace(ref, version=new_version)
                        tier[new_key] = tier.pop(key)
                        rekeyed += 1
                        continue
                    victim = tier.pop(key)
                    if tier is self._entries:
                        self._bytes_held -= victim.nbytes
                        dropped += 1
                    else:
                        self._host_bytes -= victim.nbytes
                        host_dropped += 1
            for f in self._fills.values():
                if f.index_root == root:
                    # Conservative: an in-flight fill may cover touched
                    # buckets under the old version; serve its waiters,
                    # never insert.
                    f.doomed = True
            self._publish_stats()
            self._cv.notify_all()
        if rekeyed:
            telemetry.get_registry().counter(
                "cache.segments.rekeyed").inc(rekeyed)
        _mem.cache_eviction("segments", dropped)
        if host_dropped:
            _mem.cache_eviction("segments.host", host_dropped)
        return rekeyed

    def replica_residency(self, index_root: Optional[str] = None) -> dict:
        """{device tag: resident per-device shard entry count} over the
        born-sharded (spmd) entries, optionally restricted to one index
        root — the replica-coverage introspection: a bucket range hot
        enough that concurrent traffic filled it on two slices shows up
        here as two device tags covering the same root, and replica
        coherence tests assert the version hooks sweep EVERY tag.
        Device tags come from the spmd key component
        (`parallel/mesh.mesh_device_tag`); non-spmd entries are not
        counted."""
        out: dict = {}
        with self._cv:
            for key, ent in self._entries.items():
                if index_root is not None and (
                        ent.ref is None
                        or ent.ref.index_root
                        != index_root.rstrip("/\\")):
                    continue
                for part in key:
                    if (isinstance(part, tuple) and part
                            and part[0] in ("spmd", "spmd-sub")
                            and isinstance(part[-1], tuple)):
                        tag = part[-1]
                        out[tag] = out.get(tag, 0) + 1
                        break
        return out

    def invalidate_index(self, index_root: str,
                         keep_version: Optional[int] = None) -> int:
        """Drop every cached segment of the index rooted at
        `index_root` (optionally sparing one version). Returns how many
        entries were dropped. In-flight fills for the index are doomed:
        their waiters still get their batch (pinned-version stability)
        but nothing stale is inserted."""
        root = index_root.rstrip("/\\")
        return self._drop(lambda ref: ref.index_root == root
                          and ref.version != keep_version)

    def invalidate_version(self, index_root: str, version: int) -> int:
        root = index_root.rstrip("/\\")
        return self._drop(lambda ref: ref.index_root == root
                          and (ref.version == version or version < 0))

    def clear(self) -> None:
        from hyperspace_tpu.telemetry import memory as _mem
        with self._cv:
            n = len(self._entries)
            self._entries.clear()
            self._bytes_held = 0
            nh = len(self._host)
            self._host.clear()
            self._host_bytes = 0
            for f in self._fills.values():
                f.doomed = True
            self._publish_stats()
            self._cv.notify_all()
        _mem.cache_eviction("segments", n)
        if nh:
            _mem.cache_eviction("segments.host", nh)

    # -- introspection ----------------------------------------------------

    def snapshot(self) -> dict:
        with self._cv:
            return {
                "entries": len(self._entries),
                "bytes_held": self._bytes_held,
                "reserved_bytes": self._reserved,
                "fills_in_flight": len(self._fills),
                "pinned_entries": sum(1 for e in self._entries.values()
                                      if e.pinned),
                "host_entries": len(self._host),
                "host_bytes_held": self._host_bytes,
            }


# ---------------------------------------------------------------------------
# Process-wide cache + the index-FSM invalidation hooks
# ---------------------------------------------------------------------------

_cache: Optional[SegmentCache] = None
_cache_lock = threading.Lock()


def get_cache() -> SegmentCache:
    global _cache
    if _cache is None:
        with _cache_lock:
            if _cache is None:
                _cache = SegmentCache()
    return _cache


def set_cache(cache: SegmentCache) -> SegmentCache:
    """Install a specific cache (tests: tiny budgets, fresh state)."""
    global _cache
    _cache = cache
    return cache


def reset_cache() -> None:
    global _cache
    _cache = None


def clear() -> None:
    """Empty the process cache (bench cold phases, test isolation)."""
    cache = _cache
    if cache is not None:
        cache.clear()


def read_segment(paths, columns, schema, ref=None, conf=None,
                 budget=None, shared_members: int = 0):
    """Module-level convenience: `get_cache().read(...)`.

    `shared_members > 1` marks the SHARED read of an inter-query batch
    cohort (`engine/batcher.py`): one pass through the cache — one hit,
    or one single-flight fill — serves that many concurrent queries.
    Counted as `cache.segments.shared.{reads,members}` so the
    amortization is scrape-able next to the hit/miss series (PR-8's
    single-flight dedupes concurrent fills of one key; the batch lane
    goes further and dedupes the LOOKUP to one caller)."""
    if shared_members > 1:
        from hyperspace_tpu import telemetry
        reg = telemetry.get_registry()
        reg.counter("cache.segments.shared.reads").inc()
        reg.counter("cache.segments.shared.members").inc(shared_members)
    return get_cache().read(paths, columns, schema, ref=ref, conf=conf,
                            budget=budget)


def stats_snapshot() -> dict:
    return get_cache().snapshot()


def _invalidate_host_caches(prefix: str) -> None:
    """Stale-entry sweep of the HOST-side stamped caches + the
    footprint size cache for paths under `prefix` — the other half of
    the invalidation contract (stamp validation alone races a
    mid-commit rewrite: a query can stat, validate, and serve bytes
    the action is replacing)."""
    from hyperspace_tpu.io import parquet
    from hyperspace_tpu.plan import footprint
    parquet.invalidate_paths(prefix)
    footprint.invalidate_sizes(prefix)


def invalidate_source_paths(prefix: str) -> None:
    """Sweep the stamped HOST caches + the footprint size cache under a
    SOURCE data root (not an index root). The skipping-index commit
    calls this for each source root it sketched
    (`actions/skipping.sweep_source_caches`): freshly built sketches
    must be judged against fresh source stamps by the next admission
    decision and plan-time prune, with no stale-stamp window."""
    _invalidate_host_caches(prefix)


def on_version_committed(index_root: str, version: int,
                         touched_buckets=None,
                         carried_from: Optional[int] = None) -> None:
    """A data-writing action committed `v__=<version>` under
    `index_root` (refresh/optimize/create/incremental). Older versions'
    segments are dropped — in-flight readers of those versions refill
    from disk if they come back (the dirs survive until vacuum); new
    queries resolve the new version and fill fresh keys.

    BUCKET-SCOPED form: an incremental refresh that carried
    `v__=<carried_from>`'s runs forward passes the set of bucket ids it
    actually touched; carried-from entries over provably-untouched
    buckets are rekeyed to the new version (byte-identical hard-linked
    files) instead of dropped, so an append into bucket 7 no longer
    torches the warm entries of buckets 0..6."""
    cache = _cache
    if cache is not None:
        if touched_buckets is not None and carried_from is not None:
            cache.rekey_carried(index_root, version, carried_from,
                                touched_buckets)
        else:
            cache.invalidate_index(index_root, keep_version=version)
    _invalidate_host_caches(index_root)


def on_version_deleted(index_root: str, version: int) -> None:
    """Vacuum hard-deleted `v__=<version>`: its bytes no longer exist
    on disk, so its segments must not survive in HBM either."""
    cache = _cache
    if cache is not None:
        cache.invalidate_version(index_root, version)
    _invalidate_host_caches(index_root)


def on_index_dropped(index_root: str) -> None:
    """The index log published a terminal state (DELETED/DOESNOTEXIST):
    release every segment of the index — the rules will not select it
    again, and pinned HBM for a dropped index is a leak."""
    cache = _cache
    if cache is not None:
        cache.invalidate_index(index_root)
    _invalidate_host_caches(index_root)
