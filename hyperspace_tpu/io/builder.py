"""The index build pipeline — the framework's hot data path.

Reference equivalent: `CreateActionBase.write` =
`df.select(indexed++included).repartition(numBuckets, indexedCols)
.write.saveWithBuckets(...)` (`actions/CreateActionBase.scala:99-120`,
`index/DataFrameWriterExtensions.scala:49-78`) — a distributed JVM shuffle +
per-bucket sort + parquet encode.

TPU-native pipeline (single device; the mesh-sharded variant lives in
`parallel/build.py`):
1. execute the source plan projected to indexed+included columns ->
   HBM-resident ColumnBatch;
2. murmur-mix bucket ids on device (`ops/hash_partition.py`);
3. ONE stable `lax.sort` keyed (bucket_id, *indexed columns) — this both
   groups rows by bucket and sorts within buckets in a single XLA sort
   (the reference needs a shuffle THEN a per-bucket sort);
4. bucket boundaries via two searchsorted calls;
5. slice per bucket -> Arrow -> one parquet file per bucket.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Sequence

import numpy as np

import hyperspace_tpu.engine  # noqa: F401  (x64 config)
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io import columnar, parquet
from hyperspace_tpu.plan.nodes import BucketSpec


def _write_sorted_runs(table, perm_chunks, starts, ends, path: str,
                       file_suffix: Optional[str]) -> List[str]:
    """Apply the device-computed permutation chunk by chunk on the host and
    stream bucket files out — the tail of the pipelined build.

    `perm_chunks` are contiguous slices of the global (bucket, *keys) sort
    permutation, still device-resident: their D2H copies are issued
    asynchronously up front (transfer-engine prefetch; failures land in
    `link.d2h.prefetch_errors` instead of silently degrading to the
    serial path), so chunk i+1 is in flight over the link while chunk i
    is being gathered (Arrow `take`) and parquet-encoded — and chunk i's
    parquet ENCODE runs on the writer thread while chunk i+1's fetch +
    gather proceed (one chunk of write depth, so fault/ordering
    semantics stay deterministic). A bucket whose rows span a chunk
    boundary is written as multiple run files (`part-NNNNN-cKK.parquet`);
    runs are contiguous in sort order, so their name-ordered
    concatenation stays fully sorted — the same multi-run layout the
    incremental-refresh deltas already use.
    """
    import pyarrow as pa

    from hyperspace_tpu.io import transfer

    engine = transfer.get_engine()
    # Order matters: issue every chunk's DMA before the first blocking
    # fetch (starts/ends below) so the transfers run during the
    # device-sort sync instead of after it.
    engine.prefetch(*perm_chunks)
    starts, ends = np.asarray(starts), np.asarray(ends)
    written: List[str] = []
    from hyperspace_tpu.utils import file_utils
    file_utils.create_directory(path)
    multi = len(perm_chunks) > 1
    offset = 0
    pending: List = []  # last chunk's in-flight write futures

    def drain():
        for fut in pending:
            fut.result()
        pending.clear()

    from hyperspace_tpu import telemetry
    try:
        for ci, chunk in enumerate(perm_chunks):
            # Chunk-boundary cancellation checkpoint: a cancelled query
            # (or a deadline-capped maintenance caller) stops WITHOUT
            # queueing further writes — the finally drain below leaves
            # already-submitted files landed, same partial-dir story
            # the `_committed` marker already makes crash-safe.
            telemetry.check_deadline("write")
            # Device-resident permutation chunk: engine.fetch IS the D2H
            # link crossing (the async prefetch above may have already
            # landed it — the histogram then shows a near-zero wall for
            # the same bytes, which is the overlap working).
            perm_np = engine.fetch(chunk)
            m = len(perm_np)
            if m == 0:
                continue
            chunk_table = table.take(pa.array(perm_np))
            # Previous chunk's encodes must land before this chunk's are
            # queued: single-writer FIFO keeps write (and injected
            # fault) order identical to the serial path.
            drain()
            # Buckets intersecting sorted-row range [offset, offset + m).
            b_lo = int(np.searchsorted(ends, offset, side="right"))
            b_hi = int(np.searchsorted(starts, offset + m, side="left"))
            for b in range(b_lo, b_hi):
                s = max(int(starts[b]), offset)
                e = min(int(ends[b]), offset + m)
                if e <= s:
                    continue  # empty bucket -> no file (Spark parity)
                suffix = file_suffix
                if multi and (int(starts[b]) < offset
                              or int(ends[b]) > offset + m):
                    # Partial run of a chunk-spanning bucket: unique,
                    # ordered name.
                    suffix = f"{file_suffix or ''}c{ci:02d}"
                out = os.path.join(path, parquet.bucket_file_name(b, suffix))
                pending.append(_writer_pool().submit(
                    parquet.write_table,
                    chunk_table.slice(s - offset, e - s), out))
                written.append(out)
            offset += m
    finally:
        drain()
    return written


# Single-worker writer behind `_write_sorted_runs`: ONE lane keeps file
# writes (and injected write faults) in deterministic submission order
# while still overlapping chunk i's parquet encode with chunk i+1's
# permutation fetch + Arrow gather. Lazy module-level pool — a
# per-build executor would churn a thread per maintenance action.
_writer = None
_writer_lock = threading.Lock()


def _writer_pool():
    global _writer
    if _writer is None:
        with _writer_lock:
            if _writer is None:
                from concurrent.futures import ThreadPoolExecutor
                _writer = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="hs-bucket-writer")
    return _writer


def shutdown_writer_pool(wait: bool = True) -> None:
    """Drain + stop the single-lane bucket writer (idempotent, lazily
    re-created; atexit hook — a queued parquet encode must land before
    interpreter teardown, the build already returned its file list)."""
    global _writer
    with _writer_lock:
        pool, _writer = _writer, None
    if pool is not None:
        pool.shutdown(wait=wait)


import atexit as _atexit  # noqa: E402

_atexit.register(shutdown_writer_pool)


# Below this row count the build permutation is computed on the host
# (numpy): a novel table shape costs a fresh XLA compile (~tens of
# seconds) that small builds can never amortize, and warm device builds
# only overtake host lexsort in the ~1M-row range. Bucket assignment uses
# the host mirror of THE hash identity, so the on-disk layout is
# indistinguishable from a device build.
BUILD_MIN_DEVICE_ROWS = 1_000_000


def build_lane(rows: int) -> str:
    """Which permutation engine a HOST-resident build of `rows` rows
    takes: "native-host" (C++ radix — no device link traffic,
    link-independent cost), "host-lexsort" (small build; an XLA compile
    could never amortize), or "device" (no native library and the size
    justifies the on-chip sort). THE routing predicate — the bench
    reports this same value, so artifact labels can't drift from the
    product's actual path. Device/mesh-resident batches are routed by
    residency before this is consulted (`write_bucketed_batch`,
    `parallel/build.py`). Above 2^31 rows the native lane's int32
    permutation would wrap (`native.bucket_key_sort_perm` declines), so
    sizing routes to the int64-permutation lanes instead."""
    from hyperspace_tpu import native
    if rows < BUILD_MIN_DEVICE_ROWS:
        return "host-lexsort"
    if rows < 1 << 31 and native.get_lib() is not None:
        return "native-host"
    return "device"


def _host_lane_preferred(rows: int) -> bool:
    return build_lane(rows) != "device"


def _host_build_permutation(table, names: Sequence[str], num_buckets: int):
    """Host (bucket, *keys) stable sort permutation + bucket boundaries,
    mirroring the device program's layout semantics. The sort itself runs
    in the native C++ radix lane (`native.bucket_key_sort_perm`) when the
    library is available — no device link traffic, ~radix-speed on the
    1-core host — with np.lexsort as the always-correct fallback."""
    from hyperspace_tpu import native
    from hyperspace_tpu.ops.host_hash import (host_column_hash_lanes,
                                              host_flat_hash32)
    from hyperspace_tpu.ops.keys import host_column_sort_lanes

    batch = columnar.from_arrow(table.select(names), device=False)
    hash_lanes: List = []
    for name in names:
        hash_lanes.extend(host_column_hash_lanes(batch.column(name)))
    bucket = (host_flat_hash32(hash_lanes)
              % np.uint32(num_buckets)).astype(np.int32)
    sort_lanes: List = []
    for name in names:
        sort_lanes.extend(host_column_sort_lanes(batch.column(name)))
    nat = native.bucket_key_sort_perm(bucket, num_buckets, sort_lanes)
    if nat is not None:
        perm, starts, ends = nat
        return [perm], starts, ends
    perm = np.lexsort(tuple(reversed([bucket] + sort_lanes)))
    sorted_bucket = bucket[perm]
    starts = np.searchsorted(sorted_bucket, np.arange(num_buckets), "left")
    ends = np.searchsorted(sorted_bucket, np.arange(num_buckets), "right")
    return [perm.astype(np.int64)], starts, ends


def _stage_key_tree(table, names: Sequence[str]):
    """Stage the key columns of a host Arrow table as a device key tree
    for `ops.build.permutation_from_tree`, with narrow transport: a
    null-free int64 column whose values fit uint32 (host range check over
    data already in cache) ships HALF the bytes as a single `lo32` lane —
    hash identity and sort order are unchanged (`ops/build.py`). All H2D
    rides the pipelined transfer engine: the narrow lane ships as
    byte-budgeted chunks (several concurrent streams beat one big
    transfer on the tunneled link; the compiled program concatenates —
    `_entry_assemble`), cast into reused staging buffers instead of a
    fresh `astype` materialisation per column."""
    import pyarrow as pa

    from hyperspace_tpu.io import transfer

    engine = transfer.get_engine()
    tree = {}
    wide = []
    for name in names:
        arr = table.column(name)
        chunk = (arr.combine_chunks() if hasattr(arr, "combine_chunks")
                 else arr)
        if pa.types.is_int64(chunk.type) and chunk.null_count == 0:
            vals = chunk.to_numpy(zero_copy_only=False)
            if len(vals) and vals.min() >= 0 and vals.max() < 1 << 32:
                parts = engine.put_chunks(
                    transfer.HostCast(vals, np.uint32))
                if len(parts) > 1:
                    tree[name] = {"lo32_chunks": parts}
                else:
                    tree[name] = {"lo32": parts[0]}
                continue
        wide.append(name)
    if wide:
        batch = columnar.from_arrow(table.select(wide))
        staged, _aux = columnar.batch_to_tree(batch)
        tree.update(staged)
    return tree


def write_bucketed_table(table, indexed_columns: Sequence[str],
                         num_buckets: int, path: str,
                         file_suffix: Optional[str] = None,
                         key_batch: Optional[columnar.ColumnBatch] = None
                         ) -> List[str]:
    """Bucketed build from a HOST Arrow table: only the key columns touch
    the device (hash + sort -> permutation); payload rows never cross the
    link. `key_batch` may pass an already-staged device batch containing
    the key columns (any extra columns are ignored)."""
    from hyperspace_tpu.ops.build import (build_permutation,
                                          permutation_from_tree)

    if table.num_rows == 0:
        from hyperspace_tpu.utils import file_utils
        file_utils.create_directory(path)
        return []
    if key_batch is None:
        by_lower = {n.lower(): n for n in table.column_names}
        missing = [c for c in indexed_columns if c.lower() not in by_lower]
        if missing:
            raise HyperspaceException(
                f"Column not found in table: {', '.join(missing)}")
        names = [by_lower[c.lower()] for c in indexed_columns]
        if _host_lane_preferred(table.num_rows):
            chunks, starts, ends = _host_build_permutation(
                table, names, num_buckets)
        else:
            tree = _stage_key_tree(table, names)
            chunks, starts, ends = permutation_from_tree(
                tree, names, table.num_rows, num_buckets)
    else:
        if key_batch.num_rows != table.num_rows:
            raise HyperspaceException(
                f"key_batch rows ({key_batch.num_rows}) != table rows "
                f"({table.num_rows}); the permutation would silently drop "
                f"rows.")
        chunks, starts, ends = build_permutation(key_batch, indexed_columns,
                                                 num_buckets)
    return _write_sorted_runs(table, chunks, starts, ends, path, file_suffix)


def write_bucketed_from_files(files: Sequence[str],
                              column_names: Sequence[str],
                              key_names: Sequence[str], num_buckets: int,
                              path: str, lineage_ids=None,
                              file_suffix: Optional[str] = None
                              ) -> List[str]:
    """PIPELINED build straight from parquet files (the plain-scan create
    path): the payload-column decode is kicked off on a background
    thread FIRST, then the key columns decode, stage over the link
    (chunked H2D through the transfer engine), and the device
    permutation dispatches (async — jax returns before the sort
    finishes). Payload decode thus overlaps key decode + key H2D +
    device sort, and `_write_sorted_runs` overlaps perm D2H, Arrow
    gather, and parquet encode — every stage of
    decode -> stage -> sort -> fetch -> take -> write has a partner to
    hide behind. Below the device-amortization row count this degrades
    to the single-read host path."""
    import pyarrow as pa

    from hyperspace_tpu import telemetry
    from hyperspace_tpu.ops.build import permutation_from_tree

    n = sum(parquet.file_row_counts(files))  # footers only, no decode
    if _host_lane_preferred(n):
        table = parquet.read_table(files, columns=list(column_names))
        if lineage_ids is not None:
            table = append_lineage_column(table, files, lineage_ids)
        return write_bucketed_table(table, list(key_names), num_buckets,
                                    path, file_suffix=file_suffix)
    payload_names = [c for c in column_names if c not in key_names]
    payload: dict = {}
    payload_thread = None
    if payload_names:
        # Decoded while the keys decode/stage and the device sorts
        # (pyarrow releases the GIL for the column decode).
        def _decode_payload():
            try:
                payload["table"] = parquet.read_table(
                    files, columns=payload_names)
            except BaseException as exc:  # surfaces at join below
                payload["error"] = exc

        payload_thread = threading.Thread(
            target=telemetry.propagating(_decode_payload),
            name="hs-payload-decode", daemon=True)
        payload_thread.start()
    key_table = parquet.read_table(files, columns=list(key_names))
    tree = _stage_key_tree(key_table, key_names)
    chunks, starts, ends = permutation_from_tree(tree, key_names, n,
                                                 num_buckets)
    if payload_thread is not None:
        payload_thread.join()
        if "error" in payload:
            raise payload["error"]
        ptable = payload["table"]
        table = pa.table({c: (key_table.column(c) if c in key_names
                              else ptable.column(c))
                          for c in column_names})
    else:
        table = key_table.select(list(column_names))
    if lineage_ids is not None:
        table = append_lineage_column(table, files, lineage_ids)
    return _write_sorted_runs(table, chunks, starts, ends, path,
                              file_suffix)


def write_bucketed_batch(batch: columnar.ColumnBatch,
                         indexed_columns: Sequence[str],
                         num_buckets: int, path: str,
                         file_suffix: Optional[str] = None) -> List[str]:
    """Bucketed build from a DEVICE-resident batch (post-filter/plan data).

    The permutation program and the unsorted payload's D2H copies are
    dispatched together so the payload transfer overlaps the device sort;
    the permutation is then applied host-side per chunk. This replaces the
    old device payload gather + sorted-payload transfer, which serialized
    the big D2H behind the sort."""
    from hyperspace_tpu.ops.build import build_permutation

    if batch.num_rows == 0:
        from hyperspace_tpu.utils import file_utils
        file_utils.create_directory(path)
        return []
    chunks, starts, ends = build_permutation(batch, indexed_columns,
                                             num_buckets)
    table = columnar.to_arrow(batch)  # async copies overlap the sort
    return _write_sorted_runs(table, chunks, starts, ends, path, file_suffix)


def _plain_scan_source(plan) -> Optional[tuple]:
    """If the plan is just Project*(Scan) — the shape CreateAction.validate
    admits (reference `CreateAction.scala:42-62`) — return (files, scan
    schema); else None. Lets the build read payload straight from parquet
    on the host instead of round-tripping every column through HBM."""
    from hyperspace_tpu.plan.nodes import Project, Scan

    node = plan
    while isinstance(node, Project):
        node = node.child
    if isinstance(node, Scan) and node.bucket_spec is None:
        files = node.files()
        if files:
            return files, node.schema
    return None


SHARD_LAYOUT_FILE = "_shard_layout.json"


def write_shard_layout(path: str, num_buckets: int, n_shards: int,
                       dictionaries=None, n_slices: int = 1) -> dict:
    """Persist the born-sharded layout record next to the bucket spec:
    which contiguous bucket range each device shard owns (THE map,
    `parallel/mesh.bucket_ranges`) and — for string columns — each
    range's sorted local dictionary (`dictionaries`: {column: [values
    per shard | None]}; None marks a range past the
    `distribution.dictionary.max.entries` cap, which the reader derives
    from parquet instead). Version 3 records the (slice, device)
    HIERARCHY of multi-slice builds: `numSlices` and the slice-level
    `sliceBucketRanges` (which nest exactly over the flat shard map,
    `parallel/mesh.slice_bucket_ranges`), so a reader can route
    per-slice replica fills or cross-slice repartitions without
    rederiving the topology; a flat build records the degenerate
    1-slice hierarchy. `stamp_stats` lifts the record (dictionaries
    summarized to entry counts) into the index log entry so a reader
    knows the build's shard shape without walking the data dir."""
    import json

    from hyperspace_tpu.parallel.mesh import (bucket_ranges,
                                              slice_bucket_ranges)
    from hyperspace_tpu.utils import file_utils, storage

    n_slices = max(1, int(n_slices))
    layout = {
        "version": 3,
        "numBuckets": num_buckets,
        "numShards": n_shards,
        "numSlices": n_slices,
        "bucketRanges": [[lo, hi]
                         for lo, hi in bucket_ranges(num_buckets,
                                                     n_shards)],
        "sliceBucketRanges": [
            [lo, hi] for lo, hi in slice_bucket_ranges(
                num_buckets, n_slices, n_shards // n_slices)],
    }
    if dictionaries:
        layout["dictionaries"] = dictionaries
    file_utils.create_file(storage.join(path, SHARD_LAYOUT_FILE),
                           json.dumps(layout, indent=2))
    return layout


def summarize_shard_layout(layout):
    """The log-entry form of a shard-layout record: per-range
    dictionary VALUES stay in `_shard_layout.json` (they can be large);
    the entry carries only per-range entry COUNTS (-1 = over-cap range
    recorded as null)."""
    if not layout or "dictionaries" not in layout:
        return layout
    out = dict(layout)
    out["dictionaryEntries"] = {
        col: [len(r) if r is not None else -1 for r in ranges]
        for col, ranges in layout["dictionaries"].items()}
    del out["dictionaries"]
    return out


def _range_dictionaries(table, schema, lengths, num_buckets: int,
                        n_shards: int, max_entries: int):
    """{string column: [sorted per-range value list | None]} over the
    bucket-ordered arrow table — the build-time half of the born-sharded
    string story: each device range's dictionary recorded so query-time
    global resolution is pure JSON. A range whose distinct count
    exceeds `max_entries` records None (reader falls back to the
    files)."""
    import numpy as np

    from hyperspace_tpu.parallel.mesh import (bucket_ranges,
                                              shard_row_segments)

    str_fields = [f.name for f in schema.fields if f.dtype == "string"]
    if not str_fields or max_entries <= 0:
        return None
    segs = shard_row_segments(np.asarray(lengths, dtype=np.int64),
                              n_shards)
    out = {}
    for name in str_fields:
        col = table.column(name)
        ranges = []
        for lo, hi in segs:
            chunk = col.slice(lo, hi - lo).drop_null()
            values = np.unique(np.asarray(
                chunk.to_numpy(zero_copy_only=False), dtype=str))
            ranges.append([str(v) for v in values]
                          if len(values) <= max_entries else None)
        out[name] = ranges
    return out


def read_shard_layout(path: str) -> Optional[dict]:
    """The layout record of a born-sharded version dir, or None for a
    single-device build."""
    import json

    from hyperspace_tpu.utils import file_utils, storage

    p = storage.join(path, SHARD_LAYOUT_FILE)
    if not file_utils.exists(p):
        return None
    try:
        return json.loads(file_utils.read_contents(p))
    except (ValueError, OSError):
        return None


def write_bucket_ordered(batch: columnar.ColumnBatch, lengths,
                         num_buckets: int, path: str,
                         file_suffix: Optional[str] = None,
                         mesh=None,
                         dict_max_entries: Optional[int] = None
                         ) -> List[str]:
    """Write a batch already concatenated in bucket order (the distributed
    build's output shape) as bucketed parquet files.

    With `mesh`, the index is BORN SHARDED: each flat shard's contiguous
    bucket range writes as that device's parquet shard — files carry the
    owning shard in their suffix (`part-00003-s01.parquet`), the
    `_shard_layout.json` record pins the range map PLUS each range's
    sorted local string dictionaries (capped per
    `distribution.dictionary.max.entries`; the query-time global
    dictionary then resolves from pure JSON), and because ownership is
    contiguous, shard s's files are exactly the rows its device held
    after the build exchange (and exactly what its device re-fills on a
    born-sharded read)."""
    table = columnar.to_arrow(batch)
    written: List[str] = []
    from hyperspace_tpu.utils import file_utils
    file_utils.create_directory(path)

    def write_range(bucket_lo: int, bucket_hi: int, offset: int,
                    suffix: Optional[str]) -> int:
        for b in range(bucket_lo, bucket_hi):
            count = int(lengths[b])
            if count > 0:
                out = os.path.join(path, parquet.bucket_file_name(b,
                                                                  suffix))
                parquet.write_table(table.slice(offset, count), out)
                written.append(out)
            offset += count
        return offset

    if mesh is None:
        write_range(0, num_buckets, 0, file_suffix)
        return written

    from hyperspace_tpu.parallel.mesh import (bucket_ranges, dcn_size,
                                              total_shards)

    n_shards = total_shards(mesh)
    offset = 0
    for s, (lo, hi) in enumerate(bucket_ranges(num_buckets, n_shards)):
        suffix = f"{file_suffix or ''}s{s:02d}"
        offset = write_range(lo, hi, offset, suffix)
    from hyperspace_tpu.constants import (
        DISTRIBUTION_DICT_MAX_ENTRIES_DEFAULT)
    cap = (dict_max_entries if dict_max_entries is not None
           else DISTRIBUTION_DICT_MAX_ENTRIES_DEFAULT)
    dictionaries = _range_dictionaries(table, batch.schema, lengths,
                                       num_buckets, n_shards, cap)
    write_shard_layout(path, num_buckets, n_shards,
                       dictionaries=dictionaries,
                       n_slices=dcn_size(mesh))
    return written


def lineage_schema(schema):
    """`schema` extended with the non-nullable int64 lineage column.
    Paired with `append_lineage_column` (below) so the LOGGED index schema
    and the WRITTEN data can never disagree on the column's shape."""
    from hyperspace_tpu.constants import LINEAGE_COLUMN
    from hyperspace_tpu.plan.schema import Field, Schema

    return Schema(list(schema.fields)
                  + [Field(LINEAGE_COLUMN, "int64", False)])


def append_lineage_column(table, files: Sequence[str], lineage_ids: dict):
    """Append the per-row `_hs_file_id` column to an Arrow table read by
    concatenating `files` in order: rows from file F carry lineage_ids[F].
    THE one materialization of row lineage — create, full refresh, and
    incremental refresh all route through it, so id-to-row assignment can
    never diverge between build paths."""
    import pyarrow as pa

    from hyperspace_tpu.constants import LINEAGE_COLUMN

    counts = parquet.file_row_counts(files)
    col = np.repeat(np.asarray([lineage_ids[f] for f in files],
                               dtype=np.int64), counts)
    return table.append_column(LINEAGE_COLUMN,
                               pa.array(col, type=pa.int64()))


def write_index(df, indexed_columns: Sequence[str],
                included_columns: Sequence[str], num_buckets: int,
                path: str, conf=None, lineage_ids=None) -> List[str]:
    """THE index build job (reference `CreateActionBase.scala:99-120`).

    With a multi-device mesh active (`parallel/context.py`) the build runs
    the mesh-sharded all_to_all pipeline — the reference's cluster-wide
    `repartition(numBuckets, indexedCols)` shuffle
    (`CreateActionBase.scala:110-111`) expressed as XLA collectives.

    `lineage_ids` ({source file path: id}, lineage-enabled builds) appends
    the per-row `_hs_file_id` column: rows read from file F carry
    lineage_ids[F]. Payload-only — bucket hash and sort keys are untouched.
    """
    from hyperspace_tpu.engine.executor import execute_plan
    from hyperspace_tpu.io import transfer
    from hyperspace_tpu.parallel.context import should_distribute

    transfer.configure(conf)  # session knobs -> process engine

    def build_distributed(mesh, batch):
        from hyperspace_tpu.parallel.build import distributed_build

        built, lengths = distributed_build(batch, indexed_columns,
                                           num_buckets, mesh)
        # Born sharded: per-device parquet shards over the contiguous
        # bucket-range map, with the layout record (incl. per-range
        # string dictionaries) next to the bucket spec (lifted into the
        # log entry by `stamp_stats`).
        return write_bucket_ordered(
            built, lengths, num_buckets, path, mesh=mesh,
            dict_max_entries=(conf.distribution_dict_max_entries
                              if conf is not None else None))

    columns = list(indexed_columns) + list(included_columns)
    source = _plain_scan_source(df.plan)
    if source is None and lineage_ids is not None:
        # CreateAction.validate admits only plain file scans, so this is a
        # programming error, not a user-reachable state.
        raise HyperspaceException(
            "Lineage requires a plain file-scan source.")
    if source is not None:
        files, scan_schema = source
        names = [scan_schema.field(c).name for c in columns]
        key_names = [scan_schema.field(c).name for c in indexed_columns]
        schema = scan_schema.select(columns)
        if lineage_ids is not None:
            schema = lineage_schema(schema)
        rows = sum(parquet.file_row_counts(files))  # footers only
        mesh = should_distribute(conf, rows)
        if mesh is not None:
            table = parquet.read_table(files, columns=names)
            if lineage_ids is not None:
                table = append_lineage_column(table, files, lineage_ids)
            # Host batch: `distributed_build` places each device's shard
            # straight from host memory (concurrent sharded puts through
            # the transfer engine) instead of round-tripping the whole
            # table through the default device first.
            written = build_distributed(
                mesh, columnar.from_arrow(table, schema, device=False))
        else:
            # Pipelined: key decode -> async device sort -> payload
            # decode overlapping the sort -> streamed bucket writes.
            written = write_bucketed_from_files(
                files, names, key_names, num_buckets, path,
                lineage_ids=lineage_ids)
    else:
        batch = execute_plan(df.plan, projection=columns, conf=conf)
        schema = batch.schema
        mesh = should_distribute(conf, batch.num_rows)
        if mesh is not None:
            written = build_distributed(mesh, batch)
        else:
            written = write_bucketed_batch(batch, indexed_columns,
                                           num_buckets, path)
    spec = BucketSpec(num_buckets, tuple(indexed_columns),
                      tuple(indexed_columns))
    parquet.write_bucket_spec(path, spec, schema)
    return written


_MERGE_KEY_DTYPES = ("int64", "int32", "int16", "int8", "date32",
                     "timestamp", "bool")


def _merge_path_permutation(table, ordered, counts, names, schema,
                            num_buckets):
    """The compaction fast path: single null-free integer key -> a TRUE
    merge of each bucket's sorted runs (no re-sort of the base run,
    `ops/merge.host_merge_runs_permutation`). None when the shape doesn't
    qualify (multi-key, strings, floats — float lane order differs from
    raw order — or a nullable key); callers fall back to the batched
    sort."""
    if len(names) != 1 or schema.field(names[0]).dtype not in \
            _MERGE_KEY_DTYPES:
        return None
    col = table.column(names[0])
    if col.null_count:
        return None
    from hyperspace_tpu.ops.merge import host_merge_runs_permutation
    key = col.to_numpy(zero_copy_only=False)
    # run_bounds indexed by BUCKET ID (empty list for absent buckets) so
    # the writer's starts/ends line up with bucket file numbering.
    run_bounds = [[] for _ in range(num_buckets)]
    offset = 0
    for (b, _), c in zip(ordered, counts):
        run_bounds[b].append((offset, offset + c))
        offset += c
    return host_merge_runs_permutation(key, run_bounds)


def compact_index(prev_entry, data_manager, out_path: str) -> List[str]:
    """Merge-compact the current data version's runs (base + incremental
    delta runs living side by side in one `v__=N` dir) into one
    fully-sorted file per bucket at `out_path` (OptimizeAction's op; the
    reference has no compaction — its roadmap item,
    `/root/reference/ROADMAP.md:66-75`, exceeded here).

    All buckets compact through ONE compiled program (`ops/merge.py`):
    every bucket's runs are batch-sorted on a padded [B, L] bucket axis,
    only key lanes cross the link, and the host streams the permuted
    payload out per bucket — no per-bucket compile, no per-bucket sync.
    Below the device-amortization row count the permutation comes from a
    host lexsort with identical layout semantics.
    """
    from hyperspace_tpu.ops.merge import (bucket_sort_permutation,
                                          host_bucket_sort_permutation)

    indexed = prev_entry.indexed_columns
    num_buckets = prev_entry.num_buckets
    per_bucket = dict(parquet.bucket_files(prev_entry.content.root))
    if not per_bucket:
        raise HyperspaceException("No index data files found to compact.")
    # ONE ordered read of every run, bucket-major, VERSION order within a
    # bucket: base runs (no delta suffix, chunk suffixes keep name order)
    # then delta runs by delta number — so equal keys keep their append
    # order and the stable sort reproduces the tie order a full rebuild
    # over (base files + appended files) produces.
    import re as _re

    def _run_order(path: str):
        name = os.path.basename(path)
        m = _re.search(r"-delta(\d+)", name)
        return (int(m.group(1)) if m else 0, name)

    ordered = [(b, f) for b in sorted(per_bucket)
               for f in sorted(per_bucket[b], key=_run_order)]
    counts = parquet.file_row_counts([f for _, f in ordered])
    lengths = np.zeros(num_buckets, dtype=np.int64)
    for (b, _), c in zip(ordered, counts):
        lengths[b] += c
    table = parquet.read_table([f for _, f in ordered])
    from hyperspace_tpu.plan.schema import Schema
    schema = Schema.from_arrow(table.schema)

    names = [schema.field(c).name for c in indexed]
    merge_perm = _merge_path_permutation(table, ordered, counts, names,
                                         schema, num_buckets)
    if merge_perm is not None:
        chunks, starts, ends = merge_perm
    elif _host_lane_preferred(table.num_rows):
        key_batch = columnar.from_arrow(table.select(names), device=False)
        chunks, starts, ends = host_bucket_sort_permutation(
            key_batch, names, lengths)
    else:
        key_batch = columnar.from_arrow(table.select(names))
        chunks, starts, ends = bucket_sort_permutation(key_batch, names,
                                                       lengths)
    written = _write_sorted_runs(table, chunks, starts, ends, out_path,
                                 file_suffix=None)
    spec = BucketSpec(num_buckets, tuple(indexed), tuple(indexed))
    parquet.write_bucket_spec(out_path, spec, schema)
    return written
