"""The index build pipeline — the framework's hot data path.

Reference equivalent: `CreateActionBase.write` =
`df.select(indexed++included).repartition(numBuckets, indexedCols)
.write.saveWithBuckets(...)` (`actions/CreateActionBase.scala:99-120`,
`index/DataFrameWriterExtensions.scala:49-78`) — a distributed JVM shuffle +
per-bucket sort + parquet encode.

TPU-native pipeline (single device; the mesh-sharded variant lives in
`parallel/build.py`):
1. execute the source plan projected to indexed+included columns ->
   HBM-resident ColumnBatch;
2. murmur-mix bucket ids on device (`ops/hash_partition.py`);
3. ONE stable `lax.sort` keyed (bucket_id, *indexed columns) — this both
   groups rows by bucket and sorts within buckets in a single XLA sort
   (the reference needs a shuffle THEN a per-bucket sort);
4. bucket boundaries via two searchsorted calls;
5. slice per bucket -> Arrow -> one parquet file per bucket.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

import hyperspace_tpu.engine  # noqa: F401  (x64 config)
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io import columnar, parquet
from hyperspace_tpu.plan.nodes import BucketSpec


def write_bucketed_batch(batch: columnar.ColumnBatch,
                         indexed_columns: Sequence[str],
                         num_buckets: int, path: str,
                         file_suffix: Optional[str] = None) -> List[str]:
    """Steps 2-5: bucket + sort a device batch, write one file per bucket.
    The hash/sort/gather pipeline runs as ONE jitted XLA program
    (`ops/build.py`). Returns the written file paths."""
    from hyperspace_tpu.ops.build import build_sorted
    sorted_batch, starts, ends = build_sorted(batch, indexed_columns,
                                              num_buckets)
    starts = np.asarray(starts)
    ends = np.asarray(ends)

    table = columnar.to_arrow(sorted_batch)  # one device->host transfer
    written: List[str] = []
    os.makedirs(path, exist_ok=True)
    for b in range(num_buckets):
        if ends[b] <= starts[b]:
            continue  # empty bucket -> no file, like Spark bucketed output
        out = os.path.join(path, parquet.bucket_file_name(b, file_suffix))
        parquet.write_table(table.slice(int(starts[b]),
                                        int(ends[b] - starts[b])), out)
        written.append(out)
    return written


def write_index(df, indexed_columns: Sequence[str],
                included_columns: Sequence[str], num_buckets: int,
                path: str) -> List[str]:
    """THE index build job (reference `CreateActionBase.scala:99-120`)."""
    from hyperspace_tpu.engine.executor import execute_plan

    columns = list(indexed_columns) + list(included_columns)
    batch = execute_plan(df.plan, projection=columns)
    written = write_bucketed_batch(batch, indexed_columns, num_buckets, path)
    spec = BucketSpec(num_buckets, tuple(indexed_columns),
                      tuple(indexed_columns))
    parquet.write_bucket_spec(path, spec, batch.schema)
    return written


def compact_index(prev_entry, data_manager, out_path: str) -> List[str]:
    """Merge-compact the current data version's runs (base + incremental
    delta runs living side by side in one `v__=N` dir) into one
    fully-sorted file per bucket at `out_path` (OptimizeAction's op; the
    reference has no compaction — its roadmap item, exceeded here)."""
    from hyperspace_tpu.ops.sort import sort_batch

    indexed = prev_entry.indexed_columns
    num_buckets = prev_entry.num_buckets
    per_bucket = dict(parquet.bucket_files(prev_entry.content.root))
    if not per_bucket:
        raise HyperspaceException("No index data files found to compact.")
    schema = None
    written: List[str] = []
    os.makedirs(out_path, exist_ok=True)
    for bucket in sorted(per_bucket):
        table = parquet.read_table(per_bucket[bucket])
        batch = columnar.from_arrow(table)
        schema = batch.schema
        merged = sort_batch(batch, indexed)
        out = os.path.join(out_path, parquet.bucket_file_name(bucket))
        parquet.write_table(columnar.to_arrow(merged), out)
        written.append(out)
    spec = BucketSpec(num_buckets, tuple(indexed), tuple(indexed))
    parquet.write_bucket_spec(out_path, spec, schema)
    return written
