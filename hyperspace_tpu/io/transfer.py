"""Pipelined host<->device transfer engine — THE link seam.

The index build is link-bound on this port (BENCH_r05: 0.114s of rung1
device compute vs ~1.95s of H2D key staging + D2H permutation fetch),
and the paper's data-plane lesson — keep it a streaming recipe, not a
blocking copy — maps on TPU to classic input-pipeline software
pipelining: chunk the batch and keep the decoder, the link, the device,
and the writer busy at once.

Every host->device crossing in the package routes through this module
(`scripts/check_metrics_coverage.py` bans raw `jax.device_put` anywhere
else), which buys three things at one seam:

- **chunked, double-buffered staging**: large host arrays ship as
  byte-budgeted row chunks; chunk i+1 is converted (dtype cast / null
  fill) on a staging thread into a REUSED preallocated host buffer
  while chunk i's `device_put` is in flight, under a bounded in-flight
  byte window so a wide table can't balloon pinned host + device
  transfer memory;
- **async multi-column placement**: `put_group` decodes columns on the
  staging pool and issues every column's puts before anything blocks,
  so Arrow decode overlaps the wire for the whole batch
  (`io/columnar.from_arrow`'s device path);
- **one observable, fault-injectable link**: every put fires the
  `transfer.put` fault seam, retries transiently via `utils/retry`, and
  lands in the `link.{h2d,d2h}.{bytes,seconds,chunks}` counters plus
  the `transfer.overlap_saved_seconds` estimate (serial sum of stage
  walls minus pipelined wall) — the overlap is measured, not assumed.

Knobs (session conf, `TransferEngine.configure` /
`transfer.configure`): `spark.hyperspace.io.transfer.chunk.bytes`
(chunk granularity), `...inflight.bytes` (in-flight byte window),
`...threads` (staging pool width). The engine is process-wide
(`get_engine()`); sessions sharing a process should agree on the knobs,
same caveat as the parquet cache budgets.

Staging-buffer reuse is gated on a one-time probe that `device_put`
COPIES the host buffer (it does on TPU and on current CPU jax): on a
backend where the put aliases host memory, rewriting the buffer would
corrupt the device array, so the engine falls back to fresh
materialisation there.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from hyperspace_tpu import constants

__all__ = ["TransferEngine", "HostCast", "Host", "get_engine",
           "set_engine", "reset_engine", "configure", "device_put",
           "TransferAcquireTimeoutError", "shutdown"]


class TransferAcquireTimeoutError(TimeoutError):
    """Waiting for in-flight-window headroom exceeded
    `spark.hyperspace.io.transfer.acquire.timeout.ms`. A put that died
    without releasing its bytes (hung runtime, dead link) would
    otherwise block every later caller FOREVER on a window that can
    never drain. TimeoutError parentage is deliberate: `utils/retry.py`
    classifies it transient, so retry-wrapped callers back off and
    re-try instead of treating a recoverable stall as fatal. Counted
    as `io.transfer.acquire_timeouts`."""

import logging

logger = logging.getLogger(__name__)

# Staging below this size skips the buffer pool: the copy-into-buffer
# bookkeeping costs more than the fresh allocation it avoids.
_STAGING_MIN_BYTES = 1 << 16

# Upper bound on D2H permutation chunking (`d2h_chunk_count`): each
# chunk adds a slice output to the compiled program; past ~8 concurrent
# streams the tunneled link stops scaling.
_MAX_D2H_CHUNKS = 8


class HostCast:
    """A deferred host-side conversion: `src` reinterpreted/cast to
    `dtype` lazily, chunk by chunk, into a reused staging buffer at put
    time — instead of a fresh full-size `astype` materialisation per
    column."""

    __slots__ = ("src", "dtype")

    def __init__(self, src: np.ndarray, dtype):
        self.src = np.asarray(src)
        self.dtype = np.dtype(dtype)

    @property
    def nbytes(self) -> int:
        shape = self.src.shape
        n = 1
        for d in shape:
            n *= d
        return n * self.dtype.itemsize

    def materialize(self) -> np.ndarray:
        return np.ascontiguousarray(self.src).astype(self.dtype)


class Host:
    """Marker for `put_group` payload values that must STAY host-resident
    (string dictionaries); the engine passes `value` through unplaced."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value


class _WindowEntry:
    __slots__ = ("dev", "nbytes", "buf")

    def __init__(self, dev, nbytes: int, buf):
        self.dev = dev
        self.nbytes = nbytes
        self.buf = buf


def _block_ready(dev) -> None:
    fn = getattr(dev, "block_until_ready", None)
    if fn is not None:
        fn()


class TransferEngine:
    """Process-wide pipelined host<->device transfer engine. See module
    docstring; `put_fn` is the test seam for a fake link (signature
    `(host_array, device_or_sharding_or_None) -> device_array`)."""

    def __init__(self, chunk_bytes: Optional[int] = None,
                 inflight_bytes: Optional[int] = None,
                 threads: Optional[int] = None,
                 put_fn: Optional[Callable] = None,
                 acquire_timeout_s: Optional[float] = None):
        self.chunk_bytes = int(
            chunk_bytes or constants.IO_TRANSFER_CHUNK_BYTES_DEFAULT)
        self.inflight_bytes = int(
            inflight_bytes or constants.IO_TRANSFER_INFLIGHT_BYTES_DEFAULT)
        self.threads = int(
            threads or constants.IO_TRANSFER_THREADS_DEFAULT)
        self.acquire_timeout_s = (
            acquire_timeout_s if acquire_timeout_s is not None
            else constants.IO_TRANSFER_ACQUIRE_TIMEOUT_MS_DEFAULT
            / 1000.0)
        self._put_fn = put_fn
        self._lock = threading.RLock()
        self._pool = None
        # In-flight window: puts issued but not known complete. Shared
        # across calls so concurrent callers honor ONE byte budget.
        self._window: deque = deque()
        self._window_bytes = 0
        # Staging buffer pool: [buf uint8 ndarray, gate devarr|None].
        # A gated buffer's last consumer transfer may still be in
        # flight; acquisition blocks on the gate before reuse.
        self._staging_free: List[list] = []
        self._staging_safe: Optional[bool] = None
        self.stats: Dict[str, int] = {
            "puts": 0, "chunks": 0, "groups": 0, "reshards": 0,
            "staging_allocated": 0, "staging_reused": 0,
            "window_waits": 0,
        }

    # -- configuration ----------------------------------------------------

    def configure(self, conf) -> None:
        """Refresh the knobs from a session conf (process-wide engine;
        co-resident sessions should agree)."""
        if conf is None:
            return
        self.chunk_bytes = max(1, conf.io_transfer_chunk_bytes)
        self.inflight_bytes = max(self.chunk_bytes,
                                  conf.io_transfer_inflight_bytes)
        self.threads = max(1, conf.io_transfer_threads)
        try:
            self.acquire_timeout_s = \
                conf.io_transfer_acquire_timeout_ms / 1000.0
        except Exception:
            pass  # conf-shaped test fakes without the property

    def _staging_pool(self):
        if self._pool is None:
            with self._lock:
                if self._pool is None:
                    from concurrent.futures import ThreadPoolExecutor
                    self._pool = ThreadPoolExecutor(
                        max_workers=max(1, self.threads),
                        thread_name_prefix="hs-transfer")
        return self._pool

    # -- the raw put seam -------------------------------------------------

    def _raw_put(self, arr, device):
        """ONE guarded `jax.device_put`: fault-injectable at the
        `transfer.put` seam and transiently retried (a retried attempt
        re-puts the same host view, so chunk order cannot be corrupted
        — results are placed by index, not completion order)."""
        from hyperspace_tpu.utils import faults, retry

        def attempt():
            faults.fire("transfer.put")
            if self._put_fn is not None:
                return self._put_fn(arr, device)
            import jax
            if device is None:
                return jax.device_put(arr)
            return jax.device_put(arr, device)

        return retry.call(attempt, operation="transfer.put")

    # -- in-flight byte window -------------------------------------------

    def _sweep(self) -> None:
        """Drop window entries whose transfers already completed
        (non-blocking `is_ready` probe), releasing their bytes and
        staging buffers — keeps the engine from pinning device arrays
        past their transfer (a silent leak the leak-sentinel tests
        would otherwise trip on)."""
        released = []
        with self._lock:
            keep: deque = deque()
            while self._window:
                ent = self._window.popleft()
                probe = getattr(ent.dev, "is_ready", None)
                done = False
                if probe is not None:
                    try:
                        done = bool(probe())
                    except Exception:
                        done = False
                if done:
                    self._window_bytes -= ent.nbytes
                    if ent.buf is not None:
                        released.append(ent.buf)
                else:
                    keep.append(ent)
            self._window = keep
        for buf in released:
            self._release_staging(buf, gate=None)

    def _wait_entry_ready(self, ent: _WindowEntry,
                          t_end: Optional[float]) -> None:
        """Block until `ent`'s transfer lands, bounded by `t_end`
        (monotonic). With an `is_ready` probe (every jax array; fakes
        by contract) the wait polls so it CAN time out; without one it
        falls back to the unbounded blocking sync. Timeout raises
        `TransferAcquireTimeoutError` with the entry untouched — the
        caller must re-queue it before propagating."""
        probe = getattr(ent.dev, "is_ready", None)
        if probe is None or t_end is None:
            _block_ready(ent.dev)
            return
        while True:
            try:
                if probe():
                    return
            except Exception:
                return  # a dead array is as released as it gets
            if time.monotonic() >= t_end:
                raise TransferAcquireTimeoutError(
                    f"in-flight window acquisition timed out after "
                    f"{self.acquire_timeout_s:.1f}s "
                    f"({self._window_bytes} B held, "
                    f"{self.inflight_bytes} B window)")
            time.sleep(0.002)

    def _admit(self, nbytes: int) -> None:
        """Reserve `nbytes` of in-flight budget, blocking on the OLDEST
        outstanding transfers until the window fits (their completion
        also releases their staging buffers). The wait is BOUNDED by
        the acquire timeout: a transfer that never completes raises a
        typed transient error (`TransferAcquireTimeoutError`, counted
        as `io.transfer.acquire_timeouts`) instead of hanging every
        later caller on bytes that can never drain."""
        self._sweep()
        t_end = (time.monotonic() + self.acquire_timeout_s
                 if self.acquire_timeout_s > 0 else None)
        while True:
            with self._lock:
                if (self._window_bytes + nbytes <= self.inflight_bytes
                        or not self._window):
                    self._window_bytes += nbytes
                    return
                ent = self._window.popleft()
                self.stats["window_waits"] += 1
            try:
                self._wait_entry_ready(ent, t_end)
            except TransferAcquireTimeoutError:
                with self._lock:
                    # The entry's transfer is still outstanding: its
                    # bytes stay accounted, back at the window head.
                    self._window.appendleft(ent)
                from hyperspace_tpu import telemetry
                telemetry.get_registry().counter(
                    "io.transfer.acquire_timeouts").inc()
                raise
            with self._lock:
                self._window_bytes -= ent.nbytes
            if ent.buf is not None:
                self._release_staging(ent.buf, gate=None)

    def _track(self, dev, nbytes: int, buf) -> None:
        with self._lock:
            self._window.append(_WindowEntry(dev, nbytes, buf))

    def _windowed_put(self, view, device, buf=None):
        nbytes = int(getattr(view, "nbytes", 0))
        self._admit(nbytes)
        try:
            dev = self._raw_put(view, device)
        except BaseException:
            # A put that dies must RELEASE its reservation (and its
            # staging buffer) — leaked bytes would shrink the window
            # for every later caller until nothing fits and the
            # acquire timeout becomes the only way out.
            with self._lock:
                self._window_bytes -= nbytes
            if buf is not None:
                self._release_staging(buf, gate=None)
            raise
        self._track(dev, nbytes, buf)
        with self._lock:
            self.stats["chunks"] += 1
        return dev

    # -- staging buffers --------------------------------------------------

    def _staging_ok(self) -> bool:
        """Staging reuse is only safe when `device_put` COPIES the host
        buffer (rewriting an aliased buffer would corrupt the device
        array). The CPU PJRT client zero-copies suitably ALIGNED host
        buffers — and whether a given numpy allocation is aligned is
        luck, so no runtime probe can clear it — while accelerators
        behind a real link always copy; gate on the platform."""
        if self._staging_safe is None:
            if self._put_fn is not None:
                self._staging_safe = True  # fakes copy by contract
            else:
                try:
                    import jax
                    platform = jax.devices()[0].platform
                except Exception:
                    platform = "cpu"
                self._staging_safe = platform != "cpu"
        return self._staging_safe

    def _acquire_staging(self, nbytes: int) -> Optional[np.ndarray]:
        """A host staging buffer of capacity >= nbytes (reused when one
        is free), or None when staging is disabled/pointless."""
        if nbytes < _STAGING_MIN_BYTES or not self._staging_ok():
            return None
        gate = None
        buf = None
        with self._lock:
            for i, ent in enumerate(self._staging_free):
                if ent[0].nbytes >= nbytes:
                    buf, gate = ent
                    del self._staging_free[i]
                    break
        if buf is not None:
            if gate is not None:
                _block_ready(gate)  # prior consumer transfer must land
            with self._lock:
                self.stats["staging_reused"] += 1
            return buf
        buf = np.empty(max(nbytes, self.chunk_bytes), dtype=np.uint8)
        with self._lock:
            self.stats["staging_allocated"] += 1
        return buf

    def _release_staging(self, buf: np.ndarray, gate) -> None:
        with self._lock:
            if len(self._staging_free) < 2 * max(1, self.threads) + 2:
                self._staging_free.append([buf, gate])

    def _convert(self, cast: HostCast, start: int, stop: int):
        """Chunk [start, stop) of a HostCast into a staging buffer (or a
        fresh array when staging is off). Runs on the staging pool.
        Returns (view, buf, seconds)."""
        t0 = time.perf_counter()
        src = cast.src[start:stop]
        shape = src.shape
        nbytes = int(np.prod(shape)) * cast.dtype.itemsize if shape else \
            cast.dtype.itemsize
        buf = self._acquire_staging(nbytes)
        if buf is None:
            view = np.ascontiguousarray(src).astype(cast.dtype)
        else:
            view = buf[:nbytes].view(cast.dtype).reshape(shape)
            np.copyto(view, src, casting="unsafe")
        return view, buf, time.perf_counter() - t0

    # -- chunk planning ---------------------------------------------------

    def _chunk_bounds(self, shape, itemsize: int):
        """[(start, stop)) row ranges of <= chunk_bytes each, or None for
        a single-chunk transfer."""
        if not shape:
            return None
        rows = shape[0]
        row_bytes = itemsize
        for d in shape[1:]:
            row_bytes *= d
        if row_bytes <= 0:
            return None
        per = max(1, self.chunk_bytes // row_bytes)
        if rows <= per:
            return None
        return [(i, min(rows, i + per)) for i in range(0, rows, per)]

    def d2h_chunk_count(self, nbytes: int) -> int:
        """How many concurrent D2H streams a fetch of `nbytes` should
        split into (consumed by `ops/build.permutation_from_tree` — the
        compiled program slices the permutation accordingly)."""
        if nbytes < self.chunk_bytes:
            return 1
        return int(min(_MAX_D2H_CHUNKS,
                       -(-nbytes // self.chunk_bytes)))

    # -- entry placement --------------------------------------------------

    def _assemble(self, parts):
        if len(parts) == 1:
            return parts[0]
        import jax.numpy as jnp
        return jnp.concatenate(parts)

    def _put_parts(self, entry, device, timings) -> list:
        """Place one logical array (ndarray or HostCast) as windowed
        device chunk(s); conversions run on the staging pool one chunk
        ahead of the put. Returns the ordered chunk list (length 1 for
        sub-chunk arrays)."""
        cast = isinstance(entry, HostCast)
        arr = entry.src if cast else entry
        dtype = entry.dtype if cast else arr.dtype
        bounds = self._chunk_bounds(arr.shape, dtype.itemsize)
        if bounds is None:
            if cast:
                view, buf, conv_s = self._convert(entry, 0,
                                                  arr.shape[0]
                                                  if arr.shape else 0)
                timings["convert_s"] += conv_s
            else:
                view, buf = arr, None
            t0 = time.perf_counter()
            dev = self._windowed_put(view, device, buf=buf)
            timings["put_s"] += time.perf_counter() - t0
            timings["chunks"] += 1
            return [dev]

        from hyperspace_tpu import telemetry

        parts = [None] * len(bounds)
        pending: deque = deque()
        lookahead = max(1, self.threads) + 1
        pool = self._staging_pool()

        def emit():
            # Chunk-boundary cancellation checkpoint: a cancelled query
            # stops shipping chunks here; already-issued puts complete
            # and release through the window sweep.
            telemetry.check_deadline("transfer")
            idx, fut, ready = pending.popleft()
            buf = None
            if fut is not None:
                view, buf, conv_s = fut.result()
                timings["convert_s"] += conv_s
            else:
                view = ready
            t0 = time.perf_counter()
            parts[idx] = self._windowed_put(view, device, buf=buf)
            timings["put_s"] += time.perf_counter() - t0
            timings["chunks"] += 1

        try:
            for idx, (s, e) in enumerate(bounds):
                while len(pending) >= lookahead:
                    emit()
                if cast:
                    pending.append((idx, pool.submit(self._convert,
                                                     entry, s, e), None))
                else:
                    pending.append((idx, None, arr[s:e]))
            while pending:
                emit()
        except BaseException:
            # Guaranteed release of in-flight STAGING on the error path
            # (cancellation included): conversions already submitted to
            # the pool hold pooled buffers their put will now never
            # consume — drain and return them, or the pool bleeds
            # buffers one cancelled query at a time.
            while pending:
                _idx, fut, _ready = pending.popleft()
                if fut is None:
                    continue
                try:
                    _view, buf, _s = fut.result()
                except Exception:
                    continue
                if buf is not None:
                    self._release_staging(buf, gate=None)
            raise
        return parts

    def _put_entry(self, entry, device, timings) -> object:
        """As `_put_parts`, reassembled into ONE device array."""
        return self._assemble(self._put_parts(entry, device, timings))

    # -- public API -------------------------------------------------------

    def put(self, arr, device=None, chunked: Optional[bool] = None):
        """Place one array on the device (or under a Sharding passed as
        `device`). Host numpy inputs cross the link chunked + windowed
        and land in the h2d telemetry; already-device inputs are a
        re-placement (resharding), counted but not a link crossing.
        Sharded placements are never chunk-split — each device receives
        only its slice already."""
        if not isinstance(arr, (np.ndarray, HostCast)):
            with self._lock:
                self.stats["reshards"] += 1
            return self._raw_put(arr, device)
        if chunked is None:
            chunked = device is None
        nbytes = int(arr.nbytes)
        timings = {"convert_s": 0.0, "put_s": 0.0, "chunks": 0}
        from hyperspace_tpu import telemetry
        t = telemetry.tracer()
        ts = t.now_us() if t is not None else None
        t0 = time.perf_counter()
        if chunked:
            dev = self._put_entry(arr, device, timings)
        else:
            if isinstance(arr, HostCast):
                arr = arr.materialize()
            dev = self._windowed_put(arr, device)
            timings["chunks"] = 1
        wall = time.perf_counter() - t0
        with self._lock:
            self.stats["puts"] += 1
        telemetry.record_link_transfer("h2d", nbytes, wall, ts_us=ts,
                                       chunks=timings["chunks"])
        self._sweep()
        return dev

    def put_chunks(self, arr, device=None):
        """Place a host array (ndarray or HostCast) as a TUPLE of device
        row-chunks without reassembly — for consumers whose compiled
        program concatenates internally (`ops/build._entry_assemble`'s
        `lo32_chunks`)."""
        if not isinstance(arr, HostCast):
            arr = np.asarray(arr)
        nbytes = int(arr.nbytes)
        from hyperspace_tpu import telemetry
        t = telemetry.tracer()
        ts = t.now_us() if t is not None else None
        timings = {"convert_s": 0.0, "put_s": 0.0, "chunks": 0}
        t0 = time.perf_counter()
        parts = tuple(self._put_parts(arr, device, timings))
        with self._lock:
            self.stats["puts"] += 1
        telemetry.record_link_transfer("h2d", nbytes,
                                       time.perf_counter() - t0,
                                       ts_us=ts, chunks=len(parts))
        self._sweep()
        return parts

    def put_group(self, jobs: Sequence[Callable[[], dict]], device=None,
                  tag: Optional[str] = None) -> List[dict]:
        """Pipelined multi-column placement. Each job runs on the
        staging pool and returns {name: value} where ndarray / HostCast
        values get placed (chunked + windowed), `Host(v)` unwraps to v,
        and anything else passes through. Decode of column i+1 overlaps
        column i's puts; one h2d telemetry record covers the group, and
        the measured overlap (serial stage sum minus pipelined wall)
        accumulates in `transfer.overlap_saved_seconds`.

        `tag` names the LANE for attribution: segment-cache fills pass
        `tag="fill"`, which lands the group in `transfer.fill.{bytes,
        seconds,chunks}` counters alongside the shared `link.h2d.*`
        series (fills share the link, the in-flight window, and the
        staging pool with live queries' transfers — the budget is one;
        only the accounting is split) and stamps the cancellation
        checkpoints with the `transfer.fill` phase so an interrupted
        fill is distinguishable from an interrupted query transfer in
        `serve.interrupted.*`."""
        if not jobs:
            return []
        from hyperspace_tpu import telemetry
        pool = self._staging_pool()
        phase = f"transfer.{tag}" if tag else "transfer"
        t = telemetry.tracer()
        ts = t.now_us() if t is not None else None
        t0 = time.perf_counter()

        def timed(job):
            j0 = time.perf_counter()
            out = job()
            return out, time.perf_counter() - j0

        futs = [pool.submit(timed, job) for job in jobs]
        timings = {"convert_s": 0.0, "put_s": 0.0, "chunks": 0}
        decode_s = 0.0
        total_bytes = 0
        results: List[dict] = []
        for fut in futs:
            # Per-column checkpoint: remaining decodes still run on the
            # pool (futures are not revoked) but their results are
            # plain host arrays — nothing device-side leaks.
            telemetry.check_deadline(phase)
            produced, job_s = fut.result()
            decode_s += job_s
            placed = {}
            for key, value in produced.items():
                if isinstance(value, Host):
                    placed[key] = value.value
                elif isinstance(value, (np.ndarray, HostCast)):
                    total_bytes += int(value.nbytes)
                    placed[key] = self._put_entry(value, device, timings)
                else:
                    placed[key] = value
            results.append(placed)
        wall = time.perf_counter() - t0
        serial_s = decode_s + timings["convert_s"] + timings["put_s"]
        saved = max(serial_s - wall, 0.0)
        with self._lock:
            self.stats["groups"] += 1
        if total_bytes:
            reg = telemetry.get_registry()
            reg.counter("transfer.overlap_saved_seconds").inc(saved)
            if tag:
                reg.counter(f"transfer.{tag}.bytes").inc(total_bytes)
                reg.counter(f"transfer.{tag}.seconds").inc(wall)
                reg.counter(f"transfer.{tag}.chunks").inc(
                    max(timings["chunks"], 1))
            telemetry.record_link_transfer("h2d", total_bytes, wall,
                                           ts_us=ts,
                                           chunks=max(timings["chunks"],
                                                      1))
        self._sweep()
        return results

    # -- lifecycle --------------------------------------------------------

    def sweep(self) -> None:
        """Public probe-and-release pass over the in-flight window:
        completed transfers give back their bytes and staging buffers
        NOW (the scheduler calls this after a cancellation so a dead
        query's window share does not wait for the next caller's
        put)."""
        self._sweep()

    def drain(self) -> None:
        """Block (bounded by the acquire timeout per entry) until every
        outstanding transfer lands and its resources are released."""
        while True:
            with self._lock:
                if not self._window:
                    return
                ent = self._window.popleft()
            t_end = (time.monotonic() + self.acquire_timeout_s
                     if self.acquire_timeout_s > 0 else None)
            try:
                self._wait_entry_ready(ent, t_end)
            except TransferAcquireTimeoutError:
                logger.warning("drain: abandoning a transfer that "
                               "never completed (%d B)", ent.nbytes)
            with self._lock:
                self._window_bytes -= ent.nbytes
            if ent.buf is not None:
                self._release_staging(ent.buf, gate=None)

    def shutdown(self) -> None:
        """Drain the window and stop the staging pool (idempotent;
        registered atexit so interpreter teardown neither leaks the
        staging threads nor abandons in-flight puts)."""
        try:
            self.drain()
        except Exception:
            pass
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)

    # -- device -> host ---------------------------------------------------

    def fetch(self, arr) -> np.ndarray:
        """One device->host fetch with d2h telemetry; host-resident
        inputs pass through uncounted."""
        if isinstance(arr, np.ndarray):
            return arr
        from hyperspace_tpu import telemetry
        with telemetry.link_transfer("d2h", int(getattr(arr, "nbytes",
                                                        0))):
            return np.asarray(arr)

    def prefetch(self, *arrs) -> None:
        """Issue best-effort async D2H copies so later `fetch`es hit
        landed bytes. A failing prefetch silently degrades to the
        serial fetch — so it is COUNTED (`link.d2h.prefetch_errors`)
        and debug-logged instead of swallowed invisibly."""
        from hyperspace_tpu import telemetry
        for arr in arrs:
            fn = getattr(arr, "copy_to_host_async", None)
            if fn is None:
                continue
            try:
                fn()
            except Exception as exc:
                telemetry.get_registry().counter(
                    "link.d2h.prefetch_errors").inc()
                logger.debug("d2h prefetch failed (serial fallback): %r",
                             exc)


# -- process-wide engine ---------------------------------------------------

_engine: Optional[TransferEngine] = None
_engine_lock = threading.Lock()


def get_engine() -> TransferEngine:
    global _engine
    if _engine is None:
        with _engine_lock:
            if _engine is None:
                _engine = TransferEngine()
    return _engine


def set_engine(engine: TransferEngine) -> TransferEngine:
    """Install a specific engine (tests: tiny chunk sizes, fake links)."""
    global _engine
    _engine = engine
    return engine


def reset_engine() -> None:
    global _engine
    _engine = None


def configure(conf) -> None:
    """Refresh the process engine's knobs from a session conf."""
    get_engine().configure(conf)


def device_put(arr, device=None, chunked: Optional[bool] = None):
    """Module-level convenience: `get_engine().put(...)`."""
    return get_engine().put(arr, device=device, chunked=chunked)


def shutdown() -> None:
    """Shut the process engine down (atexit hook; idempotent — a new
    engine lazily re-creates on the next put, so tests that reset the
    module keep working)."""
    engine = _engine
    if engine is not None:
        engine.shutdown()


import atexit  # noqa: E402

atexit.register(shutdown)
