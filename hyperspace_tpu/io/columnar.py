"""Columnar substrate: Arrow tables <-> device-resident column batches.

The reference's data plane rides Spark's JVM row/columnar batches; here the
on-device representation is one jax array per column (HBM-resident), which is
what XLA fuses predicate scans over and what the Pallas kernels consume.

Strings are dictionary-encoded on the host with a *sorted* dictionary so
device-side int32 codes are order-preserving (sort/compare on codes ==
lexicographic on values), and each dictionary entry carries a precomputed
64-bit value hash placed on device, so bucket assignment hashes the *value*
(stable across files/batches with different dictionaries), never the code.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401  (enables x64)
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.schema import Field as SchemaField, Schema

_NUMERIC_NP = {
    "bool": np.bool_,
    "int8": np.int8, "int16": np.int16, "int32": np.int32, "int64": np.int64,
    "float32": np.float32, "float64": np.float64,
    "date32": np.int32, "timestamp": np.int64,
}

# Logical dtype -> host numpy dtype, incl. the string code representation.
# THE map for host-lane columns; aggregate lanes import it rather than
# keeping copies.
HOST_NP_DTYPES = {**_NUMERIC_NP, "string": np.int32}


def _jnp():
    import jax.numpy as jnp
    return jnp


_fused_take_jit = None


def _fused_take(arrays, indices):
    """All columns' row gather as ONE jitted executable (see
    ColumnBatch.take)."""
    global _fused_take_jit
    if _fused_take_jit is None:
        import jax.numpy as jnp

        from hyperspace_tpu.telemetry import instrumented_jit

        @instrumented_jit("columnar.fused_take")
        def _take_all(arrs, idx):
            return tuple(jnp.take(a, idx, axis=0) for a in arrs)

        _fused_take_jit = _take_all
    return _fused_take_jit(arrays, indices)


def _string_hash64(values: np.ndarray) -> np.ndarray:
    """FNV-1a 64-bit over utf-8 bytes of each value (host side, once per
    dictionary entry — O(dictionary), not O(rows)). Uses the native C++
    batch kernel when available (`hyperspace_tpu/native`); the Python loop
    below is the reference implementation and fallback — both MUST produce
    identical hashes (device bucket layout depends on them)."""
    if len(values) >= 64:
        from hyperspace_tpu import native
        hashed = native.string_hash64(values)
        if hashed is not None:
            return hashed
    out = np.empty(len(values), dtype=np.uint64)
    fnv_offset = np.uint64(0xCBF29CE484222325)
    fnv_prime = np.uint64(0x100000001B3)
    for i, v in enumerate(values):
        h = fnv_offset
        for b in str(v).encode("utf-8"):
            h = np.uint64((int(h) ^ b) * int(fnv_prime) & 0xFFFFFFFFFFFFFFFF)
        out[i] = h
    return out


def _split_hashes(hashes: np.ndarray, device: bool = True):
    """uint64 value hashes -> (hi, lo) uint32 pair (device or host)."""
    hi = (hashes >> np.uint64(32)).astype(np.uint32)
    lo = (hashes & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    if not device:
        return hi, lo
    import jax.numpy as jnp
    return jnp.asarray(hi), jnp.asarray(lo)


def _merged_dictionary(dictionaries, device: bool = True):
    """Merge sorted dictionaries and build remap tables + value hashes.
    Returns (merged, [remap array per input], (hi, lo))."""
    merged = np.unique(np.concatenate(list(dictionaries)))
    remaps = [np.searchsorted(merged, d).astype(np.int32)
              for d in dictionaries]
    if device:
        import jax.numpy as jnp
        remaps = [jnp.asarray(r) for r in remaps]
    return merged, remaps, _split_hashes(_string_hash64(merged),
                                         device=device)


@dataclass
class DeviceColumn:
    """One column on device.

    `data`: jax array — numeric payload, or int32 dictionary codes for
    strings. `validity`: optional bool jax array (True = present).
    `dictionary`: host numpy array of unique values, sorted ascending, for
    string columns. `dict_hashes`: device uint32x2 (hi, lo) per dictionary
    entry — value hashes for bucket assignment.
    """

    data: object
    dtype: str
    validity: Optional[object] = None
    dictionary: Optional[np.ndarray] = None
    dict_hashes: Optional[object] = None

    @property
    def is_string(self) -> bool:
        return self.dictionary is not None

    @property
    def is_host(self) -> bool:
        """True when the payload lives in host memory (numpy). Host-lane
        columns flow through the same operators; numpy-aware ops stay on
        host, jnp ops transparently promote to the device."""
        return isinstance(self.data, np.ndarray)

    def __len__(self) -> int:
        return int(self.data.shape[0])


@dataclass
class ColumnBatch:
    """A batch of columns (same length) on device, with its logical schema."""

    schema: Schema
    columns: Dict[str, DeviceColumn]

    @property
    def num_rows(self) -> int:
        if not self.columns:
            return 0
        return len(next(iter(self.columns.values())))

    def column(self, name: str) -> DeviceColumn:
        f = self.schema.field(name)  # case-insensitive resolve + validation
        return self.columns[f.name]

    def select(self, names: Sequence[str]) -> "ColumnBatch":
        schema = self.schema.select(names)
        return ColumnBatch(schema, {f.name: self.columns[f.name]
                                    for f in schema.fields})

    @property
    def is_host(self) -> bool:
        return all(c.is_host for c in self.columns.values())

    def take(self, indices) -> "ColumnBatch":
        """Row gather by index array. Host-lane batches gather with numpy
        (no device round-trip) when the indices are host-side too. Device
        batches gather every column (+validity) through ONE jitted
        executable — per-column eager takes would each pay a compile
        round-trip on a tunneled backend (~25s apiece at novel shapes)."""
        host = (isinstance(indices, np.ndarray)
                and all(c.is_host for c in self.columns.values()))
        if host:
            out = {}
            for name, col in self.columns.items():
                out[name] = DeviceColumn(
                    data=np.take(col.data, indices, axis=0),
                    dtype=col.dtype,
                    validity=(np.take(col.validity, indices, axis=0)
                              if col.validity is not None else None),
                    dictionary=col.dictionary,
                    dict_hashes=col.dict_hashes)
            return ColumnBatch(self.schema, out)
        jnp = _jnp()
        arrays = []
        for col in self.columns.values():
            arrays.append(jnp.asarray(col.data))
            if col.validity is not None:
                arrays.append(jnp.asarray(col.validity))
        gathered = list(_fused_take(tuple(arrays), jnp.asarray(indices)))
        out = {}
        for name, col in self.columns.items():
            data = gathered.pop(0)
            validity = gathered.pop(0) if col.validity is not None else None
            out[name] = DeviceColumn(data=data, dtype=col.dtype,
                                     validity=validity,
                                     dictionary=col.dictionary,
                                     dict_hashes=col.dict_hashes)
        return ColumnBatch(self.schema, out)


def _encode_strings(values: np.ndarray):
    """Reference implementation of sorted-unique dictionary encoding over a
    numpy array; `_encode_strings_arrow` is the production path and
    `tests/test_columnar.py` asserts they agree (codes, dictionary, hashes).
    Returns (codes int32, dictionary, hashes uint64, mask)."""
    import pandas as pd
    mask = ~np.asarray(pd.isna(values))
    filled = np.where(mask, values, "")
    dictionary, codes = np.unique(filled.astype(str), return_inverse=True)
    return codes.astype(np.int32), dictionary, _string_hash64(dictionary), mask


def _encode_strings_arrow(arr):
    """Arrow-native sorted-dictionary encode: dictionary_encode + dictionary
    sort + code remap all run in Arrow C++; per-value hashing runs on the
    packed Arrow buffers in the native library. Returns
    (codes int32, dictionary np[str], hashes uint64, validity|None)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    if hasattr(arr, "combine_chunks"):
        arr = arr.combine_chunks()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.chunk(0) if arr.num_chunks == 1 else pa.concat_arrays(
            arr.chunks)
    if pa.types.is_dictionary(arr.type):
        # Incoming dictionaries may hold duplicates or nulls; decode and
        # re-encode so the sorted-unique invariants hold.
        arr = arr.cast(pa.string())
    validity = None
    if arr.null_count:
        validity = np.asarray(arr.is_valid())
        arr = arr.fill_null("")
    encoded = pc.dictionary_encode(arr)
    raw_dict = encoded.dictionary
    indices = encoded.indices.to_numpy(zero_copy_only=False).astype(np.int32)
    sort_idx = pc.sort_indices(raw_dict).to_numpy().astype(np.int32)
    rank = np.empty(len(raw_dict), dtype=np.int32)
    rank[sort_idx] = np.arange(len(raw_dict), dtype=np.int32)
    codes = rank[indices]
    sorted_dict = raw_dict.take(pa.array(sort_idx))
    from hyperspace_tpu import native
    hashes = native.arrow_string_hash64(sorted_dict)
    dictionary = np.asarray(sorted_dict.to_numpy(zero_copy_only=False),
                            dtype=str)
    if hashes is None:
        hashes = _string_hash64(dictionary)
    return codes, dictionary, hashes, validity


def _decode_numeric(arr, f: SchemaField):
    """Decode one non-string Arrow column to its RAW host values + null
    mask (no target-dtype cast yet — the cast is the step the transfer
    engine performs into reused staging buffers). Returns
    (np_vals, np_dtype, mask|None)."""
    np_dtype = _NUMERIC_NP.get(f.dtype)
    if np_dtype is None:
        raise HyperspaceException(f"Unsupported dtype: {f.dtype}")
    chunk = arr.combine_chunks() if hasattr(arr, "combine_chunks") else arr
    has_nulls = chunk.null_count > 0
    if f.dtype == "timestamp":
        np_vals = chunk.cast("int64").to_numpy(zero_copy_only=False)
    elif f.dtype == "date32":
        np_vals = chunk.cast("int32").to_numpy(zero_copy_only=False)
    else:
        np_vals = chunk.to_numpy(zero_copy_only=False)
    mask = None
    if has_nulls:
        mask = ~np.asarray(chunk.is_null())
        np_vals = np.where(mask, np.nan_to_num(np_vals), 0)
    return np.asarray(np_vals), np_dtype, mask


def _decode_device_column(arr, f: SchemaField) -> dict:
    """Transfer-engine job body for one column (runs on the staging
    pool): decode to host form and name what must be placed. ndarray /
    HostCast values cross the link; Host(...) values stay host."""
    from hyperspace_tpu.io import transfer

    if f.dtype == "string":
        codes, dictionary, hashes, validity = _encode_strings_arrow(arr)
        hi, lo = _split_hashes(hashes, device=False)
        return {"data": codes, "validity": validity,
                "dictionary": transfer.Host(dictionary),
                "hash_hi": hi, "hash_lo": lo}
    np_vals, np_dtype, mask = _decode_numeric(arr, f)
    data = (np.ascontiguousarray(np_vals)
            if np_vals.dtype == np_dtype
            else transfer.HostCast(np_vals, np_dtype))
    return {"data": data, "validity": mask}


def from_arrow(table, schema: Optional[Schema] = None,
               device: bool = True,
               transfer_tag: Optional[str] = None) -> ColumnBatch:
    """Arrow table -> ColumnBatch. Nulls become validity masks with
    sentinel-filled payloads (0 / empty string). `device=False` keeps the
    columns in host memory (numpy) for the adaptive host lane — small
    batches where a device round-trip would dominate the work.

    The device path is THE scan-side H2D site and runs STREAMED through
    the pipelined transfer engine (`io/transfer.py`): column decodes run
    on the staging pool while earlier columns' puts are in flight, large
    columns ship as byte-budgeted chunks cast into reused staging
    buffers, and the whole batch lands as one chunk-counted transfer
    record in the link telemetry."""
    if schema is None:
        schema = Schema.from_arrow(table.schema)
    if device:
        from functools import partial

        from hyperspace_tpu.io import transfer

        jobs = [partial(_decode_device_column, table.column(f.name), f)
                for f in schema.fields]
        placed = transfer.get_engine().put_group(jobs, tag=transfer_tag)
        columns: Dict[str, DeviceColumn] = {}
        for f, entry in zip(schema.fields, placed):
            if f.dtype == "string":
                columns[f.name] = DeviceColumn(
                    data=entry["data"], dtype="string",
                    validity=entry.get("validity"),
                    dictionary=entry["dictionary"],
                    dict_hashes=(entry["hash_hi"], entry["hash_lo"]))
            else:
                columns[f.name] = DeviceColumn(
                    data=entry["data"], dtype=f.dtype,
                    validity=entry.get("validity"))
        return ColumnBatch(schema, columns)

    columns = {}
    for f in schema.fields:
        arr = table.column(f.name)
        if f.dtype == "string":
            codes, dictionary, hashes, validity = _encode_strings_arrow(arr)
            columns[f.name] = DeviceColumn(
                data=np.asarray(codes), dtype="string",
                validity=(np.asarray(validity)
                          if validity is not None else None),
                dictionary=dictionary,
                dict_hashes=_split_hashes(hashes, device=False))
        else:
            np_vals, np_dtype, mask = _decode_numeric(arr, f)
            columns[f.name] = DeviceColumn(
                data=np_vals.astype(np_dtype), dtype=f.dtype,
                validity=(np.asarray(mask) if mask is not None else None))
    return ColumnBatch(schema, columns)


def to_arrow(batch: ColumnBatch):
    """Device ColumnBatch -> Arrow table (decodes dictionary codes).

    All device->host copies are issued asynchronously first (transfer
    engine prefetch — failures are counted, not silently swallowed) so
    the per-column transfers overlap (d2h latency dominates on tunneled
    devices); the per-column np.asarray below then hits the ready copies.
    """
    import pyarrow as pa

    from hyperspace_tpu.io import transfer

    engine = transfer.get_engine()
    for col in batch.columns.values():
        engine.prefetch(col.data, *((col.validity,)
                                    if col.validity is not None else ()))

    import time as _time

    arrays = []
    names = []
    d2h_bytes = 0
    d2h_s = 0.0
    d2h_chunks = 0
    for f in batch.schema.fields:
        col = batch.columns[f.name]
        # Result-side D2H: device arrays cross the link in these
        # np.asarray calls (the async prefetch above may already have
        # landed them — near-zero wall for the same bytes = overlap).
        t0 = _time.perf_counter()
        data = np.asarray(col.data)
        validity = np.asarray(col.validity) if col.validity is not None else None
        if not isinstance(col.data, np.ndarray):
            d2h_s += _time.perf_counter() - t0
            d2h_bytes += data.nbytes + (validity.nbytes
                                        if validity is not None else 0)
            d2h_chunks += 1 if validity is None else 2
        if col.is_string:
            values = col.dictionary[data]
            arr = pa.array(values, type=pa.string(),
                           mask=(~validity if validity is not None else None))
        else:
            pa_type = Schema([f]).to_arrow().field(0).type
            if f.dtype == "timestamp":
                arr = pa.array(data.astype("int64"),
                               mask=(~validity if validity is not None else None)
                               ).cast(pa_type)
            elif f.dtype == "date32":
                arr = pa.array(data.astype("int32"),
                               mask=(~validity if validity is not None else None)
                               ).cast(pa_type)
            else:
                arr = pa.array(data,
                               mask=(~validity if validity is not None else None))
        arrays.append(arr)
        names.append(f.name)
    if d2h_bytes:
        from hyperspace_tpu import telemetry
        telemetry.record_link_transfer("d2h", d2h_bytes, d2h_s,
                                       chunks=d2h_chunks)
    return pa.table(dict(zip(names, arrays)))


def _owned_host(arr: np.ndarray) -> np.ndarray:
    """An OWNING host copy of a fetched array. On zero-copy backends
    (CPU PJRT) `np.asarray(device_array)` is a view whose base pins the
    device buffer — a demoted entry built from views would keep its
    "evicted" HBM alive, and re-promoting the view re-aliases it into
    an unbounded buffer chain (the leak-sentinel test for the tiered
    cache caught exactly this). A view materializes; an already-owning
    array (real-accelerator D2H lands in fresh host memory) passes
    through uncopied."""
    return np.array(arr, copy=True) if arr.base is not None else arr


def batch_to_host(batch: ColumnBatch) -> ColumnBatch:
    """Device ColumnBatch -> fully host-resident copy (numpy payloads,
    numpy dict hashes) — the segment cache's DEMOTION form: everything
    needed to rebuild the device batch WITHOUT re-reading or re-decoding
    parquet, at the cost of one D2H fetch per column now and one H2D put
    at re-promotion. Fetches ride the transfer engine (d2h telemetry);
    already-host columns pass through untouched. Every payload OWNS its
    memory (`_owned_host`) so the demoted entry releases, not pins, the
    device residency it replaced."""
    from hyperspace_tpu.io import transfer

    engine = transfer.get_engine()
    for col in batch.columns.values():
        engine.prefetch(col.data, *((col.validity,)
                                    if col.validity is not None else ()))
    out: Dict[str, DeviceColumn] = {}
    for name, col in batch.columns.items():
        hashes = col.dict_hashes
        if hashes is not None:
            hashes = (_owned_host(np.asarray(hashes[0])),
                      _owned_host(np.asarray(hashes[1])))
        out[name] = DeviceColumn(
            data=_owned_host(engine.fetch(col.data)), dtype=col.dtype,
            validity=(_owned_host(engine.fetch(col.validity))
                      if col.validity is not None else None),
            dictionary=col.dictionary,
            dict_hashes=hashes)
    return ColumnBatch(batch.schema, out)


def host_batch_to_device(batch: ColumnBatch,
                         transfer_tag: Optional[str] = None
                         ) -> ColumnBatch:
    """Host ColumnBatch (the demoted form above) -> device-resident
    batch via the pipelined transfer engine — the segment cache's
    RE-PROMOTION: H2D cost paid, parquet decode skipped. `transfer_tag`
    rides the same lane accounting as fills (`tag="fill"` lands in
    `transfer.fill.*`)."""
    from hyperspace_tpu.io import transfer

    def job(col: DeviceColumn):
        def run() -> dict:
            produced = {"data": np.asarray(col.data)}
            if col.validity is not None:
                produced["validity"] = np.asarray(col.validity)
            if col.dict_hashes is not None:
                produced["hash_hi"] = np.asarray(col.dict_hashes[0])
                produced["hash_lo"] = np.asarray(col.dict_hashes[1])
            return produced
        return run

    cols = [batch.columns[f.name] for f in batch.schema.fields]
    placed = transfer.get_engine().put_group([job(c) for c in cols],
                                             tag=transfer_tag)
    out: Dict[str, DeviceColumn] = {}
    for f, col, entry in zip(batch.schema.fields, cols, placed):
        hashes = None
        if "hash_hi" in entry:
            hashes = (entry["hash_hi"], entry["hash_lo"])
        out[f.name] = DeviceColumn(
            data=entry["data"], dtype=col.dtype,
            validity=entry.get("validity"),
            dictionary=col.dictionary, dict_hashes=hashes)
    return ColumnBatch(batch.schema, out)


def concat_batches(batches: List[ColumnBatch]) -> ColumnBatch:
    """Concatenate batches row-wise. String columns are re-unified through a
    merged sorted dictionary so codes stay order-preserving and comparable.
    All-host inputs concatenate on the host lane; any device input promotes
    the result to the device."""
    if not batches:
        raise HyperspaceException("Cannot concat zero batches.")
    if len(batches) == 1:
        return batches[0]
    host = all(b.is_host for b in batches)
    xp = np if host else _jnp()
    schema = batches[0].schema
    out: Dict[str, DeviceColumn] = {}
    for f in schema.fields:
        cols = [b.columns[f.name] for b in batches]
        any_validity = any(c.validity is not None for c in cols)
        validity = None
        if any_validity:
            validity = xp.concatenate([
                c.validity if c.validity is not None
                else xp.ones(len(c), dtype=bool) for c in cols])
        if f.dtype == "string":
            merged, remaps, hashes = _merged_dictionary(
                [c.dictionary for c in cols], device=not host)
            remapped = [xp.take(remap, c.data)
                        for remap, c in zip(remaps, cols)]
            out[f.name] = DeviceColumn(xp.concatenate(remapped), "string",
                                       validity, merged, hashes)
        else:
            out[f.name] = DeviceColumn(
                xp.concatenate([c.data for c in cols]), f.dtype, validity)
    return ColumnBatch(schema, out)


def unify_string_columns(a: DeviceColumn, b: DeviceColumn):
    """Re-map two string columns onto one merged sorted dictionary so their
    codes are mutually comparable (used by the join path)."""
    import jax.numpy as jnp

    merged, (remap_a, remap_b), hashes = _merged_dictionary(
        [a.dictionary, b.dictionary])

    def remap(col: DeviceColumn, table) -> DeviceColumn:
        return DeviceColumn(jnp.take(table, col.data), "string",
                            col.validity, merged, hashes)

    return remap(a, remap_a), remap(b, remap_b)


def batch_to_tree(batch: ColumnBatch):
    """ColumnBatch -> (jit-traversable pytree of device arrays, host aux).

    The tree holds per-column {"data", "validity", "hash_hi", "hash_lo"}
    (absent entries omitted so jit caching keys on structure); aux carries
    the host-side dictionaries needed to rebuild the batch.
    """
    tree = {}
    aux = {}
    for f in batch.schema.fields:
        col = batch.columns[f.name]
        entry = {"data": col.data}
        if col.validity is not None:
            entry["validity"] = col.validity
        if col.is_string:
            entry["hash_hi"], entry["hash_lo"] = col.dict_hashes
        tree[f.name] = entry
        aux[f.name] = col.dictionary
    return tree, aux


def tree_to_batch(tree, schema: Schema, aux) -> ColumnBatch:
    columns = {}
    for f in schema.fields:
        entry = tree[f.name]
        dict_hashes = None
        if "hash_hi" in entry:
            dict_hashes = (entry["hash_hi"], entry["hash_lo"])
        columns[f.name] = DeviceColumn(
            data=entry["data"], dtype=f.dtype,
            validity=entry.get("validity"),
            dictionary=aux.get(f.name),
            dict_hashes=dict_hashes)
    return ColumnBatch(schema, columns)
