"""Central JAX configuration, imported by every module that touches jax.

x64 is mandatory for data correctness: lake data routinely carries int64
keys and float64 measures, and jax's default 32-bit mode would silently
truncate them. The perf-critical kernels (hashing, sort keys) operate on
32-bit lanes internally (`ops/hash_partition.py`), so the TPU fast path is
not sacrificed.
"""

import jax

jax.config.update("jax_enable_x64", True)
