"""Central JAX configuration, imported by every module that touches jax.

x64 is mandatory for data correctness: lake data routinely carries int64
keys and float64 measures, and jax's default 32-bit mode would silently
truncate them. The perf-critical kernels (hashing, sort keys) operate on
32-bit lanes internally (`ops/hash_partition.py`), so the TPU fast path is
not sacrificed.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: first-ever compile of the fused build/join
# programs costs tens of seconds against the tunneled TPU; subsequent
# processes reuse the on-disk executable. Opt out with
# HYPERSPACE_JAX_CACHE=0 or redirect via JAX_COMPILATION_CACHE_DIR.
if os.environ.get("HYPERSPACE_JAX_CACHE", "1") == "1":
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR",
                           os.path.expanduser("~/.cache/hyperspace_tpu_xla")))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax without the persistent cache: run without it
