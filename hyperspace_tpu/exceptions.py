"""Framework exception type.

Parity: reference `HyperspaceException.scala:19` (single framework exception).
"""


class HyperspaceException(Exception):
    """Raised for all user-facing framework errors."""

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message
