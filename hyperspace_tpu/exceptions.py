"""Framework exception types.

Parity: reference `HyperspaceException.scala:19` (single framework
exception), plus the typed scan-time signal the graceful-degradation
path keys on.
"""


class HyperspaceException(Exception):
    """Raised for all user-facing framework errors."""

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class IndexDataUnavailableError(HyperspaceException):
    """An index the optimizer selected turned out missing or unreadable
    at SCAN time (data root deleted out-of-band, files corrupt, storage
    failing past the retry policy). Raised only for rule-selected index
    scans — the serving plane (`engine/scheduler.py`) catches it and
    falls back to the source-data plan instead of failing the query,
    recording a `resilience.fallbacks` counter and a `degraded`
    decision event; repeated failures trip the per-index circuit
    breaker so a known-bad index stops re-paying the failed scan."""

    def __init__(self, message: str, index_name=None):
        super().__init__(message)
        self.index_name = index_name


class QueryServingError(HyperspaceException):
    """Base of the TYPED serving-plane errors the query scheduler
    raises (`engine/scheduler.py`). The contract, enforced by
    `scripts/check_metrics_coverage.py`: every concrete subclass
    declares `counter` — the registry counter the scheduler bumps when
    it raises the error — and appears in
    `scheduler.SERVING_ERROR_COUNTERS`, so no serving failure mode can
    exist without a scrape-able series behind it. `query_id` names the
    query for `session.cancel`/log correlation; `phase` (when set) is
    the execution phase the error interrupted (queue/scan/operator/
    stage/transfer/write) — the flight recorder and the regression
    differ's `cancellation` bucket read it."""

    counter: str = ""  # concrete subclasses MUST override

    def __init__(self, message: str, query_id=None, phase=None):
        super().__init__(message)
        self.query_id = query_id
        self.phase = phase


class QueryRejectedError(QueryServingError):
    """Admission control rejected the query OUTRIGHT: the projected
    HBM footprint does not fit the serving budget and the wait queue
    is already at `spark.hyperspace.serve.queue.depth` — backpressure
    surfaces to the caller immediately instead of piling threads up
    behind a full device."""

    counter = "serve.rejected"


class QueryCancelledError(QueryServingError):
    """The query was cooperatively cancelled (`session.cancel(id)` /
    scheduler shutdown) and stopped at the next deadline checkpoint."""

    counter = "serve.cancelled"


class QueryDeadlineExceededError(QueryCancelledError):
    """The query's deadline (`collect(timeout=...)` or
    `spark.hyperspace.serve.deadline.seconds`) expired — while queued
    or at an execution checkpoint; `phase` says which."""

    counter = "serve.deadline_exceeded"
