"""Framework exception types.

Parity: reference `HyperspaceException.scala:19` (single framework
exception), plus the typed scan-time signal the graceful-degradation
path keys on.
"""


class HyperspaceException(Exception):
    """Raised for all user-facing framework errors."""

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class IndexDataUnavailableError(HyperspaceException):
    """An index the optimizer selected turned out missing or unreadable
    at SCAN time (data root deleted out-of-band, files corrupt, storage
    failing past the retry policy). Raised only for rule-selected index
    scans — `DataFrame.collect` catches it and falls back to the
    source-data plan instead of failing the query, recording a
    `resilience.fallbacks` counter and a `degraded` decision event."""

    def __init__(self, message: str, index_name=None):
        super().__init__(message)
        self.index_name = index_name
