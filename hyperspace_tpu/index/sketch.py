"""Data-skipping sketch blobs: per-source-file zone maps + bloom filters.

The DATA of a `DataSkippingIndex` is one compact parquet blob per
committed `v__=N` version dir — `_hs_sketches` (parquet format; the
name carries no `.parquet` extension so data-file globs and bucket
listings never mistake it for rows, same convention as `_committed` /
`_bucket_spec.json`). One row per source file:

  file, size, stamp          — path + the `index/signature.file_stamp`
                               identity captured when the file was
                               sketched; the query-side pruner
                               revalidates it, so a rewritten file is
                               simply UNKNOWN (kept), never wrongly
                               pruned
  rows, bucket               — row count; bucket id when the file name
                               carries the bucketed layout's pattern
                               (-1 otherwise), so pruning a bucketed
                               source prunes whole buckets
  per sketched column i:     min_i / max_i (int64 / float64 / string by
                               column kind; NULL when no non-null,
                               non-NaN row exists), nulls_i, ok_i
                               (non-null non-NaN count), nan_i, and
                               bloom_i (split-block filter words as
                               little-endian uint32 bytes; empty when
                               the bloom sketch was not selected)

Blob-level metadata (parquet schema metadata, key
`hyperspace.sketches`) records the format version, the sketched
columns with their dtypes, the sketch types, and the bloom hash
version — a loader refuses versions it does not understand, and the
rules degrade that refusal to an unpruned scan.

CONSULTING the sketches (deciding which files a predicate refutes)
lives in `plan/rules/skipping.py` — `scripts/check_metrics_coverage.py`
fails any `load_sketches`/`prune_files` call outside the rules module
and this blob-IO home, so pruning decisions cannot scatter.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceException

SKETCH_BLOB = "_hs_sketches"
SKETCH_FORMAT_VERSION = 1
# Version of the bloom hash identity (`ops/sketch.py` dual mix over the
# bucket-hash value lanes). Bumped if the mix or lane decomposition ever
# changes; a blob under a different version loads with blooms DISABLED
# (zones still serve — they carry plain values).
SKETCH_HASH_VERSION = 1

_META_KEY = b"hyperspace.sketches"


def _kind_of(dtype: str) -> str:
    if dtype == "string":
        return "str"
    if dtype in ("float32", "float64"):
        return "float"
    return "int"


@dataclass
class ColumnSketch:
    """One column's sketch facts for one file (module docstring)."""

    dtype: str
    min: object  # None when no non-null, non-NaN value exists
    max: object
    nulls: int
    ok: int  # non-null, non-NaN row count
    has_nan: bool
    bloom: Optional[np.ndarray] = None  # uint32 words, None = no bloom


@dataclass
class FileSketch:
    path: str
    size: int
    stamp: str
    rows: int
    bucket: int  # -1 when the file name carries no bucket id
    columns: Dict[str, ColumnSketch] = field(default_factory=dict)
    # keyed by LOWERCASED column name


@dataclass
class SketchSet:
    """A loaded blob: sketched columns (+dtypes) and per-file facts."""

    columns: List[str]
    dtypes: Dict[str, str]  # lowercased name -> dtype
    sketch_types: List[str]
    blooms_usable: bool
    files: Dict[str, FileSketch] = field(default_factory=dict)

    def sketch_for(self, path: str) -> Optional[FileSketch]:
        return self.files.get(path)


# ---------------------------------------------------------------------------
# Build side
# ---------------------------------------------------------------------------


def sketch_batch(batch, names: Sequence[str], want_bloom: bool,
                 nbits: int) -> Dict[str, ColumnSketch]:
    """Sketch every column in `names` of one ColumnBatch (host- or
    device-lane; the device lane was staged through the TransferEngine
    by the caller). Strings' code-space zone bounds are mapped back
    through the sorted dictionary here."""
    from hyperspace_tpu.ops import sketch as ops_sketch

    out: Dict[str, ColumnSketch] = {}
    for name in names:
        col = batch.column(name)
        f = batch.schema.field(name)
        z = ops_sketch.zones(col)
        vmin, vmax = z["min"], z["max"]
        if col.is_string and vmin is not None:
            vmin = str(col.dictionary[int(vmin)])
            vmax = str(col.dictionary[int(vmax)])
        bloom = None
        if want_bloom and len(col):
            bloom = ops_sketch.bloom_build(col, nbits)
        out[f.name.lower()] = ColumnSketch(
            dtype=f.dtype, min=vmin, max=vmax, nulls=int(z["nulls"]),
            ok=int(z["ok"]), has_nan=bool(z["has_nan"]), bloom=bloom)
    return out


def build_file_sketches(files: Sequence[str], names: Sequence[str],
                        schema, conf) -> List[FileSketch]:
    """One FileSketch per source file: read the sketched columns,
    reduce on the adaptive lane (device kernels for batches at or above
    `spark.hyperspace.execution.min.device.rows`, staged through the
    TransferEngine; numpy below), and capture each file's (size, stamp)
    identity for query-time revalidation."""
    from hyperspace_tpu import constants
    from hyperspace_tpu.index.signature import file_stamp
    from hyperspace_tpu.io import columnar, parquet

    want_bloom = True
    fpp = constants.SKIPPING_BLOOM_FPP_DEFAULT
    max_bytes = constants.SKIPPING_BLOOM_MAX_BYTES_DEFAULT
    min_dev = constants.MIN_DEVICE_ROWS_DEFAULT
    if conf is not None:
        fpp = conf.skipping_bloom_fpp
        max_bytes = conf.skipping_bloom_max_bytes
        min_dev = conf.min_device_rows
    from hyperspace_tpu.ops.sketch import bloom_num_bits

    col_schema = schema.select(names)
    out: List[FileSketch] = []
    for path in files:
        stamp = file_stamp(path)
        if stamp is None:
            raise HyperspaceException(
                f"Cannot stat source file for sketching: {path}")
        table = parquet.read_table([path], columns=list(names))
        rows = table.num_rows
        batch = columnar.from_arrow(table, col_schema,
                                    device=rows >= min_dev)
        columns = sketch_batch(
            batch, names, want_bloom,
            bloom_num_bits(rows, fpp, max_bytes)) if rows else {
            n.lower(): ColumnSketch(col_schema.field(n).dtype, None, None,
                                    0, 0, False,
                                    np.zeros(0, dtype=np.uint32))
            for n in names}
        bucket = parquet.bucket_of_file(path)
        out.append(FileSketch(
            path=path, size=int(stamp[0]), stamp=str(stamp[1]), rows=rows,
            bucket=-1 if bucket is None else int(bucket), columns=columns))
    return out


def write_sketches(version_dir: str, sketches: Sequence[FileSketch],
                   names: Sequence[str], schema,
                   sketch_types: Sequence[str]) -> int:
    """Persist the blob into `version_dir` (before the `_committed`
    marker lands — the blob is part of the version's data). Returns the
    blob's on-disk bytes."""
    import pyarrow as pa

    from hyperspace_tpu.io import parquet
    from hyperspace_tpu.utils import storage

    resolved = [schema.field(n).name for n in names]
    dtypes = [schema.field(n).dtype for n in resolved]
    data: Dict[str, object] = {
        "file": pa.array([s.path for s in sketches], type=pa.string()),
        "size": pa.array([s.size for s in sketches], type=pa.int64()),
        "stamp": pa.array([s.stamp for s in sketches], type=pa.string()),
        "rows": pa.array([s.rows for s in sketches], type=pa.int64()),
        "bucket": pa.array([s.bucket for s in sketches], type=pa.int32()),
    }
    for i, (name, dtype) in enumerate(zip(resolved, dtypes)):
        kind = _kind_of(dtype)
        pa_type = {"str": pa.string(), "float": pa.float64(),
                   "int": pa.int64()}[kind]

        def conv(v):
            if v is None:
                return None
            if kind == "str":
                return str(v)
            return float(v) if kind == "float" else int(v)

        per = [s.columns.get(name.lower()) for s in sketches]
        data[f"min_{i}"] = pa.array([conv(c.min if c else None)
                                     for c in per], type=pa_type)
        data[f"max_{i}"] = pa.array([conv(c.max if c else None)
                                     for c in per], type=pa_type)
        data[f"nulls_{i}"] = pa.array([c.nulls if c else 0 for c in per],
                                      type=pa.int64())
        data[f"ok_{i}"] = pa.array([c.ok if c else 0 for c in per],
                                   type=pa.int64())
        data[f"nan_{i}"] = pa.array([bool(c.has_nan) if c else False
                                     for c in per], type=pa.bool_())
        data[f"bloom_{i}"] = pa.array(
            [(c.bloom.astype("<u4").tobytes()
              if c is not None and c.bloom is not None else b"")
             for c in per], type=pa.binary())
    meta = {
        "version": SKETCH_FORMAT_VERSION,
        "hashVersion": SKETCH_HASH_VERSION,
        "columns": [{"name": n, "dtype": d}
                    for n, d in zip(resolved, dtypes)],
        "sketchTypes": list(sketch_types),
    }
    table = pa.table(data).replace_schema_metadata(
        {_META_KEY: json.dumps(meta).encode("utf-8")})
    blob_path = storage.join(version_dir, SKETCH_BLOB)
    parquet.write_table(table, blob_path)
    from hyperspace_tpu.index.signature import file_stamp
    stamp = file_stamp(blob_path)
    return int(stamp[0]) if stamp is not None else 0


# ---------------------------------------------------------------------------
# Load side (bounded cache over immutable version dirs)
# ---------------------------------------------------------------------------

_cache: Dict[str, SketchSet] = {}
_cache_lock = threading.Lock()


def clear_sketch_cache() -> None:
    with _cache_lock:
        _cache.clear()


def load_sketches(version_dir: str) -> SketchSet:
    """Load (and cache) the sketch blob of one committed version dir.
    Version dirs are immutable once committed, so cache entries never
    revalidate; the cache is bounded, and a missing/corrupt/unknown-
    version blob raises HyperspaceException — the rules degrade that to
    an unpruned scan."""
    key = os.path.normpath(version_dir)
    with _cache_lock:
        hit = _cache.get(key)
    if hit is not None:
        return hit
    from hyperspace_tpu.io import parquet
    from hyperspace_tpu.utils import storage

    blob_path = storage.join(version_dir, SKETCH_BLOB)
    try:
        table = parquet.read_table([blob_path])
    except HyperspaceException:
        raise
    except Exception as exc:
        raise HyperspaceException(
            f"Unreadable sketch blob at {blob_path}: {exc!r}") from exc
    raw_meta = (table.schema.metadata or {}).get(_META_KEY)
    if raw_meta is None:
        raise HyperspaceException(
            f"Sketch blob at {blob_path} carries no metadata.")
    try:
        meta = json.loads(raw_meta.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as exc:
        raise HyperspaceException(
            f"Corrupt sketch metadata at {blob_path}: {exc}") from exc
    if meta.get("version") != SKETCH_FORMAT_VERSION:
        raise HyperspaceException(
            f"Unsupported sketch format version {meta.get('version')} "
            f"at {blob_path}.")
    columns = [c["name"] for c in meta["columns"]]
    dtypes = {c["name"].lower(): c["dtype"] for c in meta["columns"]}
    # An unknown HASH version only disables blooms — zone maps store
    # plain values and stay servable.
    blooms_usable = meta.get("hashVersion") == SKETCH_HASH_VERSION

    d = table.to_pydict()
    files: Dict[str, FileSketch] = {}
    for r in range(table.num_rows):
        cols: Dict[str, ColumnSketch] = {}
        for i, (name, cmeta) in enumerate(zip(columns, meta["columns"])):
            raw_bloom = d[f"bloom_{i}"][r]
            bloom = (np.frombuffer(raw_bloom, dtype="<u4")
                     if raw_bloom else None)
            cols[name.lower()] = ColumnSketch(
                dtype=cmeta["dtype"], min=d[f"min_{i}"][r],
                max=d[f"max_{i}"][r], nulls=int(d[f"nulls_{i}"][r]),
                ok=int(d[f"ok_{i}"][r]), has_nan=bool(d[f"nan_{i}"][r]),
                bloom=bloom if blooms_usable else None)
        fs = FileSketch(path=d["file"][r], size=int(d["size"][r]),
                        stamp=str(d["stamp"][r]), rows=int(d["rows"][r]),
                        bucket=int(d["bucket"][r]), columns=cols)
        files[fs.path] = fs
    out = SketchSet(columns=columns, dtypes=dtypes,
                    sketch_types=list(meta.get("sketchTypes", [])),
                    blooms_usable=blooms_usable, files=files)
    with _cache_lock:
        if len(_cache) > 256:
            _cache.clear()
        _cache[key] = out
    return out


# ---------------------------------------------------------------------------
# Delta build (append-only streaming refresh)
# ---------------------------------------------------------------------------


def append_file_sketches(prev_version_dir: str, files: Sequence[str],
                         names: Sequence[str], schema, conf):
    """Delta-sketch build for an append-mostly source: carry forward the
    previous version's per-file rows whose (size, stamp) identity still
    matches the live file, re-sketch only new or rewritten files, and
    drop rows for files that vanished. Returns `(sketches, detail)` —
    the merged list in current-listing order plus a report dict with
    carried/sketched/dropped counts.

    Lives here (not in the refresh action) because `load_sketches` is
    seam-linted to this module and `plan/rules/`: all blob IO stays in
    one file. Safety: `plan/rules/skipping.prune_files` revalidates
    (size, stamp) per file at query time and KEEPS any unknown or
    changed file, so even a stale carried row can only under-prune,
    never wrongly drop a file. An unreadable previous blob degrades to
    a full re-sketch of every file (counted in the detail) rather than
    failing the refresh.
    """
    from hyperspace_tpu.index.signature import file_stamp

    prev_files: Dict[str, FileSketch] = {}
    prev_unreadable = False
    try:
        prev_files = dict(load_sketches(prev_version_dir).files)
    except HyperspaceException:
        prev_unreadable = True

    carried: Dict[str, FileSketch] = {}
    to_sketch: List[str] = []
    for path in files:
        prev = prev_files.get(path)
        stamp = file_stamp(path) if prev is not None else None
        if prev is not None and stamp is not None \
                and prev.size == int(stamp[0]) \
                and prev.stamp == str(stamp[1]):
            carried[path] = prev
        else:
            to_sketch.append(path)
    fresh = {s.path: s for s in
             build_file_sketches(to_sketch, names, schema, conf)}
    merged = [carried.get(p, fresh.get(p)) for p in files]
    live = set(files)
    detail = {
        "files_carried": len(carried),
        "files_sketched": len(fresh),
        "files_dropped": sum(1 for p in prev_files if p not in live),
    }
    if prev_unreadable:
        detail["prev_blob_unreadable"] = True
    return merged, detail
