"""Read-path cache for index metadata.

Parity: reference `index/Cache.scala:23-41` (Cache trait) and
`CreationTimeBasedIndexCache` (`index/CachingIndexCollectionManager.scala:117-160`)
expiring after `spark.hyperspace.index.cache.expiryDurationInSeconds`
(default 300 s), plus the factory seam (`index/IndexCacheFactory.scala:23-38`).
"""

from __future__ import annotations

import time
from abc import ABC, abstractmethod
from typing import Generic, Optional, TypeVar

from hyperspace_tpu.config import HyperspaceConf

T = TypeVar("T")


class Cache(ABC, Generic[T]):
    @abstractmethod
    def get(self) -> Optional[T]: ...

    @abstractmethod
    def set(self, entry: T) -> None: ...

    @abstractmethod
    def clear(self) -> None: ...


class CreationTimeBasedCache(Cache[T]):
    """Single-entry expiring cache on `time.monotonic()` — wall-clock
    (`time.time()`) jumps from NTP steps or manual clock changes would
    prematurely expire (forward jump) or immortalize (backward jump)
    the entry; expiry is a DURATION, so it must ride the monotonic
    clock. Hit/miss/expiry counts land as `cache.index_metadata.*`."""

    def __init__(self, conf: HyperspaceConf):
        self._conf = conf
        self._entry: Optional[T] = None
        self._created_at: float = 0.0

    def get(self) -> Optional[T]:
        from hyperspace_tpu.telemetry import memory as _mem
        if self._entry is None:
            _mem.cache_miss("index_metadata")
            return None
        if time.monotonic() - self._created_at \
                > self._conf.cache_expiry_seconds:
            _mem.cache_miss("index_metadata")
            _mem.cache_eviction("index_metadata")
            self.clear()
            return None
        _mem.cache_hit("index_metadata")
        return self._entry

    def set(self, entry: T) -> None:
        from hyperspace_tpu.telemetry import memory as _mem
        self._entry = entry
        self._created_at = time.monotonic()
        _mem.cache_stats("index_metadata", None, 1)

    def clear(self) -> None:
        from hyperspace_tpu.telemetry import memory as _mem
        self._entry = None
        self._created_at = 0.0
        _mem.cache_stats("index_metadata", None, 0)


class IndexCacheFactory:
    def create(self, conf: HyperspaceConf) -> Cache:
        return CreationTimeBasedCache(conf)
