"""Read-path cache for index metadata.

Parity: reference `index/Cache.scala:23-41` (Cache trait) and
`CreationTimeBasedIndexCache` (`index/CachingIndexCollectionManager.scala:117-160`)
expiring after `spark.hyperspace.index.cache.expiryDurationInSeconds`
(default 300 s), plus the factory seam (`index/IndexCacheFactory.scala:23-38`).
"""

from __future__ import annotations

import time
from abc import ABC, abstractmethod
from typing import Generic, Optional, TypeVar

from hyperspace_tpu.config import HyperspaceConf

T = TypeVar("T")


class Cache(ABC, Generic[T]):
    @abstractmethod
    def get(self) -> Optional[T]: ...

    @abstractmethod
    def set(self, entry: T) -> None: ...

    @abstractmethod
    def clear(self) -> None: ...


class CreationTimeBasedCache(Cache[T]):
    def __init__(self, conf: HyperspaceConf):
        self._conf = conf
        self._entry: Optional[T] = None
        self._created_at: float = 0.0

    def get(self) -> Optional[T]:
        if self._entry is None:
            return None
        if time.time() - self._created_at > self._conf.cache_expiry_seconds:
            return None
        return self._entry

    def set(self, entry: T) -> None:
        self._entry = entry
        self._created_at = time.time()

    def clear(self) -> None:
        self._entry = None
        self._created_at = 0.0


class IndexCacheFactory:
    def create(self, conf: HyperspaceConf) -> Cache:
        return CreationTimeBasedCache(conf)
