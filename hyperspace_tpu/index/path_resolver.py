"""Index name -> filesystem path resolution.

Parity: reference `index/PathResolver.scala:30-100` — system path from conf
(default `<warehouse>/indexes`), `get_index_path(name)` enumerates the system
root for a case-insensitive match and falls back to `<root>/<name>` for
new indexes.
"""

from __future__ import annotations


from hyperspace_tpu.utils import file_utils, storage

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.utils.name_utils import normalize_index_name


class PathResolver:
    def __init__(self, conf: HyperspaceConf):
        self._conf = conf

    @property
    def system_path(self) -> str:
        return self._conf.system_path

    def get_index_path(self, name: str) -> str:
        """Case-insensitive directory match (reference `PathResolver.scala:39-58`)."""
        normalized = normalize_index_name(name)
        root = self.system_path
        if file_utils.is_dir(root):
            for entry in sorted(storage.listdir_names(root)):
                if entry.lower() == normalized.lower():
                    return storage.join(root, entry)
        return storage.join(root, normalized)
