"""Plan fingerprinting: the index <-> query matching key.

Parity: reference `index/LogicalPlanSignatureProvider.scala:27-63` (trait +
factory; the provider class name is stored in index metadata and
re-instantiated by reflection at query time) and
`index/FileBasedSignatureProvider.scala:48-74` (default provider folds
`md5(accumulate + len + mtime + path)` over all files of every file-scan
leaf). Signature = data-content identity: a rewrite is legal only if the
query's relation signature equals the one captured at index-build time.
"""

from __future__ import annotations

import importlib
import os
from abc import ABC, abstractmethod
from typing import Optional

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.utils.hashing import md5_hex


def file_stamp(path: str):
    """(size, stamp) identity of one file, or None if it is missing.

    The stamp folds the backend's modification time — plus etag/generation
    where the store exposes content identity — exactly as the signature
    fold below consumes it, so `md5(acc + str(size) + stamp + path)`
    reproduces the historical signature byte-for-byte. The same (size,
    stamp) pairs are persisted per file by lineage-enabled builds
    (`index/log_entry.FileInfo`) for per-file delta classification."""
    from hyperspace_tpu.utils import storage

    if storage.is_url(path):
        fs, real = storage.get_fs(path)
        try:
            info = fs.info(real)
        except (OSError, FileNotFoundError):
            return None
        size = info.get("size", 0) or 0
        # Backends name their modification stamp differently (S3
        # LastModified, GCS updated, ABFS last_modified, memory created);
        # the etag/generation participates too so in-place rewrites that
        # preserve size+time still change the identity where the store
        # exposes content hashes.
        mtime = next((info[k] for k in ("mtime", "updated", "last_modified",
                                        "LastModified", "created")
                      if info.get(k)), 0)
        etag = (info.get("etag") or info.get("ETag")
                or info.get("generation") or "")
        return int(size), str(mtime) + str(etag)
    try:
        stat = os.stat(path)
    except OSError:
        return None
    return int(stat.st_size), str(int(stat.st_mtime_ns))


class LogicalPlanSignatureProvider(ABC):
    @classmethod
    def name(cls) -> str:
        """Fully-qualified provider name stored in index metadata."""
        return f"{cls.__module__}.{cls.__qualname__}"

    @abstractmethod
    def signature(self, plan: LogicalPlan) -> Optional[str]:
        """Signature of `plan`, or None if the plan has unsupported leaves."""


class SignatureProviderFactory:
    """Re-instantiate a provider from its stored name by reflection
    (reference `LogicalPlanSignatureProvider.scala:55-62`)."""

    @staticmethod
    def create(name: str) -> LogicalPlanSignatureProvider:
        module_name, _, cls_name = name.rpartition(".")
        try:
            module = importlib.import_module(module_name)
            cls = getattr(module, cls_name)
        except (ImportError, AttributeError, ValueError) as exc:
            raise HyperspaceException(
                f"Cannot instantiate signature provider: {name}") from exc
        if not issubclass(cls, LogicalPlanSignatureProvider):
            raise HyperspaceException(
                f"{name} is not a LogicalPlanSignatureProvider")
        return cls()


class FileBasedSignatureProvider(LogicalPlanSignatureProvider):
    """Fold md5 over (len, mtime, path) of every file of every Scan leaf,
    bottom-up (reference `FileBasedSignatureProvider.scala:48-74`). Known
    limitation kept intentionally: ignores plan *structure*, hence the join
    rule's linearity requirement (reference `JoinIndexRule.scala:194-205`).
    """

    def signature(self, plan: LogicalPlan) -> Optional[str]:
        accumulate = ""
        saw_scan = False
        for leaf in plan.collect_leaves():
            if not isinstance(leaf, Scan):
                return None
            saw_scan = True
            for path in leaf.files():
                stamp = file_stamp(path)
                if stamp is None:
                    return None
                size, tag = stamp
                accumulate = md5_hex(accumulate + str(size) + tag + path)
        return accumulate if saw_scan else None
