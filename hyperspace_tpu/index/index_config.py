"""User-facing index specification.

Parity: reference `index/IndexConfig.scala:28-166` — name + indexed columns +
included columns; case-insensitive equality; rejects empty/duplicate/
overlapping columns; fluent builder (`index_by(...)`, `include(...)`).
"""

from __future__ import annotations

from typing import Iterable, List, Sequence

from hyperspace_tpu.exceptions import HyperspaceException


class IndexConfig:
    def __init__(self, index_name: str, indexed_columns: Sequence[str],
                 included_columns: Sequence[str] = ()):
        self.index_name = index_name
        self.indexed_columns: List[str] = list(indexed_columns)
        self.included_columns: List[str] = list(included_columns)
        self._validate()

    def _validate(self) -> None:
        if not self.index_name or not self.index_name.strip():
            raise HyperspaceException("Index name cannot be empty.")
        if not self.indexed_columns:
            raise HyperspaceException("Indexed columns cannot be empty.")
        lower_indexed = [c.lower() for c in self.indexed_columns]
        lower_included = [c.lower() for c in self.included_columns]
        if len(set(lower_indexed)) < len(lower_indexed):
            raise HyperspaceException("Duplicate indexed column names are not allowed.")
        if len(set(lower_included)) < len(lower_included):
            raise HyperspaceException("Duplicate included column names are not allowed.")
        if set(lower_indexed) & set(lower_included):
            raise HyperspaceException(
                "Duplicate column names in indexed/included columns are not allowed.")

    # Case-insensitive equality (reference `index/IndexConfig.scala:44-58`).
    def __eq__(self, other) -> bool:
        if not isinstance(other, IndexConfig):
            return NotImplemented
        return (self.index_name.lower() == other.index_name.lower()
                and [c.lower() for c in self.indexed_columns]
                == [c.lower() for c in other.indexed_columns]
                and sorted(c.lower() for c in self.included_columns)
                == sorted(c.lower() for c in other.included_columns))

    def __hash__(self) -> int:
        return hash((self.index_name.lower(),
                     tuple(c.lower() for c in self.indexed_columns),
                     tuple(sorted(c.lower() for c in self.included_columns))))

    def __repr__(self) -> str:
        return (f"IndexConfig(indexName={self.index_name}, "
                f"indexedColumns={self.indexed_columns}, "
                f"includedColumns={self.included_columns})")

    class Builder:
        """Fluent builder (reference `index/IndexConfig.scala:83-166`)."""

        def __init__(self):
            self._name: str | None = None
            self._indexed: List[str] = []
            self._included: List[str] = []

        def index_name(self, name: str) -> "IndexConfig.Builder":
            if self._name is not None:
                raise HyperspaceException("Index name is already set: " + self._name)
            if not name or not name.strip():
                raise HyperspaceException("Index name cannot be empty.")
            self._name = name
            return self

        def index_by(self, column: str, *columns: str) -> "IndexConfig.Builder":
            if self._indexed:
                raise HyperspaceException("Indexed columns are already set: "
                                          + ", ".join(self._indexed))
            self._indexed = [column, *columns]
            return self

        def include(self, column: str, *columns: str) -> "IndexConfig.Builder":
            if self._included:
                raise HyperspaceException("Included columns are already set: "
                                          + ", ".join(self._included))
            self._included = [column, *columns]
            return self

        def create(self) -> "IndexConfig":
            if self._name is None or not self._indexed:
                raise HyperspaceException(
                    "Index name and indexed columns are required.")
            return IndexConfig(self._name, self._indexed, self._included)

    @staticmethod
    def builder() -> "IndexConfig.Builder":
        return IndexConfig.Builder()


SKETCH_TYPES = ("zonemap", "bloom")


class DataSkippingIndexConfig:
    """User-facing spec of a DATA-SKIPPING index (extension): which
    columns to sketch, which sketch types to build, and an optional
    multi-column Z-order clustering of the source at build time.

    `sketch_types`: "zonemap" (per-file min/max + null/NaN counts —
    serves eq/range/IN/null-ness refutation) and/or "bloom" (per-file
    blocked bloom filter over value hashes — serves eq/IN refutation
    inside wide zones). `zorder_by` non-empty additionally writes a
    Z-order-interleave-sorted rewrite of the source under the index
    root, which tightens every file's zones and lets the filter rule
    serve the query from the clustered copy."""

    def __init__(self, index_name: str, skipping_columns: Sequence[str],
                 sketch_types: Sequence[str] = SKETCH_TYPES,
                 zorder_by: Sequence[str] = ()):
        self.index_name = index_name
        self.skipping_columns: List[str] = list(skipping_columns)
        self.sketch_types: List[str] = list(sketch_types)
        self.zorder_by: List[str] = list(zorder_by)
        self._validate()

    def _validate(self) -> None:
        if not self.index_name or not self.index_name.strip():
            raise HyperspaceException("Index name cannot be empty.")
        if not self.skipping_columns:
            raise HyperspaceException("Skipping columns cannot be empty.")
        lower = [c.lower() for c in self.skipping_columns]
        if len(set(lower)) < len(lower):
            raise HyperspaceException(
                "Duplicate skipping column names are not allowed.")
        if not self.sketch_types:
            raise HyperspaceException(
                "At least one sketch type is required.")
        bad = [t for t in self.sketch_types if t not in SKETCH_TYPES]
        if bad:
            raise HyperspaceException(
                f"Unknown sketch type(s): {', '.join(bad)} "
                f"(supported: {', '.join(SKETCH_TYPES)}).")
        zlower = [c.lower() for c in self.zorder_by]
        if len(set(zlower)) < len(zlower):
            raise HyperspaceException(
                "Duplicate Z-order column names are not allowed.")

    def __eq__(self, other) -> bool:
        if not isinstance(other, DataSkippingIndexConfig):
            return NotImplemented
        return (self.index_name.lower() == other.index_name.lower()
                and [c.lower() for c in self.skipping_columns]
                == [c.lower() for c in other.skipping_columns]
                and sorted(self.sketch_types) == sorted(other.sketch_types)
                and [c.lower() for c in self.zorder_by]
                == [c.lower() for c in other.zorder_by])

    def __hash__(self) -> int:
        return hash((self.index_name.lower(),
                     tuple(c.lower() for c in self.skipping_columns),
                     tuple(sorted(self.sketch_types)),
                     tuple(c.lower() for c in self.zorder_by)))

    def __repr__(self) -> str:
        return (f"DataSkippingIndexConfig(indexName={self.index_name}, "
                f"skippingColumns={self.skipping_columns}, "
                f"sketchTypes={self.sketch_types}, "
                f"zOrderBy={self.zorder_by})")

    class Builder:
        """Fluent builder mirroring IndexConfig.Builder."""

        def __init__(self):
            self._name: str | None = None
            self._columns: List[str] = []
            self._sketches: List[str] = list(SKETCH_TYPES)
            self._zorder: List[str] = []

        def index_name(self, name: str) -> "DataSkippingIndexConfig.Builder":
            if self._name is not None:
                raise HyperspaceException(
                    "Index name is already set: " + self._name)
            if not name or not name.strip():
                raise HyperspaceException("Index name cannot be empty.")
            self._name = name
            return self

        def skip_by(self, column: str,
                    *columns: str) -> "DataSkippingIndexConfig.Builder":
            if self._columns:
                raise HyperspaceException(
                    "Skipping columns are already set: "
                    + ", ".join(self._columns))
            self._columns = [column, *columns]
            return self

        def sketches(self, *types: str) -> "DataSkippingIndexConfig.Builder":
            self._sketches = list(types)
            return self

        def zorder_by(self, column: str,
                      *columns: str) -> "DataSkippingIndexConfig.Builder":
            if self._zorder:
                raise HyperspaceException(
                    "Z-order columns are already set: "
                    + ", ".join(self._zorder))
            self._zorder = [column, *columns]
            return self

        def create(self) -> "DataSkippingIndexConfig":
            if self._name is None or not self._columns:
                raise HyperspaceException(
                    "Index name and skipping columns are required.")
            return DataSkippingIndexConfig(self._name, self._columns,
                                           self._sketches, self._zorder)

    @staticmethod
    def builder() -> "DataSkippingIndexConfig.Builder":
        return DataSkippingIndexConfig.Builder()
