"""Index catalog management: binds the lifecycle verbs to actions.

Parity: reference `index/IndexManager.scala:24-81` (trait),
`index/IndexCollectionManager.scala:26-173` (binding + catalog listing +
IndexSummary rows), `index/CachingIndexCollectionManager.scala:37-99`
(read-path caching; every mutating API clears the cache).
"""

from __future__ import annotations

import logging
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import List, Optional, Sequence

from hyperspace_tpu import constants
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.cache import Cache, IndexCacheFactory
from hyperspace_tpu.utils import file_utils, storage
from hyperspace_tpu.index.factories import (IndexDataManagerFactory,
                                            IndexLogManagerFactory)
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.path_resolver import PathResolver
from hyperspace_tpu.actions.cancel import CancelAction
from hyperspace_tpu.actions.create import CreateAction
from hyperspace_tpu.actions.delete import DeleteAction
from hyperspace_tpu.actions.optimize import OptimizeAction
from hyperspace_tpu.actions.refresh import RefreshAction
from hyperspace_tpu.actions.restore import RestoreAction
from hyperspace_tpu.actions.vacuum import VacuumAction

logger = logging.getLogger(__name__)


@dataclass
class IndexSummary:
    """Catalog row (reference `IndexCollectionManager.scala:151-173`),
    including the source plan's pretty string (`queryPlan` — the field
    round 3 omitted)."""

    name: str
    indexed_columns: List[str]
    included_columns: List[str]
    num_buckets: int
    schema_json: str
    index_location: str
    query_plan: str
    state: str
    kind: str = "CoveringIndex"

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "indexedColumns": list(self.indexed_columns),
            "includedColumns": list(self.included_columns),
            "numBuckets": self.num_buckets,
            "schema": self.schema_json,
            "indexLocation": self.index_location,
            "queryPlan": self.query_plan,
            "state": self.state,
            "kind": self.kind,
        }


def summaries_for_roots(index_summaries: Sequence[IndexSummary],
                        roots: Sequence[str]) -> List[IndexSummary]:
    """Catalog entries whose data location matches any of the scan
    `roots` (scan equality is root-path containment, the reference's
    `PlanAnalyzer.scala:209-221` convention). ONE home for the matching —
    shared by the explain "Indexes used" section and the telemetry
    index-usage reports, so the two views can never name different
    indexes for the same plan."""
    import os

    def contains(parent: str, child: str) -> bool:
        parent = os.path.normpath(parent)
        child = os.path.normpath(child)
        return child == parent or child.startswith(parent + os.sep)

    used = []
    for summary in index_summaries:
        if any(contains(summary.index_location, root)
               or contains(root, summary.index_location)
               for root in roots):
            used.append(summary)
    return used


def _pretty_plan(entry: IndexLogEntry) -> str:
    """Pretty string of the LOGGED source plan (reference stores
    `df.queryExecution.optimizedPlan.toString`,
    `IndexCollectionManager.scala:151-173`). The log keeps the serialized
    logical IR; a corrupt/unparseable record degrades to empty rather
    than failing the whole catalog listing."""
    try:
        return entry.plan().tree_string()
    except Exception:
        return ""


class IndexManager(ABC):
    """Trait parity: reference `index/IndexManager.scala:24-81`."""

    @abstractmethod
    def indexes(self) -> List[IndexSummary]: ...

    @abstractmethod
    def create(self, df, index_config: IndexConfig) -> None: ...

    @abstractmethod
    def delete(self, index_name: str) -> None: ...

    @abstractmethod
    def restore(self, index_name: str) -> None: ...

    @abstractmethod
    def vacuum(self, index_name: str) -> None: ...

    @abstractmethod
    def refresh(self, index_name: str) -> None: ...

    @abstractmethod
    def optimize(self, index_name: str) -> None: ...

    @abstractmethod
    def cancel(self, index_name: str) -> None: ...

    @abstractmethod
    def recover(self, index_name: str) -> bool: ...

    @abstractmethod
    def get_indexes(self, states: Optional[Sequence[str]] = None) -> List[IndexLogEntry]: ...


class IndexCollectionManager(IndexManager):
    def __init__(self, conf: HyperspaceConf,
                 log_manager_factory: Optional[IndexLogManagerFactory] = None,
                 data_manager_factory: Optional[IndexDataManagerFactory] = None,
                 path_resolver: Optional[PathResolver] = None):
        self.conf = conf
        self.log_manager_factory = log_manager_factory or IndexLogManagerFactory()
        self.data_manager_factory = data_manager_factory or IndexDataManagerFactory()
        self.path_resolver = path_resolver or PathResolver(conf)

    def _managers(self, index_name: str):
        path = self.path_resolver.get_index_path(index_name)
        return (self.log_manager_factory.create(path, conf=self.conf),
                self.data_manager_factory.create(path))

    def create(self, df, index_config) -> None:
        """`index_config` selects the index KIND: an `IndexConfig`
        builds a covering index, a `DataSkippingIndexConfig` builds the
        sketch-blob skipping kind — both through the same FSM."""
        log_manager, data_manager = self._managers(index_config.index_name)
        from hyperspace_tpu.index.index_config import DataSkippingIndexConfig
        if isinstance(index_config, DataSkippingIndexConfig):
            from hyperspace_tpu.actions.skipping import (
                CreateSkippingIndexAction)
            CreateSkippingIndexAction(df, index_config, log_manager,
                                      data_manager, self.conf).run()
            return
        CreateAction(df, index_config, log_manager, data_manager, self.conf).run()

    def delete(self, index_name: str) -> None:
        log_manager, _ = self._managers(index_name)
        DeleteAction(log_manager).run()

    def restore(self, index_name: str) -> None:
        log_manager, _ = self._managers(index_name)
        RestoreAction(log_manager).run()

    def vacuum(self, index_name: str) -> None:
        log_manager, data_manager = self._managers(index_name)
        VacuumAction(log_manager, data_manager, self.conf).run()

    def refresh(self, index_name: str, mode: str = "full") -> None:
        """mode 'incremental' dispatches on the index KIND recorded in
        the op log: covering indexes take the bucketed-delta path
        (RefreshIncrementalAction), data-skipping indexes the per-file
        sketch-append path (RefreshSkippingAppendAction) — both
        append-only streaming refreshes through the same FSM."""
        log_manager, data_manager = self._managers(index_name)
        if mode == "full":
            RefreshAction(log_manager, data_manager, self.conf).run()
        elif mode == "incremental":
            from hyperspace_tpu.index.log_entry import DataSkippingIndex
            latest = log_manager.get_latest_log()
            if isinstance(latest, IndexLogEntry) and \
                    isinstance(latest.derived_dataset, DataSkippingIndex):
                from hyperspace_tpu.actions.skipping import (
                    RefreshSkippingAppendAction)
                RefreshSkippingAppendAction(log_manager, data_manager,
                                            self.conf).run()
                return
            from hyperspace_tpu.actions.refresh_incremental import (
                RefreshIncrementalAction)
            RefreshIncrementalAction(log_manager, data_manager,
                                     self.conf).run()
        else:
            raise HyperspaceException(
                f"Unknown refresh mode: {mode} (use 'full' or 'incremental').")

    def optimize(self, index_name: str) -> None:
        log_manager, data_manager = self._managers(index_name)
        OptimizeAction(log_manager, data_manager, self.conf).run()

    def cancel(self, index_name: str) -> None:
        log_manager, _ = self._managers(index_name)
        CancelAction(log_manager).run()

    def recover(self, index_name: str) -> bool:
        """Force crash recovery NOW, without waiting out the maintenance
        lease: if the index's latest log entry is transient (a writer
        died between begin and end), run the Cancel FSM transition back
        to the last stable state. Returns True iff a recovery ran; a
        stable index is a no-op (unlike `cancel`, which raises), so the
        call is safe to fire on suspicion."""
        from hyperspace_tpu import telemetry
        from hyperspace_tpu.constants import STABLE_STATES

        log_manager, _ = self._managers(index_name)
        latest = log_manager.get_latest_log()
        if latest is None:
            raise HyperspaceException(f"No such index: {index_name}.")
        if latest.state in STABLE_STATES:
            return False
        CancelAction(log_manager).run()
        telemetry.get_registry().counter("resilience.recoveries").inc()
        telemetry.event("resilience", "recovered", index=index_name,
                        stale_state=latest.state, forced=True)
        return True

    def indexes(self) -> List[IndexSummary]:
        """All indexes not in DOESNOTEXIST, as summary rows (reference
        `IndexCollectionManager.scala:79-85`)."""
        out = []
        for entry in self.get_indexes():
            if entry.state == States.DOESNOTEXIST:
                continue
            out.append(IndexSummary(
                name=entry.name,
                indexed_columns=entry.indexed_columns,
                included_columns=entry.included_columns,
                num_buckets=entry.num_buckets,
                schema_json=entry.schema_json,
                index_location=entry.content.root,
                query_plan=_pretty_plan(entry),
                state=entry.state,
                kind=entry.kind))
        return out

    def indexes_df(self):
        """Catalog as a pandas DataFrame (the reference returns a Spark
        DataFrame from `hs.indexes`)."""
        import pandas as pd
        return pd.DataFrame([s.to_dict() for s in self.indexes()])

    def get_indexes(self, states: Optional[Sequence[str]] = None) -> List[IndexLogEntry]:
        """List every index dir under the system path, read each latest log,
        filter by state (reference `IndexCollectionManager.scala:87-105`)."""
        root = self.path_resolver.system_path
        if not file_utils.is_dir(root):
            return []
        entries: List[IndexLogEntry] = []
        for name in sorted(storage.listdir_names(root)):
            index_path = storage.join(root, name)
            if not file_utils.is_dir(index_path):
                continue
            log_manager = self.log_manager_factory.create(index_path,
                                                          conf=self.conf)
            try:
                entry = log_manager.get_latest_log()
            except HyperspaceException as exc:
                # One corrupt index must not take down the whole catalog.
                logger.warning("Skipping unreadable index at %s: %s",
                               index_path, exc)
                continue
            if isinstance(entry, IndexLogEntry):
                if states is None or entry.state in states:
                    entries.append(entry)
        return entries


class CachingIndexCollectionManager(IndexCollectionManager):
    """Caches `get_indexes`; mutating APIs clear the cache (reference
    `CachingIndexCollectionManager.scala:37-99`)."""

    def __init__(self, conf: HyperspaceConf, **kwargs):
        super().__init__(conf, **kwargs)
        self._cache: Cache = IndexCacheFactory().create(conf)

    def clear_cache(self) -> None:
        self._cache.clear()

    def get_indexes(self, states: Optional[Sequence[str]] = None) -> List[IndexLogEntry]:
        if states is None:
            cached = self._cache.get()
            if cached is not None:
                return cached
            entries = super().get_indexes()
            self._cache.set(entries)
            return entries
        return [e for e in self.get_indexes() if e.state in states]

    def create(self, df, index_config: IndexConfig) -> None:
        self.clear_cache()
        super().create(df, index_config)

    def delete(self, index_name: str) -> None:
        self.clear_cache()
        super().delete(index_name)

    def restore(self, index_name: str) -> None:
        self.clear_cache()
        super().restore(index_name)

    def vacuum(self, index_name: str) -> None:
        self.clear_cache()
        super().vacuum(index_name)

    def refresh(self, index_name: str, mode: str = "full") -> None:
        self.clear_cache()
        super().refresh(index_name, mode)

    def optimize(self, index_name: str) -> None:
        self.clear_cache()
        super().optimize(index_name)

    def cancel(self, index_name: str) -> None:
        self.clear_cache()
        super().cancel(index_name)

    def recover(self, index_name: str) -> bool:
        self.clear_cache()
        return super().recover(index_name)
