"""Source-file delta between an index's build-time capture and the
current lake listing.

Hybrid scan (`plan/rules/filter_index.py`) and incremental refresh
(`actions/refresh_incremental.py`) both answer the same two questions —
"which files were appended since the build?" and "are the files captured
at build time still byte-identical?" — so the derivation lives here once
(VERDICT r1 weak #6: the two copies had started to drift).
"""

from __future__ import annotations

from typing import Iterable, List, Sequence, Set, Tuple

from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import Scan


def split_current(entry: IndexLogEntry, current_files: Iterable[str]
                  ) -> Tuple[List[str], Set[str], Set[str]]:
    """(appended, missing, stored): current files not captured at build
    time (deduplicated — overlapping scan roots may list a file twice),
    captured files no longer listed (deleted/renamed — either disqualifies
    append-only serving), and the build-time capture itself."""
    stored = set(entry.source_file_list())
    current = set(current_files)
    appended = sorted(current - stored)
    missing = stored - current
    return appended, missing, stored


def classify_current(entry: IndexLogEntry, current_files: Iterable[str]):
    """Per-file delta classification for lineage-enabled indexes:
    (appended, deleted_ids, modified) where `appended` are current files
    not captured at build time, `deleted_ids` the lineage ids of captured
    files no longer listed, and `modified` captured files whose (size,
    stamp) identity changed in place. None when the entry carries no
    per-file stamps (pre-lineage builds fall back to the aggregate
    signature over `restricted_scan`).

    Unlike the aggregate path this works when captured files are GONE —
    survivors are verified individually, so hybrid scan can exclude the
    deleted files' rows instead of losing the index."""
    from hyperspace_tpu.index.signature import file_stamp

    infos = entry.source_file_infos()
    if infos is None or not entry.has_lineage:
        return None
    current = set(current_files)
    appended = sorted(current - infos.keys())
    deleted_ids = sorted(fi.id for p, fi in infos.items()
                         if p not in current)
    modified = sorted(p for p, fi in infos.items() if p in current
                      and file_stamp(p) != (fi.size, fi.stamp))
    return appended, deleted_ids, modified


def restricted_scan(entry: IndexLogEntry, scan: Scan,
                    stored: Sequence[str]) -> Scan:
    """The scan narrowed to EXACTLY the build-time file set. Recomputing
    the signature over it and comparing with the stored one proves the
    captured files are untouched — a path-set check alone misses files
    rewritten in place with the same name."""
    return Scan(scan.root_paths, scan.schema, files=sorted(stored))
