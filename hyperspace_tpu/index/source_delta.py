"""Source-file delta between an index's build-time capture and the
current lake listing.

Hybrid scan (`plan/rules/filter_index.py`) and incremental refresh
(`actions/refresh_incremental.py`) both answer the same two questions —
"which files were appended since the build?" and "are the files captured
at build time still byte-identical?" — so the derivation lives here once
(VERDICT r1 weak #6: the two copies had started to drift).
"""

from __future__ import annotations

from typing import Iterable, List, Sequence, Set, Tuple

from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import Scan


def split_current(entry: IndexLogEntry, current_files: Iterable[str]
                  ) -> Tuple[List[str], Set[str], Set[str]]:
    """(appended, missing, stored): current files not captured at build
    time (deduplicated — overlapping scan roots may list a file twice),
    captured files no longer listed (deleted/renamed — either disqualifies
    append-only serving), and the build-time capture itself."""
    stored = set(entry.source_file_list())
    current = set(current_files)
    appended = sorted(current - stored)
    missing = stored - current
    return appended, missing, stored


def restricted_scan(entry: IndexLogEntry, scan: Scan,
                    stored: Sequence[str]) -> Scan:
    """The scan narrowed to EXACTLY the build-time file set. Recomputing
    the signature over it and comparing with the stored one proves the
    captured files are untouched — a path-set check alone misses files
    rewritten in place with the same name."""
    return Scan(scan.root_paths, scan.schema, files=sorted(stored))
