from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.log_entry import (
    Content,
    CoveringIndex,
    Directory,
    Hdfs,
    IndexLogEntry,
    LogEntry,
    LogicalPlanFingerprint,
    NoOpFingerprint,
    PlanSource,
    Signature,
    Source,
)

__all__ = [
    "IndexConfig",
    "Content",
    "CoveringIndex",
    "Directory",
    "Hdfs",
    "IndexLogEntry",
    "LogEntry",
    "LogicalPlanFingerprint",
    "NoOpFingerprint",
    "PlanSource",
    "Signature",
    "Source",
]
