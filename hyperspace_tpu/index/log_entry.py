"""Versioned, JSON-serialized index metadata records.

Parity: reference `index/LogEntry.scala:22-47` (LogEntry base with mutable
id/state/timestamp/enabled and version-dispatched `fromJson`) and
`index/IndexLogEntry.scala:27-131` (the metadata tree: Content, CoveringIndex,
Signature, LogicalPlanFingerprint, plan source, HDFS source data, helpers).
The serialized shape (kind/properties nesting, version/id/state/timestamp/
enabled tail fields) follows the reference's spec pinned by
`index/IndexLogEntryTest.scala:33-91`, with `source.plan.kind == "Plan"`
holding this framework's own relational-IR JSON instead of a Kryo-serialized
Catalyst plan.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from hyperspace_tpu.exceptions import HyperspaceException

VERSION = "0.1"


@dataclass
class NoOpFingerprint:
    """Placeholder directory fingerprint (reference `IndexLogEntry.scala:27-30`)."""

    kind: str = "NoOp"
    properties: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"kind": self.kind, "properties": dict(self.properties)}

    @staticmethod
    def from_dict(d: dict) -> "NoOpFingerprint":
        return NoOpFingerprint(d.get("kind", "NoOp"), d.get("properties", {}))


@dataclass
class FileInfo:
    """Per-file identity stamp + lineage id (extension: the surveyed
    reference stores bare paths; per-file (size, stamp) records with stable
    ids are its v0.2 lineage direction — they let hybrid scan classify each
    current file as untouched / appended / deleted and serve queries over a
    source with deletions by excluding that file's index rows)."""

    name: str
    size: int
    stamp: str  # mtime_ns locally; mtime+etag/generation on object stores
    id: int

    def to_list(self) -> list:
        return [self.name, self.size, self.stamp, self.id]

    @staticmethod
    def from_list(x: list) -> "FileInfo":
        return FileInfo(x[0], int(x[1]), str(x[2]), int(x[3]))


@dataclass
class Directory:
    """A directory of index/source files (reference `IndexLogEntry.scala:33-36`).

    `file_infos` (optional) carries per-file stamps + lineage ids; when
    absent the serialized shape is byte-identical to the reference spec."""

    path: str
    files: List[str] = field(default_factory=list)
    fingerprint: NoOpFingerprint = field(default_factory=NoOpFingerprint)
    file_infos: Optional[List[FileInfo]] = None

    def to_dict(self) -> dict:
        d = {"path": self.path, "files": list(self.files),
             "fingerprint": self.fingerprint.to_dict()}
        if self.file_infos is not None:
            d["fileInfos"] = [fi.to_list() for fi in self.file_infos]
        return d

    @staticmethod
    def from_dict(d: dict) -> "Directory":
        infos = d.get("fileInfos")
        return Directory(d["path"], list(d.get("files", [])),
                         NoOpFingerprint.from_dict(d.get("fingerprint", {})),
                         None if infos is None
                         else [FileInfo.from_list(x) for x in infos])


@dataclass
class Content:
    """Root + directories of content (reference `IndexLogEntry.scala:33-36`)."""

    root: str
    directories: List[Directory] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"root": self.root,
                "directories": [x.to_dict() for x in self.directories]}

    @staticmethod
    def from_dict(d: dict) -> "Content":
        return Content(d.get("root", ""),
                       [Directory.from_dict(x) for x in d.get("directories", [])])


@dataclass
class CoveringIndex:
    """Derived-dataset spec (reference `IndexLogEntry.scala:39-47`).

    `schema_json` is the JSON-serialized schema of indexed+included columns
    (this framework's `plan/schema.py` format rather than Spark StructType).
    """

    indexed_columns: List[str]
    included_columns: List[str]
    schema_json: str
    num_buckets: int

    kind: str = "CoveringIndex"

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "properties": {
                "columns": {
                    "indexed": list(self.indexed_columns),
                    "included": list(self.included_columns),
                },
                "schemaString": self.schema_json,
                "numBuckets": self.num_buckets,
            },
        }

    @staticmethod
    def from_dict(d: dict) -> "CoveringIndex":
        p = d["properties"]
        return CoveringIndex(
            indexed_columns=list(p["columns"]["indexed"]),
            included_columns=list(p["columns"]["included"]),
            schema_json=p["schemaString"],
            num_buckets=int(p["numBuckets"]),
            kind=d.get("kind", "CoveringIndex"))

    @classmethod
    def _serde_sample(cls) -> "CoveringIndex":
        """A representative instance for the serde round-trip lint
        (`scripts/check_metrics_coverage.py::check_index_kind_serde`)."""
        return cls(["a"], ["b", "c"], "[]", 8)


@dataclass
class DataSkippingIndex:
    """Derived-dataset spec of a DATA-SKIPPING index (extension; the
    covering index's lightweight sibling — SURVEY §1's "hybrid scan +
    incremental refresh" ecosystem). The index data is a compact
    per-source-file sketch blob (min/max zone maps + blocked bloom
    filters, `index/sketch.py`), not a copy of the rows; `zorder_by`
    non-empty means the build ALSO wrote a Z-order-clustered rewrite of
    the source under the index root, which the filter rule can serve
    pruned reads from (`schema_json` then carries the full source
    schema; otherwise just the sketched columns)."""

    skipped_columns: List[str]
    sketch_types: List[str]
    schema_json: str
    zorder_by: List[str] = field(default_factory=list)

    kind: str = "DataSkippingIndex"

    # Catalog/summary surface shared with CoveringIndex (the manager's
    # IndexSummary rows read these off any derived dataset).
    @property
    def indexed_columns(self) -> List[str]:
        return list(self.skipped_columns)

    @property
    def included_columns(self) -> List[str]:
        return []

    @property
    def num_buckets(self) -> int:
        return 0  # sketch blobs are not bucketed

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "properties": {
                "columns": {"skipped": list(self.skipped_columns)},
                "sketchTypes": list(self.sketch_types),
                "zOrderBy": list(self.zorder_by),
                "schemaString": self.schema_json,
            },
        }

    @staticmethod
    def from_dict(d: dict) -> "DataSkippingIndex":
        p = d["properties"]
        return DataSkippingIndex(
            skipped_columns=list(p["columns"]["skipped"]),
            sketch_types=list(p.get("sketchTypes", [])),
            schema_json=p["schemaString"],
            zorder_by=list(p.get("zOrderBy", [])),
            kind=d.get("kind", "DataSkippingIndex"))

    @classmethod
    def _serde_sample(cls) -> "DataSkippingIndex":
        """A representative instance for the serde round-trip lint
        (`scripts/check_metrics_coverage.py::check_index_kind_serde`)."""
        return cls(["a", "b"], ["zonemap", "bloom"], "[]", ["a", "b"])


# THE index-kind serde registry: `IndexLogEntry.from_dict` dispatches the
# `derivedDataset.kind` field through it, so a second index kind flows
# through the same log/action FSM as the covering index. Every class here
# must round-trip `from_dict(x.to_dict()) == x` and provide a
# `_serde_sample()` — `scripts/check_metrics_coverage.py` fails any
# index-kind class in this module that is missing from the registry or
# whose round-trip breaks.
DERIVED_DATASET_KINDS: Dict[str, Any] = {
    "CoveringIndex": CoveringIndex,
    "DataSkippingIndex": DataSkippingIndex,
}


def derived_dataset_from_dict(d: dict):
    kind = d.get("kind", "CoveringIndex")
    cls = DERIVED_DATASET_KINDS.get(kind)
    if cls is None:
        raise HyperspaceException(f"Unknown derived-dataset kind: {kind}")
    return cls.from_dict(d)


@dataclass
class Signature:
    """Provider-name + value pair (reference `IndexLogEntry.scala:50`)."""

    provider: str
    value: str

    def to_dict(self) -> dict:
        return {"provider": self.provider, "value": self.value}

    @staticmethod
    def from_dict(d: dict) -> "Signature":
        return Signature(d["provider"], d["value"])


@dataclass
class LogicalPlanFingerprint:
    """Fingerprint of the source logical plan (reference `IndexLogEntry.scala:53-58`)."""

    signatures: List[Signature] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"kind": "LogicalPlan",
                "properties": {"signatures": [s.to_dict() for s in self.signatures]}}

    @staticmethod
    def from_dict(d: dict) -> "LogicalPlanFingerprint":
        sigs = d.get("properties", {}).get("signatures", [])
        return LogicalPlanFingerprint([Signature.from_dict(s) for s in sigs])


@dataclass
class PlanSource:
    """Serialized source plan (reference `SparkPlan` node, `IndexLogEntry.scala:61-66`;
    kind is "Plan" here because rawPlan holds this framework's relational-IR
    JSON, not a Spark plan)."""

    raw_plan: str
    fingerprint: LogicalPlanFingerprint

    kind: str = "Plan"

    def to_dict(self) -> dict:
        return {"kind": self.kind,
                "properties": {"rawPlan": self.raw_plan,
                               "fingerprint": self.fingerprint.to_dict()}}

    @staticmethod
    def from_dict(d: dict) -> "PlanSource":
        p = d["properties"]
        return PlanSource(p["rawPlan"],
                          LogicalPlanFingerprint.from_dict(p["fingerprint"]),
                          kind=d.get("kind", "Plan"))


@dataclass
class Hdfs:
    """Source data file listing (reference `Hdfs` node, `IndexLogEntry.scala:69-74`;
    kind string "HDFS" is kept for wire-format parity — content is any
    posix-visible file listing)."""

    content: Content
    kind: str = "HDFS"

    def to_dict(self) -> dict:
        return {"kind": self.kind, "properties": {"content": self.content.to_dict()}}

    @staticmethod
    def from_dict(d: dict) -> "Hdfs":
        return Hdfs(Content.from_dict(d["properties"]["content"]),
                    kind=d.get("kind", "HDFS"))


@dataclass
class Source:
    """Plan + data provenance of an index (reference `IndexLogEntry.scala:77`)."""

    plan: PlanSource
    data: List[Hdfs] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"plan": self.plan.to_dict(), "data": [x.to_dict() for x in self.data]}

    @staticmethod
    def from_dict(d: dict) -> "Source":
        return Source(PlanSource.from_dict(d["plan"]),
                      [Hdfs.from_dict(x) for x in d.get("data", [])])


class LogEntry:
    """Base log record with mutable id/state/timestamp/enabled.

    Parity: reference `index/LogEntry.scala:22-47`; `from_json` dispatches on
    the `version` field.
    """

    def __init__(self, version: str = VERSION):
        self.version = version
        self.id: int = 0
        self.state: str = ""
        self.timestamp: int = int(time.time() * 1000)
        self.enabled: bool = True

    def _tail_dict(self) -> dict:
        return {"version": self.version, "id": self.id, "state": self.state,
                "timestamp": self.timestamp, "enabled": self.enabled}

    def _load_tail(self, d: dict) -> None:
        self.version = d.get("version", VERSION)
        self.id = int(d.get("id", 0))
        self.state = d.get("state", "")
        self.timestamp = int(d.get("timestamp", 0))
        self.enabled = bool(d.get("enabled", True))

    def to_dict(self) -> dict:
        return self._tail_dict()

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @staticmethod
    def from_json(text: str) -> "LogEntry":
        d = json.loads(text)
        version = d.get("version")
        if version != VERSION:
            raise HyperspaceException(f"Unsupported log entry version: {version}")
        if "name" in d:
            return IndexLogEntry.from_dict(d)
        entry = LogEntry()
        entry._load_tail(d)
        return entry


class IndexLogEntry(LogEntry):
    """The on-disk index spec (reference `index/IndexLogEntry.scala:80-125`)."""

    def __init__(self, name: str, derived_dataset,
                 content: Content, source: Source,
                 extra: Optional[Dict[str, Any]] = None):
        super().__init__()
        self.name = name
        # Any registered index kind (DERIVED_DATASET_KINDS): CoveringIndex
        # or DataSkippingIndex.
        self.derived_dataset = derived_dataset
        self.content = content
        self.source = source
        self.extra: Dict[str, Any] = dict(extra or {})

    # Helpers (reference `IndexLogEntry.scala:96-124`).

    @property
    def kind(self) -> str:
        """The derived dataset's kind string — what the rewrite rules
        discriminate on ("CoveringIndex" / "DataSkippingIndex")."""
        return self.derived_dataset.kind

    @property
    def schema_json(self) -> str:
        return self.derived_dataset.schema_json

    @property
    def created(self) -> bool:
        from hyperspace_tpu.constants import States
        return self.state == States.ACTIVE

    @property
    def indexed_columns(self) -> List[str]:
        return self.derived_dataset.indexed_columns

    @property
    def included_columns(self) -> List[str]:
        return self.derived_dataset.included_columns

    @property
    def num_buckets(self) -> int:
        return self.derived_dataset.num_buckets

    @property
    def shard_layout(self) -> Optional[Dict[str, Any]]:
        """The born-sharded layout record of this version's data
        (extension; `io/builder.write_shard_layout`): `numShards` and
        the per-shard contiguous `bucketRanges` the build wrote its
        per-device parquet shards under. None for single-device builds.
        The SPMD read path re-derives ownership from the SAME map
        (`parallel/mesh.bucket_ranges`), so this record is provenance —
        a reader on ANY mesh size can consume the data; a reader on the
        RECORDED size refills each device exactly its own files."""
        layout = self.extra.get("shardLayout")
        return dict(layout) if isinstance(layout, dict) else None

    @property
    def raw_plan(self) -> str:
        return self.source.plan.raw_plan

    def plan(self):
        """Deserialize the logged relational plan (reference
        `IndexLogEntry.scala:112-116` deserializes rawPlan)."""
        from hyperspace_tpu.plan.serde import plan_from_json
        return plan_from_json(self.source.plan.raw_plan)

    def signature(self) -> Signature:
        sigs = self.source.plan.fingerprint.signatures
        if len(sigs) != 1:
            raise HyperspaceException(
                "Expected exactly one signature, found: " + str(len(sigs)))
        return sigs[0]

    def source_file_list(self) -> List[str]:
        files: List[str] = []
        for hdfs in self.source.data:
            root = hdfs.content.root
            for directory in hdfs.content.directories:
                base = directory.path or root
                for f in directory.files:
                    files.append(f if "/" in f else (base.rstrip("/") + "/" + f if base else f))
        return files

    def source_file_infos(self) -> Optional[Dict[str, FileInfo]]:
        """{absolute path: FileInfo} when per-file lineage stamps were
        captured at build time (lineage-enabled builds); None otherwise
        (including partially-stamped entries, which are treated as
        stampless rather than trusted)."""
        out: Dict[str, FileInfo] = {}
        for hdfs in self.source.data:
            root = hdfs.content.root
            for directory in hdfs.content.directories:
                if directory.file_infos is None:
                    return None
                base = directory.path or root
                for fi in directory.file_infos:
                    path = (fi.name if "/" in fi.name else
                            (base.rstrip("/") + "/" + fi.name
                             if base else fi.name))
                    out[path] = fi
        return out if out else None

    @property
    def has_lineage(self) -> bool:
        """True when the index data carries the per-row lineage column."""
        from hyperspace_tpu.constants import LINEAGE_COLUMN
        from hyperspace_tpu.plan.schema import Schema
        return Schema.from_json(self.schema_json).contains(LINEAGE_COLUMN)

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "derivedDataset": self.derived_dataset.to_dict(),
            "content": self.content.to_dict(),
            "source": self.source.to_dict(),
            "extra": dict(self.extra),
        }
        d.update(self._tail_dict())
        return d

    @staticmethod
    def from_dict(d: dict) -> "IndexLogEntry":
        entry = IndexLogEntry(
            name=d["name"],
            derived_dataset=derived_dataset_from_dict(d["derivedDataset"]),
            content=Content.from_dict(d["content"]),
            source=Source.from_dict(d["source"]),
            extra=d.get("extra", {}))
        entry._load_tail(d)
        return entry

    def __eq__(self, other) -> bool:
        if not isinstance(other, IndexLogEntry):
            return NotImplemented
        return self.to_dict() == other.to_dict()

    def __hash__(self) -> int:
        return hash((self.name, self.id, self.state))

    def copy_with_state(self, state: str) -> "IndexLogEntry":
        """Clone with a different lifecycle state (test helper parity:
        reference `TestUtils.copyWithState`, `TestUtils.scala:21-27`)."""
        clone = IndexLogEntry.from_dict(self.to_dict())
        clone.state = state
        return clone
