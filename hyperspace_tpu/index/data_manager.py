"""Versioned index data directories.

Parity: reference `index/IndexDataManager.scala:24-73` — index data lives in
`<indexRoot>/v__=<N>/` (Hive-partition-style naming); refresh writes N+1,
vacuum deletes all versions. Layout doc: reference
`docs/_docs/14-toh-indexes-on-the-lake.md:16-27`.
"""

from __future__ import annotations

import os
from abc import ABC, abstractmethod
from typing import List, Optional

from hyperspace_tpu import constants
from hyperspace_tpu.utils import file_utils, storage


class IndexDataManager(ABC):
    """Trait parity: reference `index/IndexDataManager.scala:38-44`."""

    @abstractmethod
    def get_latest_version_id(self) -> Optional[int]: ...

    @abstractmethod
    def get_path(self, version_id: int) -> str: ...

    @abstractmethod
    def delete(self, version_id: int) -> None: ...


class IndexDataManagerImpl(IndexDataManager):
    def __init__(self, index_path: str):
        self.index_path = index_path

    def _version_dirs(self) -> List[int]:
        if not file_utils.is_dir(self.index_path):
            return []
        prefix = constants.INDEX_VERSION_DIRECTORY_PREFIX + "="
        out = []
        for name in storage.listdir_names(self.index_path):
            if name.startswith(prefix) and name[len(prefix):].isdigit():
                out.append(int(name[len(prefix):]))
        return sorted(out)

    def get_latest_version_id(self) -> Optional[int]:
        """Scan `v__=N` dir names (reference `IndexDataManager.scala:55-66`)."""
        versions = self._version_dirs()
        return versions[-1] if versions else None

    def get_path(self, version_id: int) -> str:
        return os.path.join(
            self.index_path,
            f"{constants.INDEX_VERSION_DIRECTORY_PREFIX}={version_id}")

    def delete(self, version_id: int) -> None:
        file_utils.delete(self.get_path(version_id))
