"""Versioned index data directories with crash-consistent commits.

Parity: reference `index/IndexDataManager.scala:24-73` — index data lives in
`<indexRoot>/v__=<N>/` (Hive-partition-style naming); refresh writes N+1,
vacuum deletes all versions. Layout doc: reference
`docs/_docs/14-toh-indexes-on-the-lake.md:16-27`.

Crash consistency (extension): every data-writing action finalizes its
`v__=N` dir with a `_committed` marker written LAST (Delta-style). Readers
asking for the CURRENT version (`get_latest_version_id`) only see committed
dirs, so a build that crashed mid-write can never be served; writers asking
for the NEXT version (`next_version_id`) see ALL dirs, so a crashed build's
partial dir is skipped — never mixed into — and vacuum's hard delete
(`all_version_ids`) sweeps partial dirs with everything else.
"""

from __future__ import annotations

import json
import os
import time
from abc import ABC, abstractmethod
from typing import List, Optional

from hyperspace_tpu import constants
from hyperspace_tpu.utils import file_utils, storage


class IndexDataManager(ABC):
    """Trait parity: reference `index/IndexDataManager.scala:38-44`, plus
    the commit-marker protocol. The commit/enumeration methods have
    working defaults so metadata-only fakes stay three methods."""

    @abstractmethod
    def get_latest_version_id(self) -> Optional[int]:
        """Latest COMMITTED version — the serving contract."""

    @abstractmethod
    def get_path(self, version_id: int) -> str: ...

    @abstractmethod
    def delete(self, version_id: int) -> None: ...

    def all_version_ids(self) -> List[int]:
        """Every version that physically exists, committed or not —
        vacuum's hard-delete contract. Default derives a dense range from
        the latest id (fakes); the filesystem impl lists real dirs, so
        sparse/partially-vacuumed layouts enumerate correctly."""
        latest = self.get_latest_version_id()
        return list(range(latest + 1)) if latest is not None else []

    def next_version_id(self) -> int:
        """First version id no dir (committed OR partial) occupies — the
        writing contract; skipping partial dirs keeps a new build from
        mixing files with a crashed one's leftovers."""
        ids = self.all_version_ids()
        return (max(ids) + 1) if ids else 0

    def commit(self, version_id: int, touched_buckets=None,
               carried_from=None) -> None:
        """Finalize a fully-written version (no-op for fakes).
        `touched_buckets`/`carried_from` is the bucket-scoped
        invalidation channel: an incremental refresh that carried the
        previous version's runs forward names exactly the bucket ids it
        rewrote, so the segment cache keeps (rekeys) warm entries of
        every other bucket instead of torching the whole set."""

    def is_committed(self, version_id: int) -> bool:
        return True


class IndexDataManagerImpl(IndexDataManager):
    def __init__(self, index_path: str):
        self.index_path = index_path

    def _version_dirs(self, committed_only: bool = False) -> List[int]:
        if not file_utils.is_dir(self.index_path):
            return []
        prefix = constants.INDEX_VERSION_DIRECTORY_PREFIX + "="
        out = []
        for name in storage.listdir_names(self.index_path):
            if name.startswith(prefix) and name[len(prefix):].isdigit():
                version = int(name[len(prefix):])
                if committed_only and not self.is_committed(version):
                    continue
                out.append(version)
        return sorted(out)

    def get_latest_version_id(self) -> Optional[int]:
        """Latest `v__=N` dir carrying the commit marker (reference
        `IndexDataManager.scala:55-66`, hardened: a crashed build's
        partial dir is invisible here)."""
        versions = self._version_dirs(committed_only=True)
        return versions[-1] if versions else None

    def all_version_ids(self) -> List[int]:
        return self._version_dirs()

    def get_path(self, version_id: int) -> str:
        return os.path.join(
            self.index_path,
            f"{constants.INDEX_VERSION_DIRECTORY_PREFIX}={version_id}")

    def _marker_path(self, version_id: int) -> str:
        return os.path.join(self.get_path(version_id),
                            constants.INDEX_DATA_COMMIT_MARKER)

    def commit(self, version_id: int, touched_buckets=None,
               carried_from=None) -> None:
        """Write the `_committed` marker — the LAST write of a build; the
        version is served only after this lands. Committing is also THE
        cache-invalidation event for the version bump: every
        data-writing action (create/refresh/incremental/optimize)
        funnels through here, so the HBM segment cache and the stamped
        host caches learn about new bytes at exactly the boundary where
        they become servable — not via per-action ad-hoc clears. An
        incremental refresh passes `touched_buckets` + `carried_from`
        so the cache invalidates bucket-scoped (rekeying untouched
        buckets' warm entries to the new version) instead of torching
        the whole warm set."""
        file_utils.create_file(
            self._marker_path(version_id),
            json.dumps({"committedAtMs": int(time.time() * 1000)}))
        from hyperspace_tpu.io import segcache
        segcache.on_version_committed(self.index_path, version_id,
                                      touched_buckets=touched_buckets,
                                      carried_from=carried_from)

    def is_committed(self, version_id: int) -> bool:
        return file_utils.exists(self._marker_path(version_id))

    def delete(self, version_id: int) -> None:
        file_utils.delete(self.get_path(version_id))
        # Vacuum's hard delete: the version's bytes are gone from disk,
        # so its segments must leave HBM (and the host caches) too.
        from hyperspace_tpu.io import segcache
        segcache.on_version_deleted(self.index_path, version_id)
