"""In-flight version-pin registry: vacuum-vs-read race safety.

Snapshot pinning (PR-9) freezes the *listing* a scan reads — the plan
records the committed version directory and its files at optimization
time — but nothing previously stopped `VacuumAction` from deleting that
directory while the read was mid-flight. Two guarantees close the race:

1. **Defer behind the pin** — executing index scans register their
   version directories here for the duration of the read; vacuum checks
   `is_pinned` before each version delete and backs off (bounded,
   jittered, via `utils/retry.py`) while a reader holds the pin. A
   version still pinned after the backoff budget is *skipped*, not
   force-deleted — the directory becomes harmless garbage and the
   deferral is counted (`resilience.vacuum.deferred`).
2. **Typed surface** — if the delete wins anyway (pin registered after
   vacuum's check, or a different process vacuumed), the read fails
   inside `ScanExec`'s guard and surfaces as a typed
   `IndexDataUnavailableError`, which the scheduler converts into a
   source-plan fallback (PR-4). Never a raw mid-query
   `FileNotFoundError`.

The registry is process-wide (module-level) because pins must be
visible across sessions sharing a warehouse in one process — the same
scoping the segment cache uses. Refcounted: concurrent readers of the
same version each hold a pin; the path unpins when the last releases.
"""

import contextlib
import os
import threading
from typing import Dict, Iterable, Iterator

_lock = threading.Lock()
_pins: Dict[str, int] = {}


def _norm(path: str) -> str:
    return os.path.normpath(str(path))


def pin(path: str) -> None:
    """Register one reader of `path` (a committed version directory)."""
    key = _norm(path)
    with _lock:
        _pins[key] = _pins.get(key, 0) + 1


def unpin(path: str) -> None:
    """Release one reader of `path`; no-op if it was never pinned."""
    key = _norm(path)
    with _lock:
        count = _pins.get(key, 0)
        if count <= 1:
            _pins.pop(key, None)
        else:
            _pins[key] = count - 1


def is_pinned(path: str) -> bool:
    """True while any in-flight read holds `path` pinned."""
    with _lock:
        return _pins.get(_norm(path), 0) > 0


def active_pins() -> int:
    """Distinct pinned paths right now (telemetry/test visibility)."""
    with _lock:
        return len(_pins)


@contextlib.contextmanager
def pinned(paths: Iterable[str]) -> Iterator[None]:
    """Hold pins on every path for the duration of the block."""
    held = [_norm(p) for p in paths]
    for p in held:
        pin(p)
    try:
        yield
    finally:
        for p in held:
            unpin(p)
