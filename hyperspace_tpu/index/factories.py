"""Dependency-injection seams for log/data managers.

Parity: reference `index/factories.scala:22-50` — the injection points tests
use to substitute fakes.
"""

from __future__ import annotations

from hyperspace_tpu.index.data_manager import IndexDataManager, IndexDataManagerImpl
from hyperspace_tpu.index.log_manager import IndexLogManager, IndexLogManagerImpl


class IndexLogManagerFactory:
    def create(self, index_path: str, conf=None) -> IndexLogManager:
        return IndexLogManagerImpl(index_path, conf=conf)


class IndexDataManagerFactory:
    def create(self, index_path: str) -> IndexDataManager:
        return IndexDataManagerImpl(index_path)
