"""Per-index operation log with optimistic concurrency.

Parity: reference `index/IndexLogManager.scala:32-157` — log lives at
`<indexRoot>/_hyperspace_log/<id>` (monotonically increasing integer
filenames) plus a `latestStable` copy. `write_log(id, entry)` fails if `<id>`
exists, else publishes atomically — exactly one concurrent writer wins an id
(the reference's temp-file + atomic-rename OCC, `IndexLogManager.scala:139-156`;
here `atomic_write_if_absent` in `util/file_utils.py`).
"""

from __future__ import annotations

import json
import os
from abc import ABC, abstractmethod
from typing import Optional

from hyperspace_tpu import constants
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.log_entry import LogEntry
from hyperspace_tpu.utils import storage
from hyperspace_tpu.utils import file_utils
from hyperspace_tpu.utils import retry


class IndexLogManager(ABC):
    """Trait parity: reference `index/IndexLogManager.scala:32-54`."""

    @abstractmethod
    def get_log(self, log_id: int) -> Optional[LogEntry]: ...

    @abstractmethod
    def get_latest_id(self) -> Optional[int]: ...

    @abstractmethod
    def get_latest_log(self) -> Optional[LogEntry]: ...

    @abstractmethod
    def get_latest_stable_log(self) -> Optional[LogEntry]: ...

    @abstractmethod
    def create_latest_stable_log(self, log_id: int) -> bool: ...

    @abstractmethod
    def delete_latest_stable_log(self) -> bool: ...

    @abstractmethod
    def write_log(self, log_id: int, entry: LogEntry) -> bool: ...

    # Action reports (observability sidecar, not part of the OCC
    # protocol): default no-ops so in-memory/test managers need not
    # care. `get_latest_id` only parses all-digit filenames, so the
    # `<id>.report.json` sidecars never perturb log-id resolution.

    def write_action_report(self, log_id: int, report: dict) -> bool:
        """Persist a structured action report next to log `<log_id>`."""
        return False

    def get_action_report(self, log_id: int) -> Optional[dict]:
        return None


class IndexLogManagerImpl(IndexLogManager):
    """Filesystem-backed impl (reference `index/IndexLogManager.scala:56-157`).

    `conf` (optional) carries `spark.hyperspace.single.writer`: on object
    stores with no create precondition, write_log RAISES unless that conf
    explicitly accepts check-then-create semantics."""

    def __init__(self, index_path: str, conf=None):
        self.index_path = index_path
        self.log_dir = os.path.join(index_path, constants.HYPERSPACE_LOG)
        self.conf = conf

    def _single_writer(self) -> bool:
        if self.conf is None:
            return False
        return (self.conf.get(constants.SINGLE_WRITER, "false")
                or "false").lower() == "true"

    def _path_for(self, log_id: int) -> str:
        return os.path.join(self.log_dir, str(log_id))

    def _read_entry(self, path: str) -> tuple[LogEntry, str]:
        """Read + parse a log file through the retry seam: transient IO
        errors retry per policy, and so do torn reads (on no-hardlink
        filesystems the OCC fallback publishes the filename before its
        contents — see file_utils.atomic_write_if_absent — so a parse
        failure may just mean the writer hasn't finished). A read that
        stays unparseable through the policy is a genuinely corrupt
        entry. ALL log-file reads must come through here, not just
        get_log."""

        def read():
            contents = file_utils.read_contents(path)
            return LogEntry.from_json(contents), contents

        try:
            return retry.call(read, operation=f"log.read:{path}",
                              policy=retry.policy_for(self.conf),
                              retryable=(json.JSONDecodeError, ValueError))
        except (json.JSONDecodeError, ValueError) as exc:
            raise HyperspaceException(
                f"Corrupt log entry at {path}: {exc}")

    def get_log(self, log_id: int) -> Optional[LogEntry]:
        path = self._path_for(log_id)
        if not file_utils.exists(path):
            return None
        entry, _ = self._read_entry(path)
        return entry

    def get_latest_id(self) -> Optional[int]:
        """Max numeric filename (reference `IndexLogManager.scala:80-89`)."""
        if not file_utils.is_dir(self.log_dir):
            return None
        ids = [int(name) for name in storage.listdir_names(self.log_dir)
               if name.isdigit()]
        return max(ids) if ids else None

    def get_latest_log(self) -> Optional[LogEntry]:
        latest = self.get_latest_id()
        return self.get_log(latest) if latest is not None else None

    def get_latest_stable_log(self) -> Optional[LogEntry]:
        """Read `latestStable`, else scan ids downward for a stable state
        (reference `IndexLogManager.scala:91-110`)."""
        stable_path = os.path.join(self.log_dir, constants.LATEST_STABLE_LOG)
        if file_utils.exists(stable_path):
            entry, _ = self._read_entry(stable_path)
            return entry
        latest = self.get_latest_id()
        if latest is None:
            return None
        for log_id in range(latest, -1, -1):
            entry = self.get_log(log_id)
            if entry is not None and entry.state in constants.STABLE_STATES:
                return entry
        return None

    def create_latest_stable_log(self, log_id: int) -> bool:
        """Copy `<id>` -> `latestStable` (reference `IndexLogManager.scala:112-122`).

        The copy publishes ATOMICALLY (temp file + rename locally, one
        object put on stores): `latestStable` is rewritten in place, so a
        reader racing a plain streamed write could observe a torn JSON —
        the one log file the OCC torn-read retry does not protect (a torn
        id file means "writer still publishing"; a torn latestStable used
        to parse as corruption). Transient write failures retry per the
        io.retry policy."""
        source = self._path_for(log_id)
        if not file_utils.exists(source):
            return False
        entry, contents = self._read_entry(source)
        if entry.state not in constants.STABLE_STATES:
            return False
        stable_path = os.path.join(self.log_dir, constants.LATEST_STABLE_LOG)
        retry.call(lambda: file_utils.atomic_publish(stable_path, contents),
                   operation=f"log.latest_stable:{stable_path}",
                   policy=retry.policy_for(self.conf))
        # Index-FSM invalidation hook for the metadata-only terminal
        # transitions: publishing a DELETED/DOESNOTEXIST stable state
        # means the rules will not select this index again — its HBM
        # segments are released here rather than squatting until byte
        # pressure evicts them. (Data-version bumps invalidate at
        # `IndexDataManager.commit`; this covers delete/vacuum-end.)
        if entry.state in (constants.States.DELETED,
                           constants.States.DOESNOTEXIST):
            from hyperspace_tpu.io import segcache
            segcache.on_index_dropped(self.index_path)
        return True

    def delete_latest_stable_log(self) -> bool:
        """Reference `IndexLogManager.scala:124-137`."""
        path = os.path.join(self.log_dir, constants.LATEST_STABLE_LOG)
        if not file_utils.exists(path):
            return True
        try:
            file_utils.remove_file(path)
            return True
        except (OSError, FileNotFoundError):
            return False

    def write_log(self, log_id: int, entry: LogEntry) -> bool:
        if file_utils.exists(self._path_for(log_id)):
            return False
        entry.id = log_id
        # Transient failures retry. If a failed-looking attempt actually
        # landed the object (response lost), the retry reports False and
        # the action aborts as a conflict — leaving ITS OWN transient
        # entry as latest, which lease-based recovery (or recover_index)
        # unwinds; correctness of the OCC log is never at risk.
        return retry.call(
            lambda: file_utils.atomic_write_if_absent(
                self._path_for(log_id), entry.to_json(indent=2),
                single_writer=self._single_writer()),
            operation=f"log.write:{self._path_for(log_id)}",
            policy=retry.policy_for(self.conf))

    # -- action reports ---------------------------------------------------

    ACTION_REPORT_SUFFIX = ".report.json"

    def _report_path(self, log_id: int) -> str:
        return os.path.join(self.log_dir,
                            f"{log_id}{self.ACTION_REPORT_SUFFIX}")

    def write_action_report(self, log_id: int, report: dict) -> bool:
        """Persist the action report alongside the log entry it
        finalized. Best-effort: the log entry is already durable, a
        failed sidecar write must NEVER fail the action — and fsspec
        object-store backends raise library-specific errors (aiohttp
        client errors, botocore ClientError, ...), so the guard is ANY
        Exception, not just OSError. Transient failures get the standard
        retries first."""
        try:
            retry.call(
                lambda: file_utils.create_file(
                    self._report_path(log_id),
                    json.dumps(report, indent=2, default=str)),
                operation=f"log.report:{self._report_path(log_id)}",
                policy=retry.policy_for(self.conf))
            return True
        except Exception:
            return False

    def get_action_report(self, log_id: int) -> Optional[dict]:
        path = self._report_path(log_id)
        if not file_utils.exists(path):
            return None
        return json.loads(file_utils.read_contents(path))
