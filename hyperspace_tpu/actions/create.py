"""Create action + shared create/refresh machinery.

Parity: reference `actions/CreateActionBase.scala:31-121` and
`actions/CreateAction.scala:27-75`. The index build job — the reference's
`df.select(indexed++included).repartition(numBuckets, indexedCols)
.write.saveWithBuckets(...)` — becomes this framework's device build
pipeline: hash-partition + sort kernels over columnar batches, bucketed
parquet write (`io/builder.py`).
"""

from __future__ import annotations

from typing import List, Optional

from hyperspace_tpu import constants
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.log_entry import (Content, CoveringIndex, Directory,
                                            Hdfs, IndexLogEntry,
                                            LogicalPlanFingerprint,
                                            NoOpFingerprint, PlanSource,
                                            Signature, Source)
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.index.signature import FileBasedSignatureProvider
from hyperspace_tpu.plan.nodes import Scan
from hyperspace_tpu.plan.serde import plan_to_json


def index_data_stats(root: str) -> dict:
    """On-disk stats of an index data root: total bytes + row count (from
    parquet footers — no data read). Computed at build time and stored in
    the log entry so no query-time code needs a filesystem walk."""
    from hyperspace_tpu.io import parquet
    from hyperspace_tpu.utils.file_utils import get_directory_size

    size = int(get_directory_size(root))
    files = [f for per_bucket in parquet.bucket_files(root).values()
             for f in per_bucket]
    rows = int(sum(parquet.file_row_counts(files))) if files else 0
    return {"dataSizeBytes": size, "rowCount": rows}


class CreateActionBase(Action):
    """Shared machinery for Create/Refresh (reference `CreateActionBase.scala`)."""

    def __init__(self, log_manager: IndexLogManager,
                 data_manager: IndexDataManager, conf: HyperspaceConf):
        super().__init__(log_manager)
        self.data_manager = data_manager
        self.conf = conf
        self._data_version: Optional[int] = None

    @property
    def index_data_path(self) -> str:
        """Next free `v__=N` dir (reference `CreateActionBase.scala:31-36`).
        Allocated over ALL existing dirs — a crashed build's uncommitted
        dir is skipped, never written into — and memoized so every phase
        of this action sees the same target."""
        if self._data_version is None:
            self._data_version = self.data_manager.next_version_id()
        return self.data_manager.get_path(self._data_version)

    def commit_data_version(self) -> None:
        """Finalize the version dir this action wrote — the `_committed`
        marker is the build's LAST data write; until it lands the version
        is invisible to `get_latest_version_id` and the rules. Actions
        that carry a previous version's bucket runs forward (incremental
        refresh) set `_touched_buckets`/`_carried_from_version` first so
        the segment cache invalidates bucket-scoped instead of torching
        the whole warm set."""
        if self._data_version is not None:
            touched = getattr(self, "_touched_buckets", None)
            carried = getattr(self, "_carried_from_version", None)
            if touched is not None and carried is not None:
                self.data_manager.commit(self._data_version,
                                         touched_buckets=touched,
                                         carried_from=carried)
            else:
                self.data_manager.commit(self._data_version)

    def _recover_stale_writer(self) -> None:
        """Lease-based crash recovery, run at the head of validate():
        when the latest log entry is TRANSIENT (a writer died between
        begin and end) and older than
        `spark.hyperspace.maintenance.lease.seconds`, run the Cancel FSM
        transition back to the last stable state so the crashed writer
        stops blocking the index forever. Within the lease the entry is
        presumed live and validation fails as before (exactly one writer
        may hold the transient slot)."""
        import time as _time

        from hyperspace_tpu import telemetry
        from hyperspace_tpu.actions.cancel import CancelAction
        from hyperspace_tpu.constants import STABLE_STATES

        latest = self.log_manager.get_latest_log()
        if latest is None or latest.state in STABLE_STATES:
            return
        age_s = _time.time() - (latest.timestamp or 0) / 1000.0
        if age_s <= self.conf.maintenance_lease_seconds:
            return
        CancelAction(self.log_manager).run()
        telemetry.get_registry().counter("resilience.recoveries").inc()
        telemetry.event("resilience", "recovered",
                        index=getattr(latest, "name", None),
                        stale_state=latest.state, age_s=round(age_s, 3))
        # Cancel appended two log entries; drop every cached view of the
        # log so this action re-reads the recovered state.
        self._base_id = None
        self._latest_entry = None
        self._data_version = None
        for attr in ("_previous", "_entry", "_df", "_delta"):
            if hasattr(self, attr):
                setattr(self, attr, None)
        if hasattr(self, "_lineage_map"):  # sentinel-cached, so delete
            delattr(self, "_lineage_map")

    def num_buckets(self) -> int:
        return self.conf.num_buckets

    def _signature_provider(self):
        return FileBasedSignatureProvider()

    def source_files(self, df) -> List[str]:
        """All files of every Scan leaf (reference `CreateActionBase.scala:89-97`)."""
        files: List[str] = []
        for leaf in df.plan.collect_leaves():
            if isinstance(leaf, Scan):
                files.extend(leaf.files())
        return files

    def lineage_enabled(self) -> bool:
        """Per-row lineage opt-in (`spark.hyperspace.index.lineage.enabled`;
        extension — the reference's v0.2 direction)."""
        return (self.conf.get(constants.LINEAGE_ENABLED, "false")
                or "false").lower() == "true"

    def _lineage_ids(self, files: List[str]) -> Optional[dict]:
        """{source file path: stable lineage id} for this build, or None
        when lineage is off. Fresh builds number files 0..n-1; incremental
        refresh overrides this to keep surviving files' ids stable (their
        rows are carried forward verbatim)."""
        if not self.lineage_enabled():
            return None
        return {f: i for i, f in enumerate(files)}

    _LINEAGE_UNSET = object()

    def lineage_id_map(self, df) -> Optional[dict]:
        """THE build's {source file: lineage id} assignment, computed once
        per action over the full current source file list. The data write
        and the log entry's FileInfos must agree row-for-row, so both read
        this one memoized map — two independent `_lineage_ids` calls would
        only agree while every source is a single sorted Scan."""
        cached = getattr(self, "_lineage_map", self._LINEAGE_UNSET)
        if cached is not self._LINEAGE_UNSET:
            return cached
        self._lineage_map = self._lineage_ids(self.source_files(df))
        return self._lineage_map

    def get_index_log_entry(self, df, index_config: IndexConfig,
                            path: str) -> IndexLogEntry:
        """Build the full metadata record (reference `CreateActionBase.scala:38-87`):
        numBuckets from conf, schema of indexed+included columns, serialized
        source plan (the *logical* IR — like the reference logging the
        unanalyzed plan), fingerprint via the signature provider, and the
        source file list."""
        provider = self._signature_provider()
        signature_value = provider.signature(df.plan)
        if signature_value is None:
            raise HyperspaceException(
                "Cannot fingerprint source plan: unsupported relations present.")
        columns = index_config.indexed_columns + index_config.included_columns
        schema = df.schema.select(columns)
        source_file_list = self.source_files(df)
        lineage_ids = self.lineage_id_map(df)
        file_infos = None
        if lineage_ids is not None:
            from hyperspace_tpu.index.log_entry import FileInfo
            from hyperspace_tpu.index.signature import file_stamp
            from hyperspace_tpu.io.builder import lineage_schema
            file_infos = []
            for f in source_file_list:
                stamp = file_stamp(f)
                if stamp is None:
                    raise HyperspaceException(
                        f"Cannot stat source file for lineage: {f}")
                file_infos.append(FileInfo(f, stamp[0], stamp[1],
                                           lineage_ids[f]))
            schema = lineage_schema(schema)
        entry = IndexLogEntry(
            name=index_config.index_name,
            derived_dataset=CoveringIndex(
                indexed_columns=list(index_config.indexed_columns),
                included_columns=list(index_config.included_columns),
                schema_json=schema.to_json(),
                num_buckets=self.num_buckets()),
            content=Content(root=path, directories=[]),
            source=Source(
                plan=PlanSource(
                    raw_plan=plan_to_json(df.plan),
                    fingerprint=LogicalPlanFingerprint(
                        [Signature(provider.name(), signature_value)])),
                data=[Hdfs(Content(root="", directories=[
                    Directory(path="", files=source_file_list,
                              fingerprint=NoOpFingerprint(),
                              file_infos=file_infos)]))]),
            extra={})
        return entry

    def write(self, df, index_config: IndexConfig, path: str) -> None:
        """THE index build job (reference `CreateActionBase.scala:99-120`).

        select(indexed ++ included) -> device hash-partition into numBuckets
        by indexed columns -> per-bucket sort by indexed columns -> bucketed
        parquet under `path`.
        """
        from hyperspace_tpu.io.builder import write_index
        written = write_index(df, list(index_config.indexed_columns),
                              list(index_config.included_columns),
                              self.num_buckets(), path, conf=self.conf,
                              lineage_ids=self.lineage_id_map(df))
        self.annotate_report(files_written=len(written),
                             num_buckets=self.num_buckets(),
                             source_files=len(self.source_files(df)))

    def stamp_stats(self) -> None:
        """Persist the written index data's on-disk size and row count in
        the entry (`extra.stats`), measured ONCE at build/refresh time from
        the files just written. Query-time ranking
        (`FilterIndexRule._rank`) reads these instead of walking the data
        root per optimization pass — the reference keeps everything a rule
        decision needs inside the log entry the same way
        (`index/IndexLogEntry.scala:80-125`). Called at the end of every
        data-writing `op()`, before `end()` serializes the entry."""
        if self._entry is None:
            return
        stats = index_data_stats(self._entry.content.root)
        self._entry.extra["stats"] = stats
        # Born-sharded builds leave a `_shard_layout.json` record next to
        # the bucket spec (io/builder.write_bucket_ordered); lift it into
        # the log entry so readers know each device's contiguous bucket
        # range without touching the data dir (the ISSUE's "recorded in
        # the index log entry" contract). Single-device builds carry no
        # layout and the key stays absent.
        from hyperspace_tpu.io.builder import (read_shard_layout,
                                               summarize_shard_layout)
        layout = read_shard_layout(self._entry.content.root)
        if layout is not None:
            # Per-range string dictionary VALUES stay in the JSON file
            # (they can be large); the entry carries per-range entry
            # counts (`dictionaryEntries`).
            self._entry.extra["shardLayout"] = \
                summarize_shard_layout(layout)
        else:
            self._entry.extra.pop("shardLayout", None)
        # The SAME numbers land in the action report: rows/bytes the
        # operation left on disk, measured once.
        self.annotate_report(rows=stats["rowCount"],
                             bytes=stats["dataSizeBytes"])


class CreateAction(CreateActionBase):
    """transient CREATING -> final ACTIVE (reference `CreateAction.scala:27-75`)."""

    def __init__(self, df, index_config: IndexConfig,
                 log_manager: IndexLogManager, data_manager: IndexDataManager,
                 conf: HyperspaceConf):
        super().__init__(log_manager, data_manager, conf)
        self.df = df
        self.index_config = index_config
        self._entry: Optional[IndexLogEntry] = None

    transient_state = States.CREATING
    final_state = States.ACTIVE

    def log_entry(self) -> IndexLogEntry:
        if self._entry is None:
            self._entry = self.get_index_log_entry(
                self.df, self.index_config, self.index_data_path)
        # A fresh copy per begin/end write so state mutation doesn't alias.
        return IndexLogEntry.from_dict(self._entry.to_dict())

    def validate(self) -> None:
        """Reference `CreateAction.scala:42-62`: source must be a plain file
        scan (no filter/project/join on top), index columns must exist in the
        source schema, and no non-DOESNOTEXIST index of the same name."""
        self._recover_stale_writer()
        if not isinstance(self.df.plan, Scan):
            raise HyperspaceException(
                "Only creating index over a plain file scan is supported.")
        schema = self.df.schema
        missing = [c for c in (self.index_config.indexed_columns
                               + self.index_config.included_columns)
                   if not schema.contains(c)]
        if missing:
            raise HyperspaceException(
                "Index config is not applicable to dataframe schema; "
                f"missing columns: {', '.join(missing)}")
        latest = self.log_manager.get_latest_log()
        if latest is not None and latest.state != States.DOESNOTEXIST:
            raise HyperspaceException(
                f"Another index with name {self.index_config.index_name} "
                f"already exists (state {latest.state}).")

    def op(self) -> None:
        self.write(self.df, self.index_config, self.index_data_path)
        self.commit_data_version()
        self.stamp_stats()
