"""Transactional action template — the lifecycle state machine core.

Parity: reference `actions/Action.scala:33-96`:
`run() = validate(); begin(); op(); end()`. `begin()` writes log id
`base_id+1` with a *transient* state; `end()` writes id `base_id+2` with the
*final* state and deletes + recreates `latestStable`. `base_id` = latest log
id or -1. A failure between begin and end strands the index in a transient
state; only `cancel()` can recover (reference `actions/CancelAction.scala`).
Optimistic concurrency: `write_log` refuses existing ids, so exactly one of
two racing actions wins the `base_id+1` slot.
"""

from __future__ import annotations

import logging
from abc import ABC, abstractmethod

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.log_entry import LogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager

logger = logging.getLogger(__name__)


class Action(ABC):
    def __init__(self, log_manager: IndexLogManager):
        self.log_manager = log_manager
        self._base_id: int | None = None
        self._latest_entry = None

    def latest_entry(self, verb: str):
        """Latest IndexLogEntry, cached; raises if the log is empty or not an
        index entry (shared by the metadata-only actions)."""
        if self._latest_entry is None:
            from hyperspace_tpu.index.log_entry import IndexLogEntry
            entry = self.log_manager.get_latest_log()
            if not isinstance(entry, IndexLogEntry):
                raise HyperspaceException(f"No index found to {verb}.")
            self._latest_entry = entry
        return self._latest_entry

    @property
    def base_id(self) -> int:
        if self._base_id is None:
            latest = self.log_manager.get_latest_id()
            self._base_id = latest if latest is not None else -1
        return self._base_id

    @property
    @abstractmethod
    def transient_state(self) -> str: ...

    @property
    @abstractmethod
    def final_state(self) -> str: ...

    @abstractmethod
    def log_entry(self) -> LogEntry:
        """The record to persist (with state filled in by begin/end)."""

    def validate(self) -> None:
        """Override to gate on the current lifecycle state."""

    @abstractmethod
    def op(self) -> None:
        """The data-moving operation (may dispatch device work)."""

    def begin(self) -> None:
        entry = self.log_entry()
        entry.state = self.transient_state
        if not self.log_manager.write_log(self.base_id + 1, entry):
            raise HyperspaceException(
                "Another operation is in progress for this index "
                f"(log id {self.base_id + 1} already exists).")
        logger.info("Begin %s (log id %d, state %s)",
                    type(self).__name__, self.base_id + 1, self.transient_state)

    def end(self) -> None:
        entry = self.log_entry()
        entry.state = self.final_state
        if not self.log_manager.write_log(self.base_id + 2, entry):
            raise HyperspaceException(
                "Another operation is in progress for this index "
                f"(log id {self.base_id + 2} already exists).")
        self.log_manager.delete_latest_stable_log()
        self.log_manager.create_latest_stable_log(self.base_id + 2)
        logger.info("End %s (log id %d, state %s)",
                    type(self).__name__, self.base_id + 2, self.final_state)

    def run(self) -> None:
        self.validate()
        self.begin()
        self.op()
        self.end()
