"""Transactional action template — the lifecycle state machine core.

Parity: reference `actions/Action.scala:33-96`:
`run() = validate(); begin(); op(); end()`. `begin()` writes log id
`base_id+1` with a *transient* state; `end()` writes id `base_id+2` with the
*final* state and deletes + recreates `latestStable`. `base_id` = latest log
id or -1. A failure between begin and end strands the index in a transient
state; the Cancel FSM transition recovers it (reference
`actions/CancelAction.scala`) — run explicitly via
`Hyperspace.recover_index`/`cancel`, or automatically by the next
create/refresh/optimize once the stranded entry outlives
`spark.hyperspace.maintenance.lease.seconds` (lease-based recovery,
`CreateActionBase._recover_stale_writer`).
Optimistic concurrency: `write_log` refuses existing ids, so exactly one of
two racing actions wins the `base_id+1` slot.

Observability: every `run()` emits a structured ACTION REPORT — action
name, index, per-phase wall seconds (validate/begin/op/end), and
op-specific detail (rows, files, bytes; annotated via
`annotate_report`). Reports land in the process metrics registry
(counters `actions.*` + the report ring) and, on success, persist as
`<id>.report.json` next to the final log entry, so index maintenance
cost is auditable per log id long after the process exits.
`Action.__init_subclass__` wraps any subclass-defined `run` with the
same machinery and stamps it, mirroring `PhysicalNode`'s operator
instrumentation — `scripts/check_metrics_coverage.py` fails if any
Action subclass can run without emitting a report.
"""

from __future__ import annotations

import functools
import logging
import time
from abc import ABC, abstractmethod

from hyperspace_tpu import telemetry
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.log_entry import LogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.utils import faults

logger = logging.getLogger(__name__)


def _instrument_run(fn):
    """Wrap a `run` implementation with the action-report machinery.
    Re-entrant: a subclass override calling `super().run()` shares the
    outer invocation's report instead of emitting two."""

    @functools.wraps(fn)
    def wrapper(self):
        if self._report is not None:
            return fn(self)
        report = self._report = {
            "action": type(self).__name__,
            "started_at": time.time(),
            "phases": {},
            "detail": {},
            "ok": False,
        }
        t0 = time.perf_counter()
        try:
            with telemetry.span(f"action:{type(self).__name__}",
                                "action"):
                out = fn(self)
            report["ok"] = True
            return out
        except BaseException as exc:
            report["error"] = repr(exc)
            raise
        finally:
            report["wall_s"] = round(time.perf_counter() - t0, 6)
            try:
                self._publish_report(report)
            finally:
                self._report = None

    wrapper.__action_report_instrumented__ = True
    return wrapper


class Action(ABC):
    def __init__(self, log_manager: IndexLogManager):
        self.log_manager = log_manager
        self._base_id: int | None = None
        self._latest_entry = None
        self._report: dict | None = None

    def __init_subclass__(cls, **kwargs):
        # EVERY subclass's run() emits an action report; opting out is
        # not supported by design (the metrics-coverage lint flags an
        # unstamped run).
        super().__init_subclass__(**kwargs)
        fn = cls.__dict__.get("run")
        if fn is not None and callable(fn) \
                and not getattr(fn, "__action_report_instrumented__",
                                False):
            cls.run = _instrument_run(fn)

    def latest_entry(self, verb: str):
        """Latest IndexLogEntry, cached; raises if the log is empty or not an
        index entry (shared by the metadata-only actions)."""
        if self._latest_entry is None:
            from hyperspace_tpu.index.log_entry import IndexLogEntry
            entry = self.log_manager.get_latest_log()
            if not isinstance(entry, IndexLogEntry):
                raise HyperspaceException(f"No index found to {verb}.")
            self._latest_entry = entry
        return self._latest_entry

    @property
    def base_id(self) -> int:
        if self._base_id is None:
            latest = self.log_manager.get_latest_id()
            self._base_id = latest if latest is not None else -1
        return self._base_id

    @property
    @abstractmethod
    def transient_state(self) -> str: ...

    @property
    @abstractmethod
    def final_state(self) -> str: ...

    @abstractmethod
    def log_entry(self) -> LogEntry:
        """The record to persist (with state filled in by begin/end)."""

    def validate(self) -> None:
        """Override to gate on the current lifecycle state."""

    @abstractmethod
    def op(self) -> None:
        """The data-moving operation (may dispatch device work)."""

    def begin(self) -> None:
        entry = self.log_entry()
        entry.state = self.transient_state
        if not self.log_manager.write_log(self.base_id + 1, entry):
            raise HyperspaceException(
                "Another operation is in progress for this index "
                f"(log id {self.base_id + 1} already exists).")
        logger.info("Begin %s (log id %d, state %s)",
                    type(self).__name__, self.base_id + 1, self.transient_state)

    def end(self) -> None:
        entry = self.log_entry()
        entry.state = self.final_state
        if not self.log_manager.write_log(self.base_id + 2, entry):
            raise HyperspaceException(
                "Another operation is in progress for this index "
                f"(log id {self.base_id + 2} already exists).")
        self.log_manager.delete_latest_stable_log()
        self.log_manager.create_latest_stable_log(self.base_id + 2)
        logger.info("End %s (log id %d, state %s)",
                    type(self).__name__, self.base_id + 2, self.final_state)

    # -- action report plumbing -------------------------------------------

    def annotate_report(self, **detail) -> None:
        """Attach op-specific detail (rows, files, bytes, ...) to the
        in-flight action report; no-op outside `run()`."""
        if self._report is not None:
            self._report["detail"].update(detail)

    def _timed_phase(self, name: str, fn) -> None:
        # Fault-injection point at every phase BOUNDARY: a "crash" rule
        # matching `action.<Class>.<phase>` aborts just before that phase
        # runs — i.e. between the preceding phase and this one, the
        # stranded-writer scenario recovery must unwind.
        faults.fire(f"action.{type(self).__name__}.{name}")
        if self._report is None:  # phase called directly, not via run()
            fn()
            return
        t0 = time.perf_counter()
        with telemetry.span(f"{type(self).__name__}.{name}", "action"):
            fn()
        self._report["phases"][name] = round(time.perf_counter() - t0, 6)

    def _index_identity(self) -> str | None:
        """Best-effort index name for the report — whichever of the
        config / cached entries the action got far enough to hold."""
        try:
            cfg = getattr(self, "index_config", None)
            if cfg is not None and getattr(cfg, "index_name", None):
                return cfg.index_name
        except Exception:
            pass
        for attr in ("_entry", "_previous", "_latest_entry"):
            entry = getattr(self, attr, None)
            if entry is not None and getattr(entry, "name", None):
                return entry.name
        return None

    def _publish_report(self, report: dict) -> None:
        """Finalize + publish one action report: registry counters and
        the report ring always; a per-query telemetry event when a
        recorder is active; persisted next to the final log entry on
        success. Publishing must never mask the action's own outcome."""
        try:
            report["index"] = self._index_identity()
            if report["ok"] and self._base_id is not None:
                report["log_id"] = self._base_id + 2
            name = report["action"]
            reg = telemetry.get_registry()
            reg.counter(f"actions.{name}.runs").inc()
            reg.counter("actions.reports").inc()
            if not report["ok"]:
                reg.counter(f"actions.{name}.failures").inc()
            reg.histogram(f"actions.{name}.wall_s").observe(
                report["wall_s"])
            detail = report["detail"]
            if detail.get("rows"):
                reg.counter("actions.rows_indexed").inc(detail["rows"])
            if detail.get("bytes"):
                reg.counter("actions.bytes_written").inc(detail["bytes"])
            reg.record_action_report(report)
            telemetry.event("action", name, index=report["index"],
                            ok=report["ok"], wall_s=report["wall_s"])
            if report.get("log_id") is not None:
                self.log_manager.write_action_report(report["log_id"],
                                                     report)
        except Exception:
            logger.warning("Failed to publish action report for %s",
                           report.get("action"), exc_info=True)

    def run(self) -> None:
        self._timed_phase("validate", self.validate)
        self._timed_phase("begin", self.begin)
        self._timed_phase("op", self.op)
        self._timed_phase("end", self.end)

    run = _instrument_run(run)
