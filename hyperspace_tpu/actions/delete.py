"""Soft delete: ACTIVE -> (DELETING) -> DELETED; metadata-only.

Parity: reference `actions/DeleteAction.scala:23-43`.
"""

from __future__ import annotations

from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.actions.base import Action


class DeleteAction(Action):
    transient_state = States.DELETING
    final_state = States.DELETED

    def __init__(self, log_manager: IndexLogManager):
        super().__init__(log_manager)

    def validate(self) -> None:
        state = self.latest_entry("delete").state
        if state != States.ACTIVE:
            raise HyperspaceException(
                f"Delete is only supported in {States.ACTIVE} state; "
                f"current state is {state}.")

    def log_entry(self) -> IndexLogEntry:
        return IndexLogEntry.from_dict(self.latest_entry("delete").to_dict())

    def op(self) -> None:
        """Metadata-only transition — no data is touched."""
