"""Hard delete: DELETED -> (VACUUMING) -> DOESNOTEXIST; removes all data
version directories latest -> 0.

Parity: reference `actions/VacuumAction.scala:23-52`.
"""

from __future__ import annotations

from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.actions.base import Action


class VacuumAction(Action):
    transient_state = States.VACUUMING
    final_state = States.DOESNOTEXIST

    def __init__(self, log_manager: IndexLogManager,
                 data_manager: IndexDataManager):
        super().__init__(log_manager)
        self.data_manager = data_manager

    def validate(self) -> None:
        state = self.latest_entry("vacuum").state
        if state != States.DELETED:
            raise HyperspaceException(
                f"Vacuum is only supported in {States.DELETED} state; "
                f"current state is {state}.")

    def log_entry(self) -> IndexLogEntry:
        return IndexLogEntry.from_dict(self.latest_entry("vacuum").to_dict())

    def op(self) -> None:
        """Delete every data version dir that actually EXISTS, newest
        first (reference `VacuumAction.scala:45-51` walks a dense
        latest..0 range — but a sparse layout, a partially vacuumed
        index, or a crashed build's uncommitted dir must not abort the
        hard delete, and uncommitted partials are invisible to
        `get_latest_version_id` by design)."""
        versions = sorted(self.data_manager.all_version_ids(),
                          reverse=True)
        for version in versions:
            self.data_manager.delete(version)
        self.annotate_report(versions_removed=len(versions))
