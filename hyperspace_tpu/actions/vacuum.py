"""Hard delete: DELETED -> (VACUUMING) -> DOESNOTEXIST; removes all data
version directories latest -> 0, deferring behind in-flight pinned reads.

Parity: reference `actions/VacuumAction.scala:23-52`. The pin deferral
has no reference analog — Spark's file sources tolerate listing drift,
but our snapshot-pinned scans read a frozen file list and a concurrent
hard delete would otherwise yank files mid-query (see `index/pins.py`).
"""

from __future__ import annotations

from typing import Optional

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.actions.base import Action


class _VersionPinnedError(HyperspaceException):
    """A data version is held by an in-flight snapshot-pinned read.

    Internal to the vacuum flow: classified retryable so the delete
    backs off (bounded, jittered) behind the reader, and caught after
    the budget to record a deferral instead of failing the vacuum.
    """


class VacuumAction(Action):
    transient_state = States.VACUUMING
    final_state = States.DOESNOTEXIST

    def __init__(self, log_manager: IndexLogManager,
                 data_manager: IndexDataManager,
                 conf: Optional[HyperspaceConf] = None):
        super().__init__(log_manager)
        self.data_manager = data_manager
        self.conf = conf

    def validate(self) -> None:
        state = self.latest_entry("vacuum").state
        if state != States.DELETED:
            raise HyperspaceException(
                f"Vacuum is only supported in {States.DELETED} state; "
                f"current state is {state}.")

    def log_entry(self) -> IndexLogEntry:
        return IndexLogEntry.from_dict(self.latest_entry("vacuum").to_dict())

    def _delete_version(self, version: int) -> bool:
        """Delete one version dir unless an in-flight read pins it.

        Backs off behind the pin with the shared retry policy (bounded
        attempts, jittered exponential delay — never a sleep-in-except);
        returns False when the version stayed pinned through the whole
        budget and the delete was deferred.
        """
        from hyperspace_tpu import telemetry
        from hyperspace_tpu.index import pins
        from hyperspace_tpu.utils import retry

        path = self.data_manager.get_path(version)

        def attempt() -> None:
            if pins.is_pinned(path):
                raise _VersionPinnedError(
                    f"Version dir {path} is pinned by an in-flight read; "
                    f"deferring the hard delete.")
            self.data_manager.delete(version)

        try:
            retry.call(attempt, operation=f"vacuum.delete.v{version}",
                       conf=self.conf, retryable=(_VersionPinnedError,))
            return True
        except _VersionPinnedError:
            telemetry.get_registry().counter(
                "resilience.vacuum.deferred").inc()
            return False

    def op(self) -> None:
        """Delete every data version dir that actually EXISTS, newest
        first (reference `VacuumAction.scala:45-51` walks a dense
        latest..0 range — but a sparse layout, a partially vacuumed
        index, or a crashed build's uncommitted dir must not abort the
        hard delete, and uncommitted partials are invisible to
        `get_latest_version_id` by design). Versions pinned by in-flight
        reads past the backoff budget are skipped — orphaned garbage is
        recoverable; a reader crashed mid-file is not."""
        versions = sorted(self.data_manager.all_version_ids(),
                          reverse=True)
        removed = deferred = 0
        for version in versions:
            if self._delete_version(version):
                removed += 1
            else:
                deferred += 1
        self.annotate_report(versions_removed=removed,
                             versions_deferred=deferred)
