"""Optimize — merge-compaction of incremental index deltas (extension).

The surveyed reference only has full rebuild (`RefreshAction`); its roadmap
(`ROADMAP.md:66-75`) and this build's baseline ladder (BASELINE.md) require
incremental refresh + compaction. OptimizeAction compacts the delta files
written by incremental refresh into full per-bucket sorted runs via the
device k-way merge kernel (`ops/merge.py`), ACTIVE -> (OPTIMIZING) -> ACTIVE
into the next `v__=N+1`.
"""

from __future__ import annotations

from typing import Optional

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.actions.create import CreateActionBase


class OptimizeAction(CreateActionBase):
    transient_state = States.OPTIMIZING
    final_state = States.ACTIVE

    def __init__(self, log_manager: IndexLogManager,
                 data_manager: IndexDataManager, conf: HyperspaceConf):
        super().__init__(log_manager, data_manager, conf)
        self._previous: Optional[IndexLogEntry] = None
        self._entry: Optional[IndexLogEntry] = None

    @property
    def previous_entry(self) -> IndexLogEntry:
        if self._previous is None:
            entry = self.log_manager.get_log(self.base_id)
            if not isinstance(entry, IndexLogEntry):
                raise HyperspaceException("No index log entry to optimize.")
            self._previous = entry
        return self._previous

    def num_buckets(self) -> int:
        return self.previous_entry.num_buckets

    def validate(self) -> None:
        self._recover_stale_writer()
        from hyperspace_tpu.index.log_entry import DataSkippingIndex
        if isinstance(self.previous_entry.derived_dataset,
                      DataSkippingIndex):
            raise HyperspaceException(
                "Optimize does not apply to data-skipping indexes: "
                "there are no incremental delta runs to compact.")
        if self.previous_entry.state != States.ACTIVE:
            raise HyperspaceException(
                f"Optimize is only supported in {States.ACTIVE} state; "
                f"current state is {self.previous_entry.state}.")

    def log_entry(self) -> IndexLogEntry:
        if self._entry is None:
            entry = IndexLogEntry.from_dict(self.previous_entry.to_dict())
            entry.content.root = self.index_data_path
            entry.content.directories = []
            entry.extra = dict(entry.extra)
            self._entry = entry
        return IndexLogEntry.from_dict(self._entry.to_dict())

    def op(self) -> None:
        from hyperspace_tpu.io import parquet
        from hyperspace_tpu.io.builder import compact_index
        runs_before = sum(
            len(files) for files in
            parquet.bucket_files(self.previous_entry.content.root)
            .values())
        written = compact_index(self.previous_entry, self.data_manager,
                                self.index_data_path)
        self.annotate_report(runs_compacted=runs_before,
                             files_written=len(written))
        self.commit_data_version()
        self.stamp_stats()
