"""Restore: DELETED -> (RESTORING) -> ACTIVE; metadata-only.

Parity: reference `actions/RestoreAction.scala:23-43`.
"""

from __future__ import annotations

from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.actions.base import Action


class RestoreAction(Action):
    transient_state = States.RESTORING
    final_state = States.ACTIVE

    def __init__(self, log_manager: IndexLogManager):
        super().__init__(log_manager)

    def validate(self) -> None:
        state = self.latest_entry("restore").state
        if state != States.DELETED:
            raise HyperspaceException(
                f"Restore is only supported in {States.DELETED} state; "
                f"current state is {state}.")

    def log_entry(self) -> IndexLogEntry:
        return IndexLogEntry.from_dict(self.latest_entry("restore").to_dict())

    def op(self) -> None:
        """Metadata-only transition."""
