"""Create action for DATA-SKIPPING indexes — the second index kind
through the SAME log/action FSM as the covering index.

`CreateSkippingIndexAction` rides the transactional template
(`actions/base.py`): validate -> begin (CREATING) -> op -> end
(ACTIVE), the `v__=N` version dir finalized by the `_committed` marker
written LAST, lease-based crash recovery, OCC on log ids, action
reports — nothing kind-specific in the lifecycle. What differs is the
DATA the op writes:

- the per-source-file sketch blob (`index/sketch.py`: zone maps +
  blocked bloom filters, reductions on the adaptive host/device lane
  with device batches staged through the `TransferEngine`), and
- optionally (config.zorder_by) a Z-ORDER clustered rewrite of the
  source rows under the same version dir (`zpart-NNNNN.parquet` —
  deliberately NOT the bucket naming pattern, the copy is clustered,
  not bucketed), whose per-file zones are tight by construction; the
  blob then sketches the COPY's files and the filter rule serves
  pruned reads from the copy.

`RefreshAction` (full rebuild) dispatches through the same build
functions when the previous entry's kind is DataSkippingIndex —
per-file sketches make a full re-sketch cheap. Under continuous ingest
the streaming path is `RefreshSkippingAppendAction` below (the
collection manager routes mode='incremental' there by kind): re-sketch
only appended/rewritten files, carry the previous blob's rows forward
(`index/sketch.append_file_sketches`), drop vanished files. Optimize
still declines skipping entries with a typed error (nothing compacted
to merge), as does the bucketed covering-delta path on direct
construction.

Commit also sweeps the SOURCE roots' host caches + footprint size
cache (`segcache.invalidate_source_paths`) — not just the index root
the generic commit hook covers — so the next admission decision and
plan-time prune see fresh source stamps instead of a stale-stamp
window.
"""

from __future__ import annotations

from typing import List, Optional

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.index_config import DataSkippingIndexConfig
from hyperspace_tpu.index.log_entry import (Content, DataSkippingIndex,
                                            Directory, Hdfs, IndexLogEntry,
                                            LogicalPlanFingerprint,
                                            NoOpFingerprint, PlanSource,
                                            Signature, Source)
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.actions.create import CreateActionBase
from hyperspace_tpu.actions.refresh import RefreshAction
from hyperspace_tpu.plan.nodes import Scan
from hyperspace_tpu.plan.serde import plan_to_json

ZORDER_FILE_PREFIX = "zpart-"


def _resolve(schema, columns: List[str]) -> List[str]:
    missing = [c for c in columns if not schema.contains(c)]
    if missing:
        raise HyperspaceException(
            "Index config is not applicable to dataframe schema; "
            f"missing columns: {', '.join(missing)}")
    return [schema.field(c).name for c in columns]


def skipping_log_entry(df, config: DataSkippingIndexConfig, path: str,
                       signature_provider) -> IndexLogEntry:
    """The metadata record, mirroring the covering
    `get_index_log_entry`: logged source plan + file-based fingerprint
    + source file list, with a DataSkippingIndex derived dataset. The
    schema records the full source schema for Z-order builds (the copy
    carries every column) and just the sketched columns otherwise."""
    signature_value = signature_provider.signature(df.plan)
    if signature_value is None:
        raise HyperspaceException(
            "Cannot fingerprint source plan: unsupported relations "
            "present.")
    skipped = _resolve(df.schema, config.skipping_columns)
    zorder = _resolve(df.schema, config.zorder_by) if config.zorder_by \
        else []
    schema = df.schema if zorder else df.schema.select(skipped)
    source_file_list: List[str] = []
    for leaf in df.plan.collect_leaves():
        if isinstance(leaf, Scan):
            source_file_list.extend(leaf.files())
    return IndexLogEntry(
        name=config.index_name,
        derived_dataset=DataSkippingIndex(
            skipped_columns=skipped,
            sketch_types=list(config.sketch_types),
            schema_json=schema.to_json(),
            zorder_by=zorder),
        content=Content(root=path, directories=[]),
        source=Source(
            plan=PlanSource(
                raw_plan=plan_to_json(df.plan),
                fingerprint=LogicalPlanFingerprint(
                    [Signature(signature_provider.name(),
                               signature_value)])),
            data=[Hdfs(Content(root="", directories=[
                Directory(path="", files=source_file_list,
                          fingerprint=NoOpFingerprint())]))]),
        extra={})


def _write_zorder_copy(files: List[str], schema,
                       zorder_cols: List[str], path: str,
                       conf) -> List[str]:
    """Cluster the source rows by the Z-order interleave of
    `zorder_cols` and write them as `zpart-NNNNN.parquet` files under
    `path`. Returns the written paths (in z order)."""
    import os

    from hyperspace_tpu import constants
    from hyperspace_tpu.io import columnar, parquet
    from hyperspace_tpu.ops.sketch import zorder_permutation
    from hyperspace_tpu.utils import file_utils

    table = parquet.read_table(files)
    key_batch = columnar.from_arrow(
        table.select([schema.field(c).name for c in zorder_cols]),
        schema.select(zorder_cols), device=False)
    perm = zorder_permutation(key_batch, zorder_cols)
    import pyarrow as pa
    clustered = table.take(pa.array(perm))
    n_files = max(1, conf.skipping_zorder_files if conf is not None
                  else constants.SKIPPING_ZORDER_FILES_DEFAULT)
    n_files = min(n_files, max(1, table.num_rows))
    file_utils.create_directory(path)
    written: List[str] = []
    rows = table.num_rows
    for i in range(n_files):
        lo = (rows * i) // n_files
        hi = (rows * (i + 1)) // n_files
        if hi <= lo:
            continue
        out = os.path.join(path, f"{ZORDER_FILE_PREFIX}{i:05d}.parquet")
        parquet.write_table(clustered.slice(lo, hi - lo), out)
        written.append(out)
    return written


def build_skipping_data(df, config: DataSkippingIndexConfig, path: str,
                        conf) -> dict:
    """THE skipping build job: (optional) Z-order rewrite, then one
    sketch row per data file, persisted as the version dir's
    `_hs_sketches` blob. Returns action-report detail."""
    from hyperspace_tpu.index import sketch as sketch_io
    from hyperspace_tpu.utils import file_utils

    skipped = _resolve(df.schema, config.skipping_columns)
    source_files: List[str] = []
    for leaf in df.plan.collect_leaves():
        if isinstance(leaf, Scan):
            source_files.extend(leaf.files())
    detail = {"source_files": len(source_files),
              "sketched_columns": len(skipped)}
    if config.zorder_by:
        zorder = _resolve(df.schema, config.zorder_by)
        data_files = _write_zorder_copy(source_files, df.schema, zorder,
                                        path, conf)
        detail["zorder_files_written"] = len(data_files)
        schema = df.schema
    else:
        data_files = source_files
        file_utils.create_directory(path)
        schema = df.schema
    sketches = sketch_io.build_file_sketches(data_files, skipped, schema,
                                             conf)
    blob_bytes = sketch_io.write_sketches(path, sketches, skipped,
                                          schema, config.sketch_types)
    detail["files_sketched"] = len(sketches)
    detail["sketch_blob_bytes"] = blob_bytes
    return detail


def sweep_source_caches(df) -> int:
    """Invalidate the footprint size cache and the stamped host parquet
    caches under every SOURCE root of `df`'s plan (the commit-time
    other-half of the generic index-root sweep): the next admission
    decision and plan-time prune must see fresh stamps, not a
    pre-commit window. Returns how many roots were swept."""
    from hyperspace_tpu.io import segcache

    roots: List[str] = []
    for leaf in df.plan.collect_leaves():
        if isinstance(leaf, Scan):
            roots.extend(leaf.root_paths)
    for root in roots:
        segcache.invalidate_source_paths(root)
    return len(roots)


class CreateSkippingIndexAction(CreateActionBase):
    """transient CREATING -> final ACTIVE, like CreateAction — only the
    data written differs (sketch blob +/- Z-order copy)."""

    transient_state = States.CREATING
    final_state = States.ACTIVE

    def __init__(self, df, index_config: DataSkippingIndexConfig,
                 log_manager: IndexLogManager,
                 data_manager: IndexDataManager, conf: HyperspaceConf):
        super().__init__(log_manager, data_manager, conf)
        self.df = df
        self.index_config = index_config
        self._entry: Optional[IndexLogEntry] = None

    def log_entry(self) -> IndexLogEntry:
        if self._entry is None:
            self._entry = skipping_log_entry(
                self.df, self.index_config, self.index_data_path,
                self._signature_provider())
        return IndexLogEntry.from_dict(self._entry.to_dict())

    def validate(self) -> None:
        self._recover_stale_writer()
        if not isinstance(self.df.plan, Scan):
            raise HyperspaceException(
                "Only creating a data-skipping index over a plain file "
                "scan is supported.")
        _resolve(self.df.schema, self.index_config.skipping_columns)
        if self.index_config.zorder_by:
            _resolve(self.df.schema, self.index_config.zorder_by)
        latest = self.log_manager.get_latest_log()
        if latest is not None and latest.state != States.DOESNOTEXIST:
            raise HyperspaceException(
                f"Another index with name {self.index_config.index_name} "
                f"already exists (state {latest.state}).")

    def op(self) -> None:
        detail = build_skipping_data(self.df, self.index_config,
                                     self.index_data_path, self.conf)
        self.annotate_report(**detail)
        self.commit_data_version()
        self.annotate_report(source_roots_swept=sweep_source_caches(self.df))
        self.stamp_stats()


class RefreshSkippingAppendAction(RefreshAction):
    """Streaming refresh for data-skipping indexes: REFRESHING ->
    ACTIVE through the same FSM as every other maintenance action, but
    the op writes a DELTA blob build — re-sketch only the source files
    that appeared or were rewritten since the previous version, carry
    every still-identical file's row forward from the previous blob,
    drop rows for vanished files (per-file sketches make deletions
    trivially servable). The merged blob lands in the next `v__=N+1`
    version dir; in-flight pinned readers keep the old one.

    Z-ordered configs decline with a typed error: the clustered copy's
    zones are tight only over the FULL row set, so appends require a
    re-cluster — `mode='full'` — not a carry.
    """

    def validate(self) -> None:
        super().validate()
        if not self._is_skipping():
            raise HyperspaceException(
                "Sketch-append refresh only applies to data-skipping "
                "indexes; covering indexes take the bucketed delta path "
                "(the collection manager dispatches mode='incremental' "
                "by kind).")
        if self.index_config.zorder_by:
            raise HyperspaceException(
                "Sketch-append refresh does not apply to Z-ordered "
                "skipping indexes — the clustered copy must be "
                "re-clustered over the full row set; use mode='full'.")

    def op(self) -> None:
        from hyperspace_tpu.index import sketch as sketch_io
        from hyperspace_tpu.utils import file_utils

        cfg = self.index_config
        skipped = _resolve(self.df.schema, cfg.skipping_columns)
        source_files: List[str] = []
        for leaf in self.df.plan.collect_leaves():
            if isinstance(leaf, Scan):
                source_files.extend(leaf.files())
        out_dir = self.index_data_path
        file_utils.create_directory(out_dir)
        sketches, detail = sketch_io.append_file_sketches(
            self.previous_entry.content.root, source_files, skipped,
            self.df.schema, self.conf)
        blob_bytes = sketch_io.write_sketches(
            out_dir, sketches, skipped, self.df.schema, cfg.sketch_types)
        self.annotate_report(source_files=len(source_files),
                             sketched_columns=len(skipped),
                             sketch_blob_bytes=blob_bytes, **detail)
        self.commit_data_version()
        self.annotate_report(source_roots_swept=sweep_source_caches(self.df))
        self.stamp_stats()
