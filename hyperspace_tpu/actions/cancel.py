"""Cancel — crash recovery back to the last stable state.

Parity: reference `actions/CancelAction.scala:23-66`: only valid from
NON-stable states; the final state is the last stable log's state (a vacuum
interrupted mid-flight resolves to DOESNOTEXIST since data may be partially
deleted; no stable log at all also resolves to DOESNOTEXIST). `op()` is
empty — partial-file cleanup is deferred to vacuum, as in the reference.
"""

from __future__ import annotations

from hyperspace_tpu.constants import STABLE_STATES, States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.actions.base import Action


class CancelAction(Action):
    transient_state = States.CANCELLING

    def __init__(self, log_manager: IndexLogManager):
        super().__init__(log_manager)

    @property
    def final_state(self) -> str:
        """Reference `CancelAction.scala:43-52`."""
        stable = self.log_manager.get_latest_stable_log()
        if stable is None or stable.state == States.VACUUMING:
            return States.DOESNOTEXIST
        return stable.state

    def validate(self) -> None:
        """Reference `CancelAction.scala:54-60`: must be mid-operation."""
        state = self.latest_entry("cancel").state
        if state in STABLE_STATES:
            raise HyperspaceException(
                f"Cancel is not supported in {state} state.")

    def log_entry(self) -> IndexLogEntry:
        """Restore the last *stable* entry's metadata, not the in-flight
        transient one: a cancelled refresh must not leave content.root
        pointing at the partially-written new version dir. Falls back to the
        latest entry when no stable record exists (final state is then
        DOESNOTEXIST, so its content is never served)."""
        stable = self.log_manager.get_latest_stable_log()
        source = stable if isinstance(stable, IndexLogEntry) else self.latest_entry("cancel")
        return IndexLogEntry.from_dict(source.to_dict())

    def op(self) -> None:
        """No data movement; the FSM transition itself is the recovery."""
