"""Incremental refresh — index only the source delta.

The surveyed reference has full rebuild only (`RefreshAction`); incremental
refresh is its roadmap (`ROADMAP.md:66-75`) and this build's baseline
ladder requires it. Semantics:

- validate: state ACTIVE, and the source delta must be servable:
  * appends are always servable;
  * DELETIONS are servable when the previous version carries per-row
    lineage (`_hs_file_id` + per-file stamps, lineage-enabled builds) —
    the carried-forward runs are filtered per bucket, which preserves
    their sort order (no source re-read, no re-shuffle, no re-sort);
  * in-place rewrites are never servable — full refresh (surfaced in the
    error with the exact reason).
- op: the new `v__=N+1` dir carries every bucket run of the previous
  version forward (hard-links when no rows are dropped — zero-copy on
  posix; a lineage-filtered rewrite otherwise), then the device build
  pipeline indexes ONLY the appended files, writing per-bucket delta runs
  with a `-delta` suffix into the same dir. Versions stay immutable +
  self-contained; readers handle multi-run buckets natively (the batched
  join sorts per-bucket ids, bucketed scans re-sort multi-run buckets).
- `OptimizeAction` merge-compacts the runs back to one file per bucket.
"""

from __future__ import annotations

import os
import shutil
from typing import List, Optional, Tuple

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.actions.refresh import RefreshAction


def _version_of(root: str):
    """Committed `v__=N` parsed from a data root, or None (same parse
    as `plan/rules/base._version_of_root`, inlined to keep actions/ off
    the rules package)."""
    import re

    from hyperspace_tpu import constants
    m = re.search(re.escape(constants.INDEX_VERSION_DIRECTORY_PREFIX)
                  + r"=(\d+)$", os.path.basename(root.rstrip("/\\")))
    return int(m.group(1)) if m else None


def _link_or_copy(src: str, dst: str) -> None:
    from hyperspace_tpu.utils import file_utils, storage
    if storage.is_url(src) or storage.is_url(dst):
        file_utils.save_byte_array(dst, file_utils.load_byte_array(src))
        return
    try:
        os.link(src, dst)
    except OSError:
        shutil.copy2(src, dst)


class RefreshIncrementalAction(RefreshAction):
    """REFRESHING -> ACTIVE, writing only a source-delta update."""

    def _source_scans(self):
        from hyperspace_tpu.plan.nodes import Scan
        return [leaf for leaf in self.df.plan.collect_leaves()
                if isinstance(leaf, Scan)]

    def _current_files(self) -> List[str]:
        return [f for scan in self._source_scans() for f in scan.files()]

    def source_delta(self) -> Tuple[List[str], List[int]]:
        """(appended files, deleted lineage ids) of the current listing vs
        the build-time capture. Per-file stamps (lineage-enabled previous
        version) classify every file individually — deletions become ids
        to exclude; without stamps only appends are servable (shared
        derivation: `index/source_delta.py`). Memoized for the action's
        lifetime: validate() and op() see ONE consistent snapshot and the
        per-file stat pass runs once, not once per phase."""
        cached = getattr(self, "_delta", None)
        if cached is not None:
            return cached
        from hyperspace_tpu.index.source_delta import (classify_current,
                                                       split_current)
        current = self._current_files()
        delta = classify_current(self.previous_entry, current)
        if delta is not None:
            appended, deleted_ids, modified = delta
            if modified:
                raise HyperspaceException(
                    "Incremental refresh cannot serve in-place rewrites; "
                    f"{len(modified)} indexed file(s) were modified — run "
                    "a full refresh. Modified: "
                    + ", ".join(sorted(modified)[:3]))
            self._delta = (appended, deleted_ids)
            return self._delta
        appended, missing, _stored = split_current(self.previous_entry,
                                                   current)
        if missing:
            raise HyperspaceException(
                "Incremental refresh without lineage supports appended "
                f"data only; {len(missing)} indexed file(s) were deleted "
                "or rewritten — run a full refresh (or recreate the index "
                "with spark.hyperspace.index.lineage.enabled=true to make "
                "deletions servable). Missing: "
                + ", ".join(sorted(missing)[:3]))
        self._delta = (appended, [])
        return self._delta

    def appended_files(self) -> List[str]:
        return self.source_delta()[0]

    def lineage_enabled(self) -> bool:
        """Lineage continues iff the previous version carries it — the
        conf cannot retrofit ids onto carried-forward runs, and dropping
        them would corrupt the per-file identity story mid-index."""
        prev = self.previous_entry
        return prev.has_lineage and prev.source_file_infos() is not None

    def _lineage_ids(self, files: List[str]) -> Optional[dict]:
        """Surviving files keep their build-time ids (their rows are
        carried forward verbatim); appended files get fresh ids past the
        previous maximum."""
        if not self.lineage_enabled():
            return None
        infos = self.previous_entry.source_file_infos()
        next_id = max((fi.id for fi in infos.values()), default=-1) + 1
        out = {}
        for f in files:
            if f in infos:
                out[f] = infos[f].id
            else:
                out[f] = next_id
                next_id += 1
        return out

    def validate(self) -> None:
        super().validate()
        if self._is_skipping():
            raise HyperspaceException(
                "The bucketed incremental-refresh path applies to "
                "covering indexes only; data-skipping indexes take the "
                "sketch-append delta path (mode='incremental' via the "
                "collection manager dispatches there by kind).")
        self.source_delta()  # raises on un-servable deltas
        if self.lineage_enabled():
            return  # classify_current verified every survivor per file
        # Pre-lineage path: a file rewritten in place keeps its path —
        # verify the previously indexed files are byte-identical by
        # recomputing the aggregate signature over exactly the stored set.
        from hyperspace_tpu.index.signature import SignatureProviderFactory
        from hyperspace_tpu.index.source_delta import restricted_scan
        stored_sig = self.previous_entry.signature()
        restricted = restricted_scan(
            self.previous_entry, self._source_scans()[-1],
            self.previous_entry.source_file_list())
        provider = SignatureProviderFactory.create(stored_sig.provider)
        if provider.signature(restricted) != stored_sig.value:
            raise HyperspaceException(
                "Incremental refresh supports appended data only; previously "
                "indexed files were modified in place — run a full refresh.")

    def _carry_previous_runs(self, out_dir: str,
                             deleted_ids: List[int]) -> set:
        """Bring the previous version's bucket runs into `out_dir`.
        Without deletions every run hard-links (zero-copy). With
        deletions, runs containing a deleted file's rows are rewritten
        with those rows filtered out — a pure mask on the lineage column,
        so the run's sort order (and therefore the whole bucketed layout)
        is preserved without touching a sort kernel. Returns the bucket
        ids whose CONTENT changed relative to the previous version
        (rewritten or emptied runs) — the bucket-scoped invalidation
        input; hard-linked runs are byte-identical and stay out of it."""
        import numpy as np
        import pyarrow as pa

        from hyperspace_tpu.constants import LINEAGE_COLUMN
        from hyperspace_tpu.io import parquet

        prev_root = self.previous_entry.content.root
        deleted_arr = np.asarray(sorted(deleted_ids), dtype=np.int64)
        touched = set()
        for bucket, files in sorted(parquet.bucket_files(prev_root).items()):
            for f in files:
                dst = os.path.join(out_dir, os.path.basename(f))
                if not len(deleted_arr):
                    _link_or_copy(f, dst)
                    continue
                table = parquet.read_table([f])
                ids = table.column(LINEAGE_COLUMN).combine_chunks() \
                    .to_numpy(zero_copy_only=False)
                keep = ~np.isin(ids, deleted_arr)
                if keep.all():
                    _link_or_copy(f, dst)
                elif keep.any():
                    parquet.write_table(table.filter(pa.array(keep)), dst)
                    touched.add(int(bucket))
                else:
                    # every row dropped -> no file (empty-bucket parity
                    # with the full build, which writes no file either)
                    # — still a CONTENT change for the bucket.
                    touched.add(int(bucket))
        return touched

    def op(self) -> None:
        from hyperspace_tpu.io import parquet
        from hyperspace_tpu.io.builder import write_bucketed_table

        from hyperspace_tpu.utils import file_utils
        out_dir = self.index_data_path
        prev_root = self.previous_entry.content.root
        appended, deleted_ids = self.source_delta()
        self.annotate_report(appended_files=len(appended),
                             deleted_lineage_ids=len(deleted_ids))
        file_utils.create_directory(out_dir)
        touched = self._carry_previous_runs(out_dir, deleted_ids)
        spec_path = os.path.join(prev_root, parquet.BUCKET_SPEC_FILE)
        if file_utils.exists(spec_path):
            _link_or_copy(spec_path,
                          os.path.join(out_dir, parquet.BUCKET_SPEC_FILE))
        # Bucket-scoped invalidation channel: the commit names exactly
        # the buckets whose bytes changed vs the carried-from version;
        # everything else hard-linked byte-identically, so the segment
        # cache rekeys those warm entries instead of dropping them.
        prev_version = _version_of(prev_root)
        if prev_version is not None:
            self._touched_buckets = touched
            self._carried_from_version = prev_version

        if not appended:
            self.commit_data_version()
            self.stamp_stats()
            return  # metadata-only refresh (signature/file set catches up)
        cfg = self.index_config
        source_scan = self._source_scans()[-1]
        columns = cfg.indexed_columns + cfg.included_columns
        names = [source_scan.schema.field(c).name for c in columns]
        table = parquet.read_table(appended, columns=names)
        # One shared {file: id} map per action (memoized over the FULL
        # current listing) — the same map the log entry's FileInfos are
        # built from, so appended rows can never be written under an id
        # that disagrees with the logged metadata.
        lineage_ids = self.lineage_id_map(self.df)
        if lineage_ids is not None:
            from hyperspace_tpu.io.builder import append_lineage_column
            table = append_lineage_column(table, appended, lineage_ids)
        delta_version = os.path.basename(out_dir).split("=")[-1]
        written = write_bucketed_table(table, cfg.indexed_columns,
                                       self.num_buckets(), out_dir,
                                       file_suffix=f"delta{delta_version}")
        if prev_version is not None:
            for f in written:
                m = parquet.BUCKET_FILE_RE.search(os.path.basename(f))
                if m is not None:
                    touched.add(int(m.group(1)))
                else:
                    # Unparseable delta name: the bucket set is no
                    # longer provable — fall back to the full sweep.
                    self._touched_buckets = None
                    self._carried_from_version = None
                    break
        self.annotate_report(delta_files_written=len(written),
                             delta_rows=table.num_rows,
                             touched_buckets=sorted(touched))
        self.commit_data_version()
        self.stamp_stats()
