"""Incremental refresh — index only the appended source files.

The surveyed reference has full rebuild only (`RefreshAction`); incremental
refresh is its roadmap (`ROADMAP.md:66-75`) and this build's baseline
ladder requires it. Semantics:

- validate: state ACTIVE, and the stored source file set must be a SUBSET
  of the current listing (appends only; deletions/rewrites need a full
  refresh — surfaced in the error).
- op: the new `v__=N+1` dir hard-links every bucket file of the previous
  version (zero-copy on posix; falls back to copy), then the device build
  pipeline indexes ONLY the appended files, writing per-bucket delta runs
  with a `-delta` suffix into the same dir. Versions stay immutable +
  self-contained; readers handle multi-run buckets natively (the batched
  join sorts per-bucket ids, bucketed scans re-sort multi-run buckets).
- `OptimizeAction` merge-compacts the runs back to one file per bucket.
"""

from __future__ import annotations

import os
import shutil
from typing import List, Optional

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.actions.refresh import RefreshAction


def _link_or_copy(src: str, dst: str) -> None:
    from hyperspace_tpu.utils import file_utils, storage
    if storage.is_url(src) or storage.is_url(dst):
        file_utils.save_byte_array(dst, file_utils.load_byte_array(src))
        return
    try:
        os.link(src, dst)
    except OSError:
        shutil.copy2(src, dst)


class RefreshIncrementalAction(RefreshAction):
    """REFRESHING -> ACTIVE, writing only an appended-data delta."""

    def _source_scans(self):
        from hyperspace_tpu.plan.nodes import Scan
        return [leaf for leaf in self.df.plan.collect_leaves()
                if isinstance(leaf, Scan)]

    def appended_files(self) -> List[str]:
        """Current source listing (over ALL scan leaves — the build-time
        capture spans them too) minus the files captured at build time
        (shared derivation: `index/source_delta.py`)."""
        from hyperspace_tpu.index.source_delta import split_current
        current = [f for scan in self._source_scans() for f in scan.files()]
        appended, missing, _stored = split_current(self.previous_entry,
                                                   current)
        if missing:
            raise HyperspaceException(
                "Incremental refresh supports appended data only; "
                f"{len(missing)} indexed file(s) were deleted or rewritten "
                "— run a full refresh. Missing: "
                + ", ".join(sorted(missing)[:3]))
        return appended

    def validate(self) -> None:
        super().validate()
        self.appended_files()  # raises on deletions
        # A file rewritten in place keeps its path: verify the previously
        # indexed files are byte-identical by recomputing the signature over
        # exactly the stored file set.
        from hyperspace_tpu.index.signature import SignatureProviderFactory
        from hyperspace_tpu.index.source_delta import restricted_scan
        stored_sig = self.previous_entry.signature()
        restricted = restricted_scan(
            self.previous_entry, self._source_scans()[-1],
            self.previous_entry.source_file_list())
        provider = SignatureProviderFactory.create(stored_sig.provider)
        if provider.signature(restricted) != stored_sig.value:
            raise HyperspaceException(
                "Incremental refresh supports appended data only; previously "
                "indexed files were modified in place — run a full refresh.")

    def op(self) -> None:
        from hyperspace_tpu.engine.dataframe import DataFrame
        from hyperspace_tpu.io import parquet
        from hyperspace_tpu.io.builder import write_bucketed_batch
        from hyperspace_tpu.engine.executor import execute_plan
        from hyperspace_tpu.plan.nodes import Scan

        from hyperspace_tpu.utils import file_utils
        out_dir = self.index_data_path
        prev_root = self.previous_entry.content.root
        file_utils.create_directory(out_dir)
        # Carry the previous version's runs forward (zero-copy links).
        for _bucket, files in sorted(parquet.bucket_files(prev_root).items()):
            for f in files:
                _link_or_copy(f, os.path.join(out_dir, os.path.basename(f)))
        spec_path = os.path.join(prev_root, parquet.BUCKET_SPEC_FILE)
        if file_utils.exists(spec_path):
            _link_or_copy(spec_path,
                          os.path.join(out_dir, parquet.BUCKET_SPEC_FILE))

        appended = self.appended_files()
        if not appended:
            return  # metadata-only refresh (signature catches up)
        cfg = self.index_config
        source_scan = self._source_scans()[-1]
        delta_scan = Scan(source_scan.root_paths, source_scan.schema,
                          files=appended)
        columns = cfg.indexed_columns + cfg.included_columns
        batch = execute_plan(delta_scan, projection=columns)
        delta_version = os.path.basename(out_dir).split("=")[-1]
        write_bucketed_batch(batch, cfg.indexed_columns, self.num_buckets(),
                             out_dir, file_suffix=f"delta{delta_version}")
