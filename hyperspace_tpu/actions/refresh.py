"""Refresh action — full rebuild from the logged plan.

Parity: reference `actions/RefreshAction.scala:23-78`: deserializes the
logged plan back into a dataframe (the Scan re-enumerates source files, so
appended/changed data is picked up), reuses the stored IndexConfig,
REFRESHING -> ACTIVE, `op()` writes into the next `v__=N+1` version dir.
Requires current state ACTIVE.
"""

from __future__ import annotations

from typing import Optional

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.actions.create import CreateActionBase


class RefreshAction(CreateActionBase):
    transient_state = States.REFRESHING
    final_state = States.ACTIVE

    def __init__(self, log_manager: IndexLogManager,
                 data_manager: IndexDataManager, conf: HyperspaceConf):
        super().__init__(log_manager, data_manager, conf)
        self._previous: Optional[IndexLogEntry] = None
        self._entry: Optional[IndexLogEntry] = None
        self._df = None

    @property
    def previous_entry(self) -> IndexLogEntry:
        """Reference `RefreshAction.scala:36-40`."""
        if self._previous is None:
            entry = self.log_manager.get_log(self.base_id)
            if not isinstance(entry, IndexLogEntry):
                raise HyperspaceException("No index log entry to refresh.")
            self._previous = entry
        return self._previous

    @property
    def df(self):
        """Re-derive the dataframe from the logged plan (reference
        `RefreshAction.scala:44-50`); re-lists source files."""
        if self._df is None:
            from hyperspace_tpu.engine.dataframe import DataFrame
            self._df = DataFrame(self.previous_entry.plan())
        return self._df

    @property
    def index_config(self):
        """Reuse the stored config (reference `RefreshAction.scala:52-55`).
        The config TYPE follows the previous entry's kind — refreshing a
        DataSkippingIndex re-runs the sketch build through this same
        FSM action (per-file sketches make a full re-sketch cheap)."""
        prev = self.previous_entry
        from hyperspace_tpu.index.log_entry import DataSkippingIndex
        if isinstance(prev.derived_dataset, DataSkippingIndex):
            from hyperspace_tpu.index.index_config import (
                DataSkippingIndexConfig)
            dd = prev.derived_dataset
            return DataSkippingIndexConfig(prev.name, dd.skipped_columns,
                                           dd.sketch_types, dd.zorder_by)
        return IndexConfig(prev.name, prev.indexed_columns, prev.included_columns)

    def num_buckets(self) -> int:
        """Keep the bucket count the index was created with, so a refresh
        can't silently change the join-compatibility key."""
        return self.previous_entry.num_buckets

    def lineage_enabled(self) -> bool:
        """Lineage is a property of the index once set at creation: a full
        refresh preserves it regardless of the current conf (turning it ON
        via conf for a rebuilt index is allowed — a rebuild rewrites every
        row, so fresh ids are consistent)."""
        return self.previous_entry.has_lineage or super().lineage_enabled()

    def validate(self) -> None:
        """Reference `RefreshAction.scala:64-70`: state must be ACTIVE."""
        self._recover_stale_writer()
        if self.previous_entry.state != States.ACTIVE:
            raise HyperspaceException(
                f"Refresh is only supported in {States.ACTIVE} state; "
                f"current state is {self.previous_entry.state}.")

    def _is_skipping(self) -> bool:
        from hyperspace_tpu.index.index_config import DataSkippingIndexConfig
        return isinstance(self.index_config, DataSkippingIndexConfig)

    def log_entry(self) -> IndexLogEntry:
        if self._entry is None:
            if self._is_skipping():
                from hyperspace_tpu.actions.skipping import skipping_log_entry
                self._entry = skipping_log_entry(
                    self.df, self.index_config, self.index_data_path,
                    self._signature_provider())
            else:
                self._entry = self.get_index_log_entry(
                    self.df, self.index_config, self.index_data_path)
        return IndexLogEntry.from_dict(self._entry.to_dict())

    def op(self) -> None:
        """Reference `RefreshAction.scala:72-77` — rebuild into the next
        version dir; the old dir is retained for in-flight readers."""
        if self._is_skipping():
            from hyperspace_tpu.actions.skipping import (
                build_skipping_data, sweep_source_caches)
            detail = build_skipping_data(self.df, self.index_config,
                                         self.index_data_path, self.conf)
            self.annotate_report(**detail)
            self.commit_data_version()
            self.annotate_report(
                source_roots_swept=sweep_source_caches(self.df))
            self.stamp_stats()
            return
        self.write(self.df, self.index_config, self.index_data_path)
        self.commit_data_version()
        self.stamp_stats()
