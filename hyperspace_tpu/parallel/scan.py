"""Mesh-sharded predicate scan.

Reference rationale: `FilterIndexRule.scala:112-120` replaces the relation
with NO BucketSpec precisely so the engine parallelizes the scan freely —
the filter path's parallelism axis is rows, not buckets (SURVEY §2.12 row
4). Here rows are sharded over the mesh and the compiled predicate runs
SPMD: each chip evaluates the mask over its shard; only the compaction
gather crosses chips.
"""

from __future__ import annotations

import time
from typing import Dict

import numpy as np

from hyperspace_tpu import telemetry
from hyperspace_tpu.io.columnar import ColumnBatch, DeviceColumn
from hyperspace_tpu.parallel.mesh import shard_rows, total_shards


def shard_batch(batch: ColumnBatch, mesh):
    """Pad rows to a multiple of the mesh size and place every column
    row-sharded. Returns (sharded batch, row_valid mask) — padding rows are
    marked invalid and must be excluded by the caller.

    Host-resident columns pad in numpy and cross the link through the
    transfer engine (each device pulls only its slice of the sharded
    put; every column's put is issued before the first block); device
    columns only re-lay out."""
    import jax.numpy as jnp

    from hyperspace_tpu.io import transfer

    n = batch.num_rows
    n_shards = total_shards(mesh)
    padded = -(-n // n_shards) * n_shards
    pad = padded - n
    sharding = shard_rows(mesh)
    engine = transfer.get_engine()

    def place(arr, fill):
        if isinstance(arr, np.ndarray):
            if pad:
                arr = np.concatenate(
                    [arr, np.full((pad,) + arr.shape[1:], fill,
                                  arr.dtype)])
            return engine.put(arr, device=sharding)
        if pad:
            arr = jnp.concatenate(
                [arr, jnp.full((pad,) + arr.shape[1:], fill, arr.dtype)])
        return engine.put(arr, device=sharding)

    # The engine records each host column's link crossing; the span
    # keeps the placement visible as one mesh stage in traces.
    with telemetry.span("mesh:place", "mesh", rows=n, shards=n_shards):
        columns: Dict[str, DeviceColumn] = {}
        for name, col in batch.columns.items():
            columns[name] = DeviceColumn(
                data=place(col.data, 0),
                dtype=col.dtype,
                validity=(place(col.validity, False)
                          if col.validity is not None else None),
                dictionary=col.dictionary,
                dict_hashes=col.dict_hashes)
        row_valid = place(np.ones(n, dtype=bool), False)
    return ColumnBatch(batch.schema, columns), row_valid


def distributed_filter(batch: ColumnBatch, expression, mesh) -> ColumnBatch:
    """Filter `batch` on the mesh; result equals the single-chip
    `engine.compiler.apply_filter` bit for bit. The predicate (the FLOPs)
    runs shard-locally; the compaction gather is the only cross-chip step."""
    import jax.numpy as jnp

    from hyperspace_tpu.engine.compiler import compile_predicate

    n_shards = total_shards(mesh)
    reg = telemetry.get_registry()
    with telemetry.span("mesh:filter", "mesh", rows=batch.num_rows,
                        shards=n_shards):
        sharded, row_valid = shard_batch(batch, mesh)
        mask = compile_predicate(expression, sharded) & row_valid
        t0 = time.perf_counter()
        count = int(jnp.sum(mask))  # host sync — sizes the output
        sync_s = time.perf_counter() - t0
        reg.counter("mesh.filter.execs").inc()
        reg.counter("mesh.filter.sync_s").inc(sync_s)
        telemetry.add_seconds("mesh.sync_s", sync_s)
        telemetry.event("mesh", "filter", shards=n_shards,
                        rows=batch.num_rows, selected=count)
        (indices,) = jnp.nonzero(mask, size=count, fill_value=0)
        return sharded.take(indices)
