"""Device mesh helpers.

The reference delegates distribution to the Spark cluster (driver/executor
split, SURVEY §2.12); here the cluster is a `jax.sharding.Mesh` over TPU
chips — ICI within a slice, DCN across slices — and data movement is XLA
collectives, not a block-shuffle service.

Mesh shapes: single-slice deployments use a 1-axis `(shard,)` mesh.
Multi-host deployments use a 2-axis `(dcn, shard)` mesh — `shard` is the
INNER axis (devices within a slice, connected by ICI), `dcn` the outer
axis (one row per slice, connected by datacenter network). Collectives
issued over one named axis are confined to its device groups, so the
build's heavy within-slice re-bucket rides ICI and only the cross-slice
stage touches DCN (SURVEY §2.12: "DCN only across slices").

Bucket <-> shard ownership: flat shard `s` of an `n`-total-shard mesh owns
the CONTIGUOUS bucket range `[ceil(s*B/n), ceil((s+1)*B/n))` —
`bucket_owner(b) = b*n // B`; on a 2-axis mesh flat order is row-major
(dcn, shard), i.e. `s = d * n_ici + i`. The build (all_to_all routing),
the born-sharded parquet shard layout recorded in the index log entry,
the per-device segment-cache fills, and the SPMD co-sharded join all rely
on this ONE mapping (`bucket_ranges` / `bucket_owner` below), which is
also why equal bucket counts join with ZERO inter-chip traffic (the
ranker's preference, reference `index/rankers/JoinIndexRanker.scala:40-55`).
Contiguous ranges — rather than the former `b % n` stripes — are what let
a bucket-ordered on-disk layout slice straight into per-device shards: a
device's bucket range is one contiguous run of rows/files, so a born-
sharded read fills each device's HBM from its own files with no
interleaving gather.

This module is also THE layout-spec seam: every `NamedSharding` /
`PartitionSpec` / `shard_map` the package constructs comes from the
helpers here (`row_spec`, `shard_rows`, `replicated`, `device_of_shard`,
`compat_shard_map`), so layouts cannot drift between operators —
`scripts/check_metrics_coverage.py` bans raw construction elsewhere.
"""

from __future__ import annotations

import math
from typing import Optional

import hyperspace_tpu._jax_config  # noqa: F401

SHARD_AXIS = "shard"
DCN_AXIS = "dcn"


def compat_shard_map(body, mesh, in_specs, out_specs,
                     check_vma: bool = False):
    """`jax.shard_map` across jax versions: newer jax exports it
    top-level with `check_vma`; older jax ships
    `jax.experimental.shard_map` with the same semantics under
    `check_rep`. ONE shim here so every mesh kernel stays
    version-agnostic."""
    try:
        from jax import shard_map as sm
        return sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
        return sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)


def make_mesh(num_devices: Optional[int] = None,
              dcn_size: Optional[int] = None):
    """1-axis `(shard,)` mesh, or — with `dcn_size` > 1 — a 2-axis
    `(dcn, shard)` mesh of dcn_size slices."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if num_devices is not None:
        if len(devices) < num_devices:
            raise ValueError(
                f"Requested {num_devices} devices, have {len(devices)}.")
        devices = devices[:num_devices]
    from hyperspace_tpu import telemetry
    telemetry.get_registry().gauge("mesh.devices").set(len(devices))
    import numpy as np
    if dcn_size is not None and dcn_size > 1:
        if len(devices) % dcn_size != 0:
            raise ValueError(
                f"dcn size {dcn_size} must divide device count "
                f"{len(devices)}.")
        grid = np.array(devices).reshape(dcn_size, -1)
        return Mesh(grid, (DCN_AXIS, SHARD_AXIS))
    return Mesh(np.array(devices), (SHARD_AXIS,))


def row_axes(mesh):
    """The mesh axis names the ROW dimension shards over — every axis,
    outer (dcn) first, so flat shard order is row-major (dcn, shard)."""
    return tuple(mesh.axis_names)


def total_shards(mesh) -> int:
    return math.prod(mesh.shape.values())


def dcn_size(mesh) -> int:
    """Number of slices (1 on a flat single-axis mesh)."""
    return mesh.shape.get(DCN_AXIS, 1)


def ici_size(mesh) -> int:
    """Devices per slice (the inner ICI axis; the whole mesh when
    flat)."""
    return mesh.shape.get(SHARD_AXIS, total_shards(mesh))


def slice_of_shard(shard: int, n_ici: int) -> int:
    """Owning slice of flat shard `shard` under row-major (dcn, shard)
    flat order."""
    return shard // n_ici


def slice_submesh(mesh, idx: int):
    """Flat 1-axis submesh over slice `idx`'s devices — THE replica
    execution mesh: with replication on, a query routed to slice `idx`
    runs the whole born-sharded pipeline over this submesh exactly as a
    single-slice deployment would (`bucket_ranges(B, n_ici)` over the
    slice's devices), so replica execution is the degenerate flat case
    by construction. On a flat mesh only slice 0 exists and the mesh is
    returned as-is."""
    import numpy as np
    from jax.sharding import Mesh

    grid = np.asarray(mesh.devices)
    if grid.ndim == 1:
        if idx != 0:
            raise ValueError(f"flat mesh has one slice; asked for {idx}")
        return mesh
    if not 0 <= idx < grid.shape[0]:
        raise ValueError(
            f"slice {idx} out of range for a {grid.shape[0]}-slice mesh")
    return Mesh(grid[idx], (SHARD_AXIS,))


def mesh_device_tag(mesh) -> tuple:
    """Stable identity of the mesh's device set in flat shard order —
    the replica discriminator in per-device segment-cache keys: two
    slices of one topology hold the SAME bucket ranges on DIFFERENT
    devices, and their cached shards must never alias."""
    return tuple(int(getattr(d, "id", i))
                 for i, d in enumerate(mesh_device_list(mesh)))


def row_spec(mesh):
    """PartitionSpec splitting axis 0 across ALL mesh axes — THE row
    sharding used by every parallel operator (build/join/aggregate/scan)."""
    from jax.sharding import PartitionSpec
    return PartitionSpec(row_axes(mesh))


def shard_rows(mesh):
    """Sharding spec: rows (axis 0) split across ALL mesh devices."""
    from jax.sharding import NamedSharding
    return NamedSharding(mesh, row_spec(mesh))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())


# -- contiguous bucket-range ownership --------------------------------------
#
# THE bucket <-> shard map (module docstring). Every consumer — the build's
# all_to_all routing, the born-sharded parquet writer, the per-device
# segment-cache fills, and the SPMD join/aggregate — derives ownership from
# these two functions so the on-disk shard layout, the HBM residency, and
# the collective routing can never disagree.


def bucket_ranges(num_buckets: int, n_shards: int):
    """[(lo, hi)) bucket range per flat shard: shard s owns
    `[ceil(s*B/n), ceil((s+1)*B/n))` — contiguous, balanced to within one
    bucket, exact `B/n`-sized when `n_shards` divides `num_buckets`."""
    return [((s * num_buckets + n_shards - 1) // n_shards,
             ((s + 1) * num_buckets + n_shards - 1) // n_shards)
            for s in range(n_shards)]


def bucket_owner(bucket, num_buckets: int, n_shards: int):
    """Owning flat shard of `bucket` (scalar, numpy, or traced jax array)
    under the contiguous-range map — the exact inverse of
    `bucket_ranges`."""
    return bucket * n_shards // num_buckets


def slice_bucket_ranges(num_buckets: int, n_slices: int, n_ici: int):
    """[(lo, hi)) bucket range per SLICE of an (n_slices x n_ici)
    topology. The hierarchy nests exactly: because flat shard
    `s = d * n_ici + i` owns `[ceil(s*B/n), ...)` with
    `n = n_slices * n_ici`, slice d's union of its shards' ranges is
    `[ceil(d*B/n_slices), ceil((d+1)*B/n_slices))` — i.e. the slice-level
    map IS `bucket_ranges(B, n_slices)`, so a slice-granular record
    (layout v3, replica residency) and the flat shard map can never
    disagree."""
    del n_ici  # the identity above makes the inner size irrelevant
    return bucket_ranges(num_buckets, n_slices)


def shard_row_segments(lengths, n_shards: int):
    """Per-shard (row_start, row_end) into a bucket-ordered row space:
    shard s's rows are exactly its bucket range's rows — the property
    that makes a bucket-ordered table sliceable into per-device shards
    with no gather. `lengths` is the [num_buckets] per-bucket row-count
    vector."""
    import numpy as np
    lengths = np.asarray(lengths, dtype=np.int64)
    cum = np.concatenate([[0], np.cumsum(lengths)])
    return [(int(cum[lo]), int(cum[hi]))
            for lo, hi in bucket_ranges(len(lengths), n_shards)]


def mesh_device_list(mesh):
    """The mesh's devices in FLAT shard order (row-major over the axes) —
    the order `shard_rows` places shard s of a [S*C] row-sharded array on
    device s. Per-device segment-cache fills target these."""
    import numpy as np
    return list(np.asarray(mesh.devices).reshape(-1))


def device_of_shard(mesh, shard: int):
    """The device owning flat shard `shard` (per-device cache fills and
    born-sharded placements target it)."""
    return mesh_device_list(mesh)[shard]


def assemble_sharded_rows(mesh, per_device_arrays):
    """Build ONE globally row-sharded array from per-device single-shard
    arrays (equal first-dim length, array i resident on flat-shard device
    i) with ZERO data movement — the warm-path assembly of born-sharded
    reads: each device's segment-cache entry becomes its shard of the
    global array, and no byte crosses a link."""
    import jax
    total = sum(int(a.shape[0]) for a in per_device_arrays)
    shape = (total,) + tuple(per_device_arrays[0].shape[1:])
    return jax.make_array_from_single_device_arrays(
        shape, shard_rows(mesh), list(per_device_arrays))
