"""Device mesh helpers.

The reference delegates distribution to the Spark cluster (driver/executor
split, SURVEY §2.12); here the cluster is a `jax.sharding.Mesh` over TPU
chips — ICI within a slice, DCN across slices — and data movement is XLA
collectives, not a block-shuffle service.

Mesh shapes: single-slice deployments use a 1-axis `(shard,)` mesh.
Multi-host deployments use a 2-axis `(dcn, shard)` mesh — `shard` is the
INNER axis (devices within a slice, connected by ICI), `dcn` the outer
axis (one row per slice, connected by datacenter network). Collectives
issued over one named axis are confined to its device groups, so the
build's heavy within-slice re-bucket rides ICI and only the cross-slice
stage touches DCN (SURVEY §2.12: "DCN only across slices").

Bucket <-> shard ownership: flat shard `s` of an `n`-total-shard mesh owns
every bucket `b` with `b % n == s`; on a 2-axis mesh flat order is
row-major (dcn, shard), i.e. `s = d * n_ici + i`. Both the build
(all_to_all routing) and the co-sharded join rely on this one mapping,
which is also why equal bucket counts join with ZERO inter-chip traffic
(the ranker's preference, reference
`index/rankers/JoinIndexRanker.scala:40-55`).
"""

from __future__ import annotations

import math
from typing import Optional

import hyperspace_tpu._jax_config  # noqa: F401

SHARD_AXIS = "shard"
DCN_AXIS = "dcn"


def compat_shard_map(body, mesh, in_specs, out_specs,
                     check_vma: bool = False):
    """`jax.shard_map` across jax versions: newer jax exports it
    top-level with `check_vma`; older jax ships
    `jax.experimental.shard_map` with the same semantics under
    `check_rep`. ONE shim here so every mesh kernel stays
    version-agnostic."""
    try:
        from jax import shard_map as sm
        return sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
        return sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)


def make_mesh(num_devices: Optional[int] = None,
              dcn_size: Optional[int] = None):
    """1-axis `(shard,)` mesh, or — with `dcn_size` > 1 — a 2-axis
    `(dcn, shard)` mesh of dcn_size slices."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if num_devices is not None:
        if len(devices) < num_devices:
            raise ValueError(
                f"Requested {num_devices} devices, have {len(devices)}.")
        devices = devices[:num_devices]
    from hyperspace_tpu import telemetry
    telemetry.get_registry().gauge("mesh.devices").set(len(devices))
    import numpy as np
    if dcn_size is not None and dcn_size > 1:
        if len(devices) % dcn_size != 0:
            raise ValueError(
                f"dcn size {dcn_size} must divide device count "
                f"{len(devices)}.")
        grid = np.array(devices).reshape(dcn_size, -1)
        return Mesh(grid, (DCN_AXIS, SHARD_AXIS))
    return Mesh(np.array(devices), (SHARD_AXIS,))


def row_axes(mesh):
    """The mesh axis names the ROW dimension shards over — every axis,
    outer (dcn) first, so flat shard order is row-major (dcn, shard)."""
    return tuple(mesh.axis_names)


def total_shards(mesh) -> int:
    return math.prod(mesh.shape.values())


def dcn_size(mesh) -> int:
    """Number of slices (1 on a flat single-axis mesh)."""
    return mesh.shape.get(DCN_AXIS, 1)


def row_spec(mesh):
    """PartitionSpec splitting axis 0 across ALL mesh axes — THE row
    sharding used by every parallel operator (build/join/aggregate/scan)."""
    from jax.sharding import PartitionSpec
    return PartitionSpec(row_axes(mesh))


def shard_rows(mesh):
    """Sharding spec: rows (axis 0) split across ALL mesh devices."""
    from jax.sharding import NamedSharding
    return NamedSharding(mesh, row_spec(mesh))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())
