"""Device mesh helpers.

The reference delegates distribution to the Spark cluster (driver/executor
split, SURVEY §2.12); here the cluster is a `jax.sharding.Mesh` over TPU
chips — ICI within a slice, DCN across slices — and data movement is XLA
collectives, not a block-shuffle service.

Bucket <-> shard ownership: shard `s` of an `n`-shard mesh owns every bucket
`b` with `b % n == s`. Both the build (all_to_all routing) and the
co-sharded join rely on this one mapping, which is also why equal bucket
counts join with ZERO inter-chip traffic (the ranker's preference,
reference `index/rankers/JoinIndexRanker.scala:40-55`).
"""

from __future__ import annotations

from typing import Optional

import hyperspace_tpu._jax_config  # noqa: F401

SHARD_AXIS = "shard"


def make_mesh(num_devices: Optional[int] = None):
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if num_devices is not None:
        if len(devices) < num_devices:
            raise ValueError(
                f"Requested {num_devices} devices, have {len(devices)}.")
        devices = devices[:num_devices]
    import numpy as np
    return Mesh(np.array(devices), (SHARD_AXIS,))


def shard_rows(mesh):
    """Sharding spec: rows (axis 0) split across the mesh."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(SHARD_AXIS))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())
