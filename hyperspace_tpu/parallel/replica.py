"""Replica routing: slices as read replicas for concurrent throughput.

The PR-10/13 SPMD lane makes ONE query faster — the whole mesh executes
each query. A serving plane needs N queries AT ONCE: on a multi-slice
`(slice, device)` topology (`spark.hyperspace.distribution.slices` >= 2)
with replication enabled, each slice is a full READ REPLICA — its
devices hold the entire bucket-range map at slice-local granularity
(`bucket_ranges(B, n_ici)` over the slice's devices, the degenerate
flat case of `parallel/mesh.slice_bucket_ranges`'s nesting identity) —
and the query scheduler routes each admitted query's fills + execution
to the LEAST-LOADED replica (`QueryScheduler` calls `route()` per
collect; execution is pinned through `parallel/context.replica_scope`,
so every `distribution_mesh` consultation under the query sees that
slice's flat submesh).

Coherence is by construction, not by protocol: the per-device segment
cache keys residency by (index root, committed version, bucket range,
DEVICE TAG) — two slices fill independent entries for the same range,
both invalidated by the same index-FSM version hooks, so a refresher
never leaves one replica serving stale bytes (the cache sweeps by root,
device tags included).

Hot-vs-cold policy — which ranges are worth holding on >= 2 slices:
the router mines the flight ring's per-bucket access counts
incrementally (scans annotate `bucket_ids` when bucket pruning
narrowed the read; `FlightRecorder.snapshot(since_seq)`, the advisor
miner's cursor discipline). A bucket whose count reaches
`replication.hot.fraction` of the hottest bucket's count is HOT:
queries over hot (or unclassifiable) ranges fan to the least-loaded
replica — concurrent traffic naturally makes hot ranges resident on
every slice it lands on — while queries provably confined to COLD
buckets pin to their range's HOME slice (`bucket_owner` at slice
granularity), so rarely-read ranges are not duplicated across HBMs.

Telemetry: `serve.replica.<i>.routed` counters,
`serve.replica.<i>.admitted_bytes` gauges (scheduler-side), and
`serve.replica.cold_pinned` for home-slice pins.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from hyperspace_tpu import telemetry

# Re-mine the flight ring at most this often — routing is on the
# per-query hot path and the ring only changes as queries finish.
_MINE_INTERVAL_S = 1.0

# Halve every mined per-bucket count this often, dropping zeros:
# hotness then tracks RECENT traffic (a bucket hot last hour but idle
# now decays back to cold/unclassified) and the count map cannot grow
# without bound on a long-lived serving process. Halving preserves the
# ratios the hot-fraction bar compares.
_DECAY_INTERVAL_S = 60.0

# Hard backstop on the count map between decay sweeps: past this many
# (root, bucket) entries, the coldest half is dropped immediately.
_MAX_TRACKED_BUCKETS = 65536


class ReplicaRouter:
    """Process-wide replica router (one per process, `get_router()`).
    Holds the hot-bucket miner's cursor and the per-replica routed
    counts; the scheduler owns the byte-level load gauges."""

    def __init__(self):
        self._lock = threading.Lock()
        self._since_seq = 0
        self._counts: Dict[Tuple[str, int], int] = {}
        self._routed: Dict[int, int] = {}
        self._last_mine_t = 0.0
        self._last_decay_t = time.monotonic()

    # -- hot-bucket mining ------------------------------------------------

    def _mine_locked(self) -> None:
        now = time.monotonic()
        if now - self._last_mine_t < _MINE_INTERVAL_S:
            return
        self._last_mine_t = now
        if now - self._last_decay_t >= _DECAY_INTERVAL_S:
            self._last_decay_t = now
            self._counts = {k: c // 2 for k, c in self._counts.items()
                            if c // 2 > 0}
        recorder = telemetry.flight.get_recorder()
        fresh, self._since_seq = recorder.snapshot(self._since_seq)
        for metrics in fresh:
            for op in getattr(metrics, "operators", ()):
                if op.name != "Scan":
                    continue
                buckets = op.detail.get("bucket_ids")
                if not buckets:
                    continue
                root = (op.detail.get("roots") or [""])[0]
                for b in buckets:
                    key = (root, int(b))
                    self._counts[key] = self._counts.get(key, 0) + 1
        if len(self._counts) > _MAX_TRACKED_BUCKETS:
            keep = sorted(self._counts.items(), key=lambda kv: -kv[1])
            self._counts = dict(keep[:_MAX_TRACKED_BUCKETS // 2])

    def hot_buckets(self, root: str, hot_fraction: float) -> set:
        """Bucket ids of `root` at or above `hot_fraction` of the
        hottest bucket's access count (empty when nothing is mined yet
        — unclassified traffic fans freely)."""
        with self._lock:
            self._mine_locked()
            counts = {b: c for (r, b), c in self._counts.items()
                      if r == root}
        if not counts:
            return set()
        bar = max(counts.values()) * max(0.0, min(1.0, hot_fraction))
        return {b for b, c in counts.items() if c >= bar}

    # -- routing ----------------------------------------------------------

    def route(self, plan, conf, scheduler,
              buckets: Optional[dict] = None) -> Optional[int]:
        """Pick the replica slice for one query, or None when replica
        routing does not apply (flat mesh, replication off, too few
        slices). `buckets` overrides the plan-derived bucket hints:
        {root: (bucket_ids, num_buckets)} — the bench drives the
        hot/cold policy through it deterministically."""
        from hyperspace_tpu.parallel.context import topology

        if conf is not None and not conf.distribution_replication:
            return None
        topo = topology(conf)
        if topo is None:
            return None
        n_slices, _ici = topo
        min_slices = (conf.distribution_replication_min_slices
                      if conf is not None else 2)
        if n_slices < max(2, min_slices):
            return None
        if buckets is None:
            buckets = _plan_buckets(plan)
        choice = self._cold_pin(buckets, conf, n_slices)
        reg = telemetry.get_registry()
        if choice is None:
            choice = self._least_loaded(scheduler, n_slices)
        else:
            reg.counter("serve.replica.cold_pinned").inc()
        with self._lock:
            self._routed[choice] = self._routed.get(choice, 0) + 1
        reg.counter(f"serve.replica.{choice}.routed").inc()
        telemetry.event("serve", "replica_routed", replica=choice,
                        slices=n_slices)
        return choice

    def _cold_pin(self, buckets: Optional[dict], conf,
                  n_slices: int) -> Optional[int]:
        """Home slice when EVERY hinted bucket is provably cold (all
        hinted roots mined, no hot hit); None = fan to least-loaded."""
        if not buckets:
            return None
        from hyperspace_tpu.parallel.mesh import bucket_owner

        frac = (conf.distribution_replication_hot_fraction
                if conf is not None else 0.5)
        home = None
        for root, (ids, num_buckets) in buckets.items():
            if not ids:
                return None
            hot = self.hot_buckets(root, frac)
            if not hot or any(b in hot for b in ids):
                return None  # hot or unclassified: fan out
            # Slice ownership is a contiguous bucket range, so the min
            # and max hinted ids bound every hinted bucket's owner —
            # a single root whose buckets straddle a range boundary
            # must fan out too, not pin to the first bucket's slice.
            owner = int(bucket_owner(min(ids), num_buckets, n_slices))
            hi_owner = int(bucket_owner(max(ids), num_buckets, n_slices))
            if owner != hi_owner:
                return None  # spans home slices within one root: fan out
            if home is None:
                home = owner
            elif home != owner:
                return None  # spans home slices: fan out
        return home

    def _least_loaded(self, scheduler, n_slices: int) -> int:
        """Least-loaded replica by the scheduler's per-replica admitted
        bytes, per-replica in-flight count as the tiebreak, then the
        router's own routed counts (so an idle process still
        round-robins)."""
        admitted = getattr(scheduler, "replica_admitted_bytes",
                           lambda: {})()
        inflight = getattr(scheduler, "replica_inflight",
                           lambda: {})()
        with self._lock:
            routed = dict(self._routed)
        return min(range(n_slices),
                   key=lambda i: (admitted.get(i, 0),
                                  inflight.get(i, 0),
                                  routed.get(i, 0), i))

    def routed_counts(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._routed)

    def reset(self) -> None:
        with self._lock:
            self._since_seq = 0
            self._counts.clear()
            self._routed.clear()
            self._last_mine_t = 0.0
            self._last_decay_t = time.monotonic()


def _plan_buckets(plan) -> Optional[dict]:
    """{root: (bucket id set, num_buckets)} provable from the plan:
    Filter-over-bucketed-Scan shapes resolve through the SAME literal
    pruning the physical planner uses (`engine/physical._prune_buckets`
    — the build hash kernel, so hints can never disagree with the
    layout). None / missing entries = unclassifiable (fan out)."""
    try:
        from hyperspace_tpu.engine.physical import _prune_buckets
        from hyperspace_tpu.plan.nodes import Filter, Project, Scan
    except Exception:
        return None

    out: dict = {}

    def visit(node, condition=None):
        if isinstance(node, Filter):
            visit(node.child, node.condition)
            return
        if isinstance(node, Project):
            visit(node.child, condition)  # projection keeps the hint
            return
        if isinstance(node, Scan):
            spec = node.bucket_spec
            if spec is None or condition is None:
                return
            try:
                ids = _prune_buckets(condition, node)
            except Exception:
                ids = None
            if ids:
                root = node.root_paths[0] if node.root_paths else ""
                prev = out.get(root)
                merged = set(ids) | (prev[0] if prev else set())
                out[root] = (merged, spec.num_buckets)
            return
        for child in getattr(node, "children", ()):
            visit(child, None)

    try:
        visit(plan)
    except Exception:
        return None
    return out or None


_router: Optional[ReplicaRouter] = None
_router_lock = threading.Lock()


def get_router() -> ReplicaRouter:
    global _router
    if _router is None:
        with _router_lock:
            if _router is None:
                _router = ReplicaRouter()
    return _router


def reset_router() -> None:
    global _router
    _router = None
