"""Mesh-sharded index build: the TPU-native replacement for the build-time
shuffle.

Reference equivalent: `df.repartition(numBuckets, indexedCols)` — a Spark
block-shuffle exchange (`actions/CreateActionBase.scala:110-111`). Here the
exchange is ONE `lax.all_to_all` over the mesh's ICI links inside
`shard_map`, with MoE-style fixed per-peer capacity (XLA needs static
shapes; ragged routing is expressed as capacity + validity masks, and
overflow is detected exactly and retried with a larger capacity factor):

per shard (local rows [Ls]):
1. bucket id = murmur-mix(keys) % num_buckets       (32-bit lanes)
2. dest shard = bucket * n_shards // num_buckets    (contiguous-range map)
3. one local stable sort by dest groups rows per peer
4. rows scatter into a [n_shards, capacity] send buffer; overflow beyond
   capacity is counted (never silently dropped: the host retries)
5. lax.all_to_all swaps peer slabs across the mesh -> each shard holds
   exactly the rows of its buckets
6. one local stable sort by (bucket, keys) orders every bucket run

The host then writes each shard's buckets as bucketed parquet, identical
layout to the single-chip path.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Sequence, Tuple

import numpy as np

import hyperspace_tpu._jax_config  # noqa: F401
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.io.columnar import (ColumnBatch, batch_to_tree,
                                        tree_to_batch)
from hyperspace_tpu.ops import keys as keymod
from hyperspace_tpu.ops.build import _entry_sort_lanes, _tree_hash_lanes
from hyperspace_tpu.parallel.mesh import SHARD_AXIS


def _route_stage(tree, row_valid, bucket, dest, axis: str, n_peers: int,
                 capacity: int):
    """One routing exchange: sort local rows by `dest` peer, scatter into
    a [n_peers, capacity] send buffer, all_to_all over the named mesh
    `axis` (the collective is CONFINED to that axis's device groups).
    Returns (routed tree, routed valid, routed bucket, overflow count) —
    overflow rows are counted exactly, never silently dropped."""
    import jax
    import jax.numpy as jnp

    n_local = dest.shape[0]
    iota = jnp.arange(n_local, dtype=jnp.int32)
    dest_sorted, perm = jax.lax.sort([dest, iota], num_keys=1, is_stable=True)

    # Slot within the destination segment.
    seg_start = jnp.searchsorted(
        dest_sorted, jnp.arange(n_peers + 1, dtype=jnp.int32), side="left")
    offset = jnp.arange(n_local, dtype=jnp.int32) - jnp.take(
        seg_start, jnp.clip(dest_sorted, 0, n_peers))
    keep = (offset < capacity) & (dest_sorted < n_peers)
    overflow = jnp.sum((offset >= capacity) & (dest_sorted < n_peers))
    slot = jnp.where(keep, dest_sorted * capacity + offset,
                     n_peers * capacity)

    def route(arr):
        src = jnp.take(arr, perm, axis=0)
        buf_shape = (n_peers * capacity + 1,) + src.shape[1:]
        buf = jnp.zeros(buf_shape, dtype=src.dtype)
        buf = buf.at[slot].set(src, mode="drop")
        send = buf[:n_peers * capacity].reshape(
            (n_peers, capacity) + src.shape[1:])
        return jax.lax.all_to_all(send, axis, split_axis=0,
                                  concat_axis=0, tiled=False)

    routed = {}
    for name, entry in tree.items():
        out = dict(entry)
        out["data"] = route(entry["data"]).reshape(
            -1, *entry["data"].shape[1:])
        if "validity" in entry:
            out["validity"] = route(entry["validity"]).reshape(-1)
        routed[name] = out
    # Unwritten send slots keep their zero-init => validity defaults False,
    # so routing the raw validity/bucket arrays is sufficient (route()
    # applies the dest-sort permutation internally).
    recv_valid = route(row_valid).reshape(-1)
    recv_bucket = route(bucket).reshape(-1)
    return routed, recv_valid, recv_bucket, overflow


def _stage_capacity(local_rows: int, n_peers: int,
                    capacity_factor: float) -> int:
    return max(16, int(local_rows / n_peers * capacity_factor))


def _shard_step(tree, key_names: Tuple[str, ...], num_buckets: int,
                n_ici: int, n_dcn: int, capacity_factor: float):
    """The per-shard body (runs under shard_map; local shapes).

    1-axis mesh (n_dcn == 1): one all_to_all routes each row to its
    bucket's owner shard. 2-axis mesh: HIERARCHICAL routing — stage 1
    moves rows to the owner's ICI position within the source slice
    (all_to_all over the inner `shard` axis: rides ICI), stage 2 moves
    them to the owner's slice (all_to_all over the outer `dcn` axis);
    each stage changes exactly one mesh coordinate, so the flat owner
    `bucket % (n_dcn * n_ici) = d * n_ici + i` is reached in two
    axis-confined hops instead of one flat exchange."""
    import jax.numpy as jnp
    from hyperspace_tpu.ops.hash_partition import flat_hash32

    row_valid = tree["__valid__"]
    data_tree = {k: v for k, v in tree.items() if k != "__valid__"}
    lanes = []
    for name in key_names:
        lanes.extend(_tree_hash_lanes(tree[name]))
    h = flat_hash32(lanes)  # the one shared hash identity
    bucket = (h % jnp.uint32(num_buckets)).astype(jnp.int32)

    n_total = n_ici * n_dcn
    # Contiguous-range ownership (mesh.bucket_owner): shard s receives the
    # bucket range [ceil(s*B/n), ceil((s+1)*B/n)) — the same map the
    # born-sharded parquet writer and the per-device cache fills use. The
    # int64 intermediate keeps bucket * n_total exact for large bucket
    # counts before the narrowing divide.
    owner = ((bucket.astype(jnp.int64) * n_total)
             // num_buckets).astype(jnp.int32)
    overflow = jnp.zeros((), dtype=jnp.int32)

    # Stage 1 (ICI): to the owner's position within THIS slice.
    dest1 = jnp.where(row_valid, owner % n_ici, jnp.int32(n_ici))
    cap1 = _stage_capacity(dest1.shape[0], n_ici, capacity_factor)
    data_tree, row_valid, bucket, ov = _route_stage(
        data_tree, row_valid, bucket, dest1, SHARD_AXIS, n_ici, cap1)
    overflow = overflow + ov

    if n_dcn > 1:
        # Stage 2 (DCN): to the owner slice, ICI position already final.
        # Ownership re-derives from the ROUTED bucket ids (the data moved
        # in stage 1) through the same contiguous-range map.
        from hyperspace_tpu.parallel.mesh import DCN_AXIS
        owner2 = ((bucket.astype(jnp.int64) * n_total)
                  // num_buckets).astype(jnp.int32) // n_ici
        dest2 = jnp.where(row_valid, owner2, jnp.int32(n_dcn))
        cap2 = _stage_capacity(dest2.shape[0], n_dcn, capacity_factor)
        data_tree, row_valid, bucket, ov2 = _route_stage(
            data_tree, row_valid, bucket, dest2, DCN_AXIS, n_dcn, cap2)
        overflow = overflow + ov2

    recv_bucket = jnp.where(row_valid, bucket, num_buckets)

    # Local order: (bucket, keys); invalid rows (bucket=num_buckets) last.
    operands = [recv_bucket]
    for name in key_names:
        operands.extend(_entry_sort_lanes(data_tree[name]))
    m = recv_bucket.shape[0]
    iota2 = jnp.arange(m, dtype=jnp.int32)
    import jax
    results = jax.lax.sort([*operands, iota2], num_keys=len(operands),
                           is_stable=True)
    perm2 = results[-1]
    sorted_bucket = results[0]
    out_tree = {}
    for name, entry in data_tree.items():
        out = dict(entry)
        out["data"] = jnp.take(entry["data"], perm2, axis=0)
        if "validity" in entry:
            out["validity"] = jnp.take(entry["validity"], perm2, axis=0)
        out_tree[name] = out
    out_tree["__valid__"] = {"data": jnp.take(row_valid, perm2)}
    out_tree["__bucket__"] = {"data": sorted_bucket}
    out_tree["__overflow__"] = {"data": overflow.reshape(1)}
    return out_tree


def make_distributed_build_step(mesh, key_names: Tuple[str, ...],
                                num_buckets: int, capacity_factor: float):
    """Compile the full mesh-sharded build step (jit of shard_map). On a
    2-axis (dcn, shard) mesh the row axis shards over BOTH axes and the
    body runs the hierarchical two-stage exchange."""
    import jax

    from hyperspace_tpu.parallel.mesh import (compat_shard_map, dcn_size,
                                              row_spec)

    n_ici = mesh.shape[SHARD_AXIS]
    n_dcn = dcn_size(mesh)
    rows_spec = row_spec(mesh)

    def spec_like(tree):
        return jax.tree_util.tree_map(lambda _: rows_spec, tree)

    def step(tree):
        body = partial(_shard_step, key_names=key_names,
                       num_buckets=num_buckets, n_ici=n_ici, n_dcn=n_dcn,
                       capacity_factor=capacity_factor)
        sharded = compat_shard_map(body, mesh=mesh,
                                   in_specs=(spec_like(tree),),
                                   out_specs=rows_spec,
                                   check_vma=False)
        return sharded(tree)

    # A fresh jit per call means every dispatch traces; the compile
    # tracker makes that cost (and any future retrace storm here)
    # visible as compile.mesh.build_step.traces instead of silent wall.
    from hyperspace_tpu.telemetry import instrumented_jit
    return instrumented_jit("mesh.build_step", step)


def distributed_build(batch: ColumnBatch, key_columns: Sequence[str],
                      num_buckets: int, mesh,
                      capacity_factor: float = 2.0):
    """Run the mesh-sharded build. Returns (sorted ColumnBatch of valid rows
    in (shard, bucket, keys) order, per-bucket lengths np[num_buckets]).

    Hash tables / dictionaries are replicated; row data is sharded on entry
    (XLA moves the host arrays to the right chips). Exact overflow recovery:
    if any shard overflowed its per-peer capacity, retry with 2x capacity.
    """
    import time as _time

    import jax
    import jax.numpy as jnp

    from hyperspace_tpu import telemetry
    from hyperspace_tpu.parallel.mesh import shard_rows, total_shards

    n_shards = total_shards(mesh)
    key_names = tuple(batch.schema.field(c).name for c in key_columns)
    n = batch.num_rows
    local = -(-n // n_shards)  # ceil
    padded = local * n_shards

    tracer = telemetry.tracer()
    reg = telemetry.get_registry()
    span_ts = tracer.now_us() if tracer is not None else 0.0

    tree, aux = batch_to_tree(batch)
    # Host-resident sources build the padded tree in numpy and place
    # every leaf with the row sharding DIRECTLY (pipelined transfer
    # engine, all shards' puts issued before the first block) — each
    # device receives only its slice, instead of the whole table
    # round-tripping through the default device before the exchange.
    host_input = all(isinstance(entry["data"], np.ndarray)
                     for entry in tree.values())
    xp = np if host_input else jnp

    # Pad rows to a multiple of the shard count; padding rows are invalid.
    def pad(arr):
        pad_width = [(0, padded - n)] + [(0, 0)] * (arr.ndim - 1)
        return xp.pad(arr, pad_width)

    in_tree: Dict = {}
    for name, entry in tree.items():
        out = dict(entry)
        out["data"] = pad(entry["data"])
        if "validity" in entry:
            out["validity"] = pad(entry["validity"])
        # hash tables stay replicated: broadcast to per-shard copies
        if "hash_hi" in entry:
            out["hash_hi"] = xp.tile(entry["hash_hi"], (n_shards, 1)).reshape(
                n_shards * entry["hash_hi"].shape[0])
            out["hash_lo"] = xp.tile(entry["hash_lo"], (n_shards, 1)).reshape(
                n_shards * entry["hash_lo"].shape[0])
        in_tree[name] = out
    in_tree["__valid__"] = xp.concatenate(
        [xp.ones(n, dtype=bool), xp.zeros(padded - n, dtype=bool)])
    if host_input:
        from hyperspace_tpu.io import transfer

        engine = transfer.get_engine()
        sharding = shard_rows(mesh)
        in_tree = jax.tree_util.tree_map(
            lambda a: (engine.put(a, device=sharding)
                       if isinstance(a, np.ndarray) else a), in_tree)

    factor = capacity_factor
    while True:
        step = make_distributed_build_step(mesh, key_names, num_buckets,
                                           factor)
        t0 = _time.perf_counter()
        with telemetry.span("mesh:build:dispatch", "mesh",
                            shards=n_shards, rows=n):
            out = step(in_tree)
        reg.counter("mesh.build.dispatch_s").inc(
            _time.perf_counter() - t0)
        t0 = _time.perf_counter()
        overflow = int(jnp.sum(out["__overflow__"]["data"]))  # host sync
        sync_s = _time.perf_counter() - t0
        reg.counter("mesh.build.sync_s").inc(sync_s)
        telemetry.add_seconds("mesh.sync_s", sync_s)
        if overflow == 0:
            break
        reg.counter("mesh.build.overflow_retries").inc()
        factor *= 2  # exact recovery: nothing was lost, rerun wider

    result_tree = {}
    for name, entry in out.items():
        if name.startswith("__"):
            continue
        cleaned = dict(entry)
        if "hash_hi" in cleaned:
            # restore single replicated hash tables
            cleaned["hash_hi"] = tree[name]["hash_hi"]
            cleaned["hash_lo"] = tree[name]["hash_lo"]
        result_tree[name] = cleaned
    full = tree_to_batch(result_tree, batch.schema, aux)

    # Compact + globally order ON DEVICE: invalid rows carry bucket id
    # num_buckets, and every bucket lives on exactly one shard (the
    # contiguous-range map — shard s's buckets all precede shard s+1's),
    # so ONE stable argsort by bucket yields global (bucket, keys) order
    # with invalid rows at the tail — the per-shard key order within each
    # bucket is preserved, and under range ownership the sort is nearly
    # shard-local (rows only compact within their shard's run). The only
    # host traffic is the [num_buckets] length vector, which also sizes
    # the final slice.
    buckets_dev = out["__bucket__"]["data"]
    valid_dev = out["__valid__"]["data"]
    order = jnp.argsort(buckets_dev, stable=True)
    lengths = np.asarray(jax.ops.segment_sum(
        valid_dev.astype(jnp.int32), buckets_dev.astype(jnp.int32),
        num_segments=num_buckets + 1))[:num_buckets].astype(np.int64)
    total = int(lengths.sum())
    final = full.take(order[:total])
    # Per-device attribution: flat shard s owns the contiguous bucket
    # range (mesh.bucket_ranges), so the length vector yields each chip's
    # row load exactly — the histogram + device-track spans are where
    # multi-chip skew becomes visible.
    from hyperspace_tpu.parallel.mesh import bucket_ranges
    shard_rows = [int(lengths[lo:hi].sum())
                  for lo, hi in bucket_ranges(num_buckets, n_shards)]
    for rows in shard_rows:
        reg.histogram("mesh.build.shard_rows").observe(rows)
    reg.counter("mesh.build.execs").inc()
    telemetry.event("mesh", "build", shards=n_shards, rows=n,
                    buckets=num_buckets, shard_rows=shard_rows)
    if tracer is not None:
        tracer.device_spans("build", span_ts, shard_rows,
                            buckets=num_buckets)
    return final, lengths
